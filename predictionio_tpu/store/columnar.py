"""Columnar event batches — the host→device staging format.

The reference's training path scans HBase into Spark ``RDD[Event]`` partitions
(reference: data/.../storage/hbase/HBPEvents.scala via TableInputFormat).  A
TPU has no use for row-objects: the analogous structure here is a
struct-of-arrays block — integer-coded entity/event columns plus string
dictionaries — that can be staged to device HBM as dense ``int32`` arrays and
consumed by jitted programs without further host processing.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.events.event import Event
from predictionio_tpu.native import core as _ncore


class IdDict:
    """Bidirectional string↔dense-int dictionary (SURVEY.md §7 hard part (c)).

    Used to map external entity ids ("u123", item SKUs, event verbs) to dense
    int32 codes suitable for device-side gathers/segment ops.

    Lazily materializable: the native scan path builds instances straight
    from a utf-8 blob + int64 offsets (:meth:`from_blob`) or grows them by
    appending merged-dictionary export blobs — WITHOUT decoding.  A
    cross-shard merge that only re-codes integer columns never pays the
    per-string decode or the reverse-index dictcomp at all; the first
    accessor that needs Python strings (or the string→id index) pays it
    once.  ``from_state`` is lazy on the index side for the same reason:
    snapshot loads stop paying an eager dictcomp per dictionary.
    """

    __slots__ = ("_to_id", "_to_str", "_pending")

    def __init__(self, items: Optional[Sequence[str]] = None):
        self._to_id: Optional[Dict[str, int]] = {}
        self._to_str: List[str] = []
        self._pending: Optional[List[Tuple[bytes, np.ndarray]]] = None
        if items:
            for s in items:
                self.add(s)

    # -- lazy plumbing --------------------------------------------------------

    @classmethod
    def from_blob(cls, blob: bytes, offs: np.ndarray) -> "IdDict":
        """Dictionary over ``n`` utf-8 strings packed as ``blob`` +
        ``n+1`` offsets; nothing is decoded until an accessor needs it."""
        d = cls.__new__(cls)
        d._to_str = []
        d._to_id = None
        d._pending = [(blob, offs)] if len(offs) > 1 else None
        if d._pending is None:
            d._to_id = {}
        return d

    def _append_pending(self, blob: bytes, offs: np.ndarray) -> None:
        """Append already-deduplicated strings (codes continue from the
        current length) as an undecoded blob; the reverse index goes
        stale until the next materialization."""
        if len(offs) <= 1:
            return
        if self._pending is None:
            self._pending = []
        self._pending.append((blob, offs))
        self._to_id = None

    def _strings(self) -> List[str]:
        """The live ``_to_str`` list with any pending blobs decoded in."""
        if self._pending is not None:
            to_str = self._to_str
            for blob, offs in self._pending:
                text = blob.decode("utf-8", "surrogatepass")
                o = offs.tolist() if hasattr(offs, "tolist") else list(offs)
                if len(text) == len(blob):
                    # pure ASCII: byte offsets ARE char offsets — slice the
                    # single decoded str instead of per-piece decodes
                    to_str.extend(text[o[j]:o[j + 1]]
                                  for j in range(len(o) - 1))
                else:
                    to_str.extend(
                        blob[o[j]:o[j + 1]].decode("utf-8", "surrogatepass")
                        for j in range(len(o) - 1))
            self._pending = None
        return self._to_str

    def _index(self) -> Dict[str, int]:
        if self._to_id is None:
            self._to_id = {s: i for i, s in enumerate(self._strings())}
        return self._to_id

    # -- public API (unchanged semantics) ------------------------------------

    def add(self, s: str) -> int:
        to_id = self._to_id
        if to_id is None:
            to_id = self._index()
        i = to_id.get(s)
        if i is None:
            i = len(self._to_str)
            to_id[s] = i
            self._to_str.append(s)
        return i

    def id(self, s: str) -> Optional[int]:
        to_id = self._to_id
        if to_id is None:
            to_id = self._index()
        return to_id.get(s)

    def str(self, i: int) -> str:
        if self._pending is not None:
            self._strings()
        return self._to_str[i]

    def __len__(self) -> int:
        n = len(self._to_str)
        if self._pending is not None:
            for _blob, offs in self._pending:
                n += len(offs) - 1
        return n

    def __contains__(self, s: str) -> bool:
        to_id = self._to_id
        if to_id is None:
            to_id = self._index()
        return s in to_id

    def strings(self) -> List[str]:
        return list(self._strings())

    def clone(self) -> "IdDict":
        """O(n) C-level copy (dict/list copy constructors) — the
        copy-on-write step when a dictionary is shared with an emitted
        model: ~10× cheaper than re-adding every string through
        ``__init__`` at million-entry sizes.  Pending blobs are shared
        (immutable), not decoded."""
        out = IdDict.__new__(IdDict)
        out._to_id = dict(self._to_id) if self._to_id is not None else None
        out._to_str = list(self._to_str)
        out._pending = list(self._pending) if self._pending is not None else None
        return out

    def encode(self, values: Sequence[str]) -> np.ndarray:
        # hot loop: one list-comp over a local-aliased dict .get — hits
        # never touch a method frame, only misses pay the add() call
        get = self._index().get
        add = self.add
        codes = [c if (c := get(v)) is not None else add(v) for v in values]
        return np.fromiter(codes, dtype=np.int32, count=len(codes))

    def lookup_many(self, values: Sequence[str]) -> np.ndarray:
        """ids for known strings, -1 for unknown — one list-comp over a
        local-aliased ``.get`` + one fromiter, for bulk translation."""
        get = self._index().get
        return np.fromiter([get(v, -1) for v in values], dtype=np.int32,
                           count=len(values))

    def to_state(self) -> List[str]:
        return self._strings()

    @classmethod
    def from_state(cls, strings: Sequence[str]) -> "IdDict":
        d = cls.__new__(cls)
        d._to_str = list(strings)
        d._to_id = None
        d._pending = None
        return d

    # __slots__ + lazy state need an explicit pickle protocol: the state
    # is just the string list (always wrapped in a tuple — an empty list
    # would read as falsy and skip __setstate__)
    def __getstate__(self):
        return (list(self._strings()),)

    def __setstate__(self, state) -> None:
        self._to_str = list(state[0])
        self._to_id = None
        self._pending = None


def _export_dict_blob(d: IdDict) -> Tuple[bytes, np.ndarray]:
    """``(utf-8 blob, int64 offsets)`` for every string of ``d``.

    A blob-backed dictionary (native columnar read, never mutated)
    hands back its blob with zero work — the common case in a native
    cross-shard merge.  Otherwise encode once; for ASCII content the
    char lengths double as byte lengths."""
    if not d._to_str and d._pending is not None and len(d._pending) == 1:
        return d._pending[0]
    strs = d._strings()
    joined = "".join(strs)
    blob = joined.encode("utf-8", "surrogatepass")
    if len(blob) == len(joined):
        lens = [len(s) for s in strs]
    else:
        lens = [len(s.encode("utf-8", "surrogatepass")) for s in strs]
    offs = np.zeros(len(strs) + 1, np.int64)
    if strs:
        np.cumsum(lens, out=offs[1:])
    return blob, offs


class CSRLookup:
    """Row → sorted unique int values, stored as two flat arrays.

    Replaces per-row Python dicts of arrays in serialized models (e.g. a
    user's seen items): at 10⁷ rows a dict of ndarrays dominates the model
    blob and load time, while CSR is two contiguous arrays — O(1) pickle,
    O(nnz) memory, O(1) row slicing.
    """

    __slots__ = ("indptr", "values")

    def __init__(self, indptr: np.ndarray, values: np.ndarray):
        self.indptr = np.asarray(indptr, np.int64)
        self.values = np.asarray(values, np.int32)

    @classmethod
    def from_pairs(cls, rows: np.ndarray, values: np.ndarray, n_rows: int) -> "CSRLookup":
        rows = np.asarray(rows, np.int64)
        values = np.asarray(values, np.int64)
        if len(rows):
            n_vals = int(values.max()) + 1 if len(values) else 1
            # sort + neighbor-diff ≈ 1.6× np.unique (which sorts AND
            # re-derives uniques); measured 50 ms vs 79 ms at 4M pairs
            flat = np.sort(rows * n_vals + values)
            flat = flat[np.concatenate(([True], flat[1:] != flat[:-1]))]
            rows, values = flat // n_vals, flat % n_vals
        counts = np.bincount(rows, minlength=n_rows) if len(rows) else np.zeros(n_rows, np.int64)
        indptr = np.zeros(n_rows + 1, np.int64)
        np.cumsum(counts, out=indptr[1:])
        return cls(indptr, values.astype(np.int32))

    @classmethod
    def from_sorted_pairs(cls, rows: np.ndarray, values: np.ndarray,
                          n_rows: int) -> "CSRLookup":
        """``from_pairs`` for pairs that are ALREADY (row, value)-
        lexicographically sorted and deduplicated (e.g. the fold state's
        resident ``(user<<32|item)`` key sets) — skips the O(n log n)
        flat sort and is array-identical to ``from_pairs`` on such input
        (tested).  Caller contract, not checked: violating the sort or
        uniqueness silently builds a wrong lookup."""
        rows = np.asarray(rows, np.int64)
        counts = (np.bincount(rows, minlength=n_rows) if len(rows)
                  else np.zeros(n_rows, np.int64))
        indptr = np.zeros(n_rows + 1, np.int64)
        np.cumsum(counts, out=indptr[1:])
        return cls(indptr, np.asarray(values, np.int32))

    @classmethod
    def empty(cls, n_rows: int = 0) -> "CSRLookup":
        return cls(np.zeros(n_rows + 1, np.int64), np.empty(0, np.int32))

    def row(self, r: int) -> np.ndarray:
        if r < 0 or r >= len(self):
            return np.empty(0, np.int32)
        return self.values[self.indptr[r]:self.indptr[r + 1]]

    def __len__(self) -> int:
        return len(self.indptr) - 1

    @property
    def nnz(self) -> int:
        return len(self.values)

    def to_state(self) -> Dict[str, np.ndarray]:
        return {"indptr": self.indptr, "values": self.values}

    @classmethod
    def from_state(cls, state: Dict[str, np.ndarray]) -> "CSRLookup":
        return cls(state["indptr"], state["values"])


@dataclass
class PropColumn:
    """Sparse per-key property column (discovered schema; native scanner).

    Entry j belongs to batch row ``rows[j]``; ``kind[j]`` is 0 num, 1 bool,
    2 str, 3 str-list; numbers/bools live in ``num``, strings as
    dictionary codes in ``codes[str_offs[j]:str_offs[j+1]]``.
    """

    rows: np.ndarray      # int64 [n], ascending
    kind: np.ndarray      # int8 [n]
    num: np.ndarray       # f64 [n]
    str_offs: np.ndarray  # int64 [n+1]
    codes: np.ndarray     # int32 [total strings]
    dict: IdDict

    def __len__(self) -> int:
        return int(self.rows.shape[0])

    def value_at(self, j: int):
        k = int(self.kind[j])
        if k == 0:
            v = float(self.num[j])
            return int(v) if v.is_integer() else v
        if k == 1:
            return bool(self.num[j])
        if k == 4:
            return None
        s, e = int(self.str_offs[j]), int(self.str_offs[j + 1])
        strs = [self.dict.str(int(c)) for c in self.codes[s:e]]
        if k == 5:   # nested object kept as its raw JSON span
            import json as _json

            try:
                return _json.loads(strs[0]) if strs else None
            except ValueError:
                return None
        return strs if k == 3 else (strs[0] if strs else "")

    def remap_rows(self, new_row_of: np.ndarray) -> "PropColumn":
        """Column for a row-subset: ``new_row_of[old_row]`` is the new row
        index or -1 if dropped."""
        nr = new_row_of[self.rows]
        keep = nr >= 0
        if keep.all():
            return PropColumn(nr, self.kind, self.num, self.str_offs,
                              self.codes, self.dict)
        idx = np.flatnonzero(keep)
        lens = np.diff(self.str_offs)[idx]
        offs = np.zeros(len(idx) + 1, np.int64)
        np.cumsum(lens, out=offs[1:])
        total = int(offs[-1])
        if total == 0:
            codes = np.empty(0, np.int32)
        else:
            # vectorized ragged gather: source position = kept entry's start
            # + intra-entry offset (no per-entry Python loop)
            starts = self.str_offs[idx]
            gather = np.arange(total, dtype=np.int64) + np.repeat(
                starts - offs[:-1], lens)
            codes = self.codes[gather]
        return PropColumn(nr[keep], self.kind[keep], self.num[keep],
                          offs, codes.astype(np.int32), self.dict)


@dataclass
class EventBatch:
    """Struct-of-arrays block of events.

    Columns are parallel arrays of length N; string columns are dictionary
    encoded.  ``target_ids`` rows with no target are -1.  ``prop_columns``
    (native-scan path) holds the FULL property maps as sparse per-key
    columns; None means only the legacy ``ratings`` column is available.
    """

    event_codes: np.ndarray      # int32 [N] → event_dict
    entity_type_codes: np.ndarray  # int32 [N] → entity_type_dict
    entity_ids: np.ndarray       # int32 [N] → entity_dict
    target_ids: np.ndarray       # int32 [N] → target_dict (or -1)
    times_us: np.ndarray         # int64 [N] epoch microseconds
    ratings: np.ndarray          # float32 [N] numeric 'rating' property (NaN if absent)
    event_dict: IdDict
    entity_type_dict: IdDict
    entity_dict: IdDict
    target_dict: IdDict
    prop_columns: Optional[Dict[str, PropColumn]] = None

    def __len__(self) -> int:
        return int(self.event_codes.shape[0])

    @classmethod
    def from_events(
        cls,
        events: Sequence[Event],
        entity_dict: Optional[IdDict] = None,
        target_dict: Optional[IdDict] = None,
        event_dict: Optional[IdDict] = None,
    ) -> "EventBatch":
        n = len(events)
        event_dict = event_dict if event_dict is not None else IdDict()
        entity_type_dict = IdDict()
        entity_dict = entity_dict if entity_dict is not None else IdDict()
        target_dict = target_dict if target_dict is not None else IdDict()
        ev = np.empty(n, np.int32)
        et = np.empty(n, np.int32)
        ei = np.empty(n, np.int32)
        ti = np.full(n, -1, np.int32)
        ts = np.empty(n, np.int64)
        rt = np.full(n, np.nan, np.float32)
        for k, e in enumerate(events):
            ev[k] = event_dict.add(e.event)
            et[k] = entity_type_dict.add(e.entity_type)
            ei[k] = entity_dict.add(e.entity_id)
            if e.target_entity_id is not None:
                ti[k] = target_dict.add(e.target_entity_id)
            ts[k] = int(e.event_time.timestamp() * 1e6)
            r = e.properties.get("rating")
            if isinstance(r, (int, float)):
                rt[k] = float(r)
        return cls(ev, et, ei, ti, ts, rt, event_dict, entity_type_dict, entity_dict, target_dict)

    @classmethod
    def concat(cls, batches: Sequence["EventBatch"]) -> "EventBatch":
        """Concatenate batches, re-coding each batch's codes into shared dicts.

        Fast path: batches whose dictionaries ARE the same objects (the
        snapshot+tail scan stages the tail directly into the snapshot's
        dicts) need no re-coding at all — the merge is pure
        ``np.concatenate``, with no per-string Python rescan of the (large,
        already-shared) snapshot dictionaries.  Mixed inputs fall back to
        per-batch re-coding into fresh dicts, exactly as before.

        ``prop_columns`` merge when every batch carries them; a key whose
        string dictionaries are shared *objects* across batches (the
        snapshot+tail contract) merges code-for-code, and disagreeing
        dictionaries are RE-CODED into a merged one (the sharded store's
        cross-shard scans land here: each shard's snapshot owns its own
        dicts) — only a batch with no prop columns at all drops them.

        The mixed-dictionary path delegates to :class:`BatchMerger` —
        one k-way merge with preallocated output columns, each input
        column re-coded at most once regardless of batch count."""
        if len(batches) == 1:
            return batches[0]
        shared = all(
            b.event_dict is batches[0].event_dict
            and b.entity_type_dict is batches[0].entity_type_dict
            and b.entity_dict is batches[0].entity_dict
            and b.target_dict is batches[0].target_dict
            for b in batches[1:])
        if shared:
            b0 = batches[0]
            return cls(
                np.concatenate([b.event_codes for b in batches]),
                np.concatenate([b.entity_type_codes for b in batches]),
                np.concatenate([b.entity_ids for b in batches]),
                np.concatenate([b.target_ids for b in batches]),
                np.concatenate([b.times_us for b in batches]),
                np.concatenate([b.ratings for b in batches]),
                b0.event_dict, b0.entity_type_dict, b0.entity_dict,
                b0.target_dict,
                prop_columns=cls._concat_props(batches),
            )
        merger = BatchMerger()
        for b in batches:
            merger.add(b)
        merged, _ids = merger.finish()
        return merged

    @staticmethod
    def _concat_props(batches: Sequence["EventBatch"]
                      ) -> Optional[Dict[str, "PropColumn"]]:
        """Row-shifted merge of per-key property columns across batches.

        Requires every batch to carry prop_columns.  A key whose string
        dictionary is the same OBJECT across batches merges codes
        directly (the snapshot+tail shared-dict contract — zero-copy);
        disagreeing dictionaries (each shard's snapshot owns its own)
        are RE-CODED into a merged dictionary — one pass over each
        batch's dictionary strings plus one vectorized code gather, so
        cross-shard merged scans keep their property columns instead of
        dropping them (which used to force training onto the slow
        row-object path).  Returns None only when some batch carries no
        prop_columns at all."""
        if any(b.prop_columns is None for b in batches):
            return None
        offsets = np.cumsum([0] + [len(b) for b in batches])
        keys: List[str] = []
        for b in batches:
            for k in b.prop_columns:
                if k not in keys:
                    keys.append(k)
        out: Dict[str, PropColumn] = {}
        for key in keys:
            entries = [(offsets[i], b.prop_columns[key])
                       for i, b in enumerate(batches)
                       if key in b.prop_columns]
            d = entries[0][1].dict
            code_cols: List[np.ndarray] = []
            if any(c.dict is not d for _, c in entries[1:]):
                # disagreeing dictionaries: re-code into a merged dict
                d = IdDict(entries[0][1].dict.strings())
                for _, c in entries:
                    if c.dict.strings() == d.strings():
                        code_cols.append(np.asarray(c.codes, np.int32))
                        continue
                    n = len(c.dict)
                    code_map = (np.fromiter(
                        (d.add(s) for s in c.dict.strings()),
                        np.int32, count=n) if n else np.empty(0, np.int32))
                    code_cols.append(
                        code_map[np.asarray(c.codes, np.int64)]
                        if len(c.codes) else np.asarray(c.codes, np.int32))
            else:
                code_cols = [np.asarray(c.codes, np.int32)
                             for _, c in entries]
            rows = np.concatenate([c.rows + off for off, c in entries])
            kind = np.concatenate([c.kind for _, c in entries])
            num = np.concatenate([c.num for _, c in entries])
            code_base = np.cumsum(
                [0] + [len(c.codes) for _, c in entries])
            str_offs = np.concatenate(
                [np.asarray([0], np.int64)]
                + [c.str_offs[1:] + code_base[i]
                   for i, (_, c) in enumerate(entries)])
            codes = (np.concatenate(code_cols)
                     if code_base[-1] else np.empty(0, np.int32))
            out[key] = PropColumn(rows, kind, num, str_offs, codes, d)
        return out

    def subset(self, mask: np.ndarray) -> "EventBatch":
        """Row-filter by boolean mask; dictionaries are shared."""
        props = None
        if self.prop_columns is not None:
            new_row_of = np.full(len(self), -1, np.int64)
            new_row_of[mask] = np.arange(int(mask.sum()), dtype=np.int64)
            props = {k: c.remap_rows(new_row_of) for k, c in self.prop_columns.items()}
        return EventBatch(
            self.event_codes[mask], self.entity_type_codes[mask], self.entity_ids[mask],
            self.target_ids[mask], self.times_us[mask], self.ratings[mask],
            self.event_dict, self.entity_type_dict, self.entity_dict, self.target_dict,
            prop_columns=props,
        )

    def select_events(self, names: Sequence[str]) -> "EventBatch":
        """Filter to rows whose event verb is in ``names`` (dicts shared)."""
        codes = [self.event_dict.id(n) for n in names]
        codes = [c for c in codes if c is not None]
        mask = np.isin(self.event_codes, np.asarray(codes, np.int32))
        return self.subset(mask)


class EventIdColumn:
    """Per-row event ids as a flat byte blob + int64 offsets — the
    mmap-able companion of an :class:`EventBatch` (the batch itself has no
    id column; snapshots need one for tombstone deltas and integrity
    checks).  ``blob`` holds the ids back to back; row j is
    ``blob[offs[j]:offs[j+1]]``."""

    __slots__ = ("blob", "offs", "_bytes")

    def __init__(self, blob: np.ndarray, offs: np.ndarray):
        self.blob = np.asarray(blob, np.uint8)
        self.offs = np.asarray(offs, np.int64)
        self._bytes: Optional[bytes] = None

    @classmethod
    def from_ids(cls, ids: Sequence[str]) -> "EventIdColumn":
        encoded = [s.encode("utf-8", "surrogatepass") for s in ids]
        offs = np.zeros(len(encoded) + 1, np.int64)
        np.cumsum([len(b) for b in encoded], out=offs[1:])
        blob = np.frombuffer(b"".join(encoded), np.uint8).copy()
        return cls(blob, offs)

    def __len__(self) -> int:
        return len(self.offs) - 1

    def _materialize(self) -> bytes:
        if self._bytes is None:
            self._bytes = self.blob.tobytes()
        return self._bytes

    def tolist(self) -> List[str]:
        b = self._materialize()
        offs = self.offs
        return [b[offs[j]:offs[j + 1]].decode("utf-8", "surrogatepass")
                for j in range(len(self))]

    def index_of(self, event_id: str) -> int:
        """Row of ``event_id`` or -1 — a C-speed substring scan validated
        against the offset table (a raw hit inside a longer id is skipped)."""
        needle = event_id.encode("utf-8", "surrogatepass")
        if not needle:
            return -1
        blob = self._materialize()
        start = 0
        while True:
            p = blob.find(needle, start)
            if p < 0:
                return -1
            row = int(np.searchsorted(self.offs, p, side="left"))
            if (row < len(self) and self.offs[row] == p
                    and self.offs[row + 1] - p == len(needle)):
                return row
            start = p + 1

    @classmethod
    def concat(cls, columns: Sequence["EventIdColumn"]) -> "EventIdColumn":
        if len(columns) == 1:
            return columns[0]
        blob = np.concatenate([np.asarray(c.blob, np.uint8) for c in columns])
        offs = [np.asarray([0], np.int64)]
        base = 0
        for c in columns:
            offs.append(np.asarray(c.offs[1:], np.int64) + base)
            base += int(c.offs[-1])
        return cls(blob, np.concatenate(offs))

    def subset(self, mask: np.ndarray) -> "EventIdColumn":
        idx = np.flatnonzero(mask)
        lens = np.diff(self.offs)[idx]
        offs = np.zeros(len(idx) + 1, np.int64)
        np.cumsum(lens, out=offs[1:])
        total = int(offs[-1])
        if total == 0:
            return EventIdColumn(np.empty(0, np.uint8), offs)
        gather = np.arange(total, dtype=np.int64) + np.repeat(
            self.offs[idx] - offs[:-1], lens)
        return EventIdColumn(np.asarray(self.blob)[gather], offs)


class BatchMerger:
    """Incremental k-way merge of batch parts (+ optional id columns).

    Replaces pairwise ``EventBatch.concat([acc, part])`` accumulation —
    O(parts²) copying, with the accumulator's ever-growing dictionaries
    re-scanned at every step — with ONE k-way merge split into two
    phases:

    - :meth:`add` (phase A, called once per part IN PART ORDER) merges
      the part's string dictionaries into the target dictionaries and
      records the per-part code maps.  This is the Python-loop-bound
      work, and it runs per part as the part becomes available — the
      sharded store's parallel scan pipeline calls it for completed
      shards while later shards are still parsing.
    - :meth:`finish` (phase B) allocates every output column exactly
      once and gathers each part into its slice (``np.take(map, codes,
      out=slice)``) — no intermediate per-part copies, each column
      re-coded at most once.

    With ``base`` given, codes are assigned IN the base batch's
    dictionaries (mutating them in place, per-key property dictionaries
    included — the same contract as ``ColumnarBuilder(base=...)``), so
    the merged result concatenates with the base via the shared-dict
    fast path: the sharded store's delta staging depends on this to
    splice a cross-shard tail into a retained batch with zero
    re-coding of the retained part.

    Row order is the order of ``add`` calls — the cross-shard row-order
    contract (shard 0's rows, then shard 1's, ...) — and dictionary
    codes are assigned in first-appearance order across parts, exactly
    what sequential pairwise accumulation produced, so the merged batch
    is bit-exact vs the legacy path, codes included.
    """

    def __init__(self, base: Optional[EventBatch] = None):
        if base is not None:
            self.event_dict = base.event_dict
            self.entity_type_dict = base.entity_type_dict
            self.entity_dict = base.entity_dict
            self.target_dict = base.target_dict
            self._base_props = base.prop_columns or {}
        else:
            self.event_dict = IdDict()
            self.entity_type_dict = IdDict()
            self.entity_dict = IdDict()
            self.target_dict = IdDict()
            self._base_props = {}
        # per part: (batch, ids, ev_map, et_map, ei_map, ti_map);
        # a None map means the part already speaks the target dict
        self._parts: List[tuple] = []
        # key -> {"dict": target IdDict, "entries": [(row_off, col, map)]}
        self._props: Dict[str, dict] = {}
        self._props_ok = True
        self._ids_ok = True
        self._rows = 0
        # native dictionary-union handles (PIO_NATIVE): only for fresh
        # targets — seeding a handle from a large pre-populated base dict
        # would cost O(base) per tail merge, exactly what base= avoids
        self._native = base is None and _ncore.scan_enabled()
        self._handles: Dict[int, object] = {}
        self._handle_keep: List[IdDict] = []

    def _code_map(self, target: IdDict,
                  part_dict: IdDict) -> Optional[np.ndarray]:
        """Merge ``part_dict`` into ``target``; None = identity (the
        part's codes are already valid in the target).  The first part
        into an empty target bulk-installs its strings (a dictcomp, ~3×
        a per-string add loop) and needs no gather at all.

        Native path (PIO_NATIVE): the union runs in C with the GIL
        dropped, operating on utf-8 blobs; the target accumulates the
        new strings as UNDECODED pending blobs (in handle order == code
        order), so a merge whose consumer never reads the strings skips
        the decode entirely.  Code assignment order is identical to the
        Python path, and a mid-merge native failure falls back cleanly:
        materializing the pending blobs reconstructs exactly the state
        the Python path needs."""
        if part_dict is target:
            return None
        if self._native:
            try:
                return self._code_map_native(target, part_dict)
            except Exception:
                _ncore.note_fallback("error")
                self._native = False
        if not len(target):
            strings = part_dict.strings()
            target._to_str = strings
            target._to_id = {s: i for i, s in enumerate(strings)}
            target._pending = None
            return None
        n = len(part_dict)
        if not n:
            return np.empty(0, np.int32)
        # two C-level passes beat a per-string add loop on the miss-heavy
        # cross-shard case (disjoint entity vocabularies): filter misses,
        # bulk-install them, then map every string through one lookup
        strings = part_dict.strings()
        to_id = target._index()
        miss = [s for s in strings if s not in to_id]
        if miss:
            start = len(target._to_str)
            to_id.update(zip(miss, range(start, start + len(miss))))
            target._to_str.extend(miss)
        return np.fromiter(map(to_id.__getitem__, strings), np.int32,
                           count=n)

    def _code_map_native(self, target: IdDict,
                         part_dict: IdDict) -> Optional[np.ndarray]:
        h = self._handles.get(id(target))
        if h is None:
            h = _ncore.DictHandle()
            if len(target):      # defensive: fresh targets start empty
                blob, offs = _export_dict_blob(target)
                h.union(blob, offs)
            self._handles[id(target)] = h
            self._handle_keep.append(target)   # pin: id() stays unique
        was_empty = len(h) == 0
        blob, offs = _export_dict_blob(part_dict)
        cmap, n_new = h.union(blob, offs)
        if was_empty:
            # bulk-install: the part's codes are already the target's
            target._append_pending(blob, offs)
            return None
        if n_new:
            new_blob, new_offs = h.export(len(h) - n_new)
            target._append_pending(new_blob, new_offs)
        return cmap

    def add(self, batch: EventBatch,
            ids: Optional["EventIdColumn"] = None) -> None:
        """Phase A for one part: dictionary merge + code maps."""
        self._parts.append((
            batch, ids,
            self._code_map(self.event_dict, batch.event_dict),
            self._code_map(self.entity_type_dict, batch.entity_type_dict),
            self._code_map(self.entity_dict, batch.entity_dict),
            self._code_map(self.target_dict, batch.target_dict),
        ))
        if ids is None:
            self._ids_ok = False
        if batch.prop_columns is None:
            self._props_ok = False
        elif self._props_ok:
            for key, col in batch.prop_columns.items():
                st = self._props.get(key)
                if st is None:
                    base_col = self._base_props.get(key)
                    st = self._props[key] = {
                        "dict": (base_col.dict if base_col is not None
                                 else IdDict()),
                        "entries": [],
                    }
                st["entries"].append(
                    (self._rows, col, self._code_map(st["dict"], col.dict)))
        self._rows += len(batch)

    def _finish_props(self) -> Optional[Dict[str, PropColumn]]:
        if not self._props_ok:
            return None
        out: Dict[str, PropColumn] = {}
        for key, st in self._props.items():
            entries = st["entries"]
            n = sum(len(c) for _, c, _ in entries)
            total = sum(len(c.codes) for _, c, _ in entries)
            rows = np.empty(n, np.int64)
            kind = np.empty(n, np.int8)
            num = np.empty(n, np.float64)
            str_offs = np.empty(n + 1, np.int64)
            str_offs[0] = 0
            codes = np.empty(total, np.int32)
            ep = cp = 0
            native = _ncore.scan_enabled()
            for row_off, col, cmap in entries:
                m, k = len(col), len(col.codes)
                np.add(col.rows, row_off, out=rows[ep:ep + m])
                kind[ep:ep + m] = col.kind
                num[ep:ep + m] = col.num
                np.add(col.str_offs[1:], cp,
                       out=str_offs[ep + 1:ep + m + 1])
                if k:
                    if cmap is None:
                        codes[cp:cp + k] = col.codes
                    elif not (native and _ncore.take_i32(
                            cmap, col.codes, codes[cp:cp + k], False)):
                        np.take(cmap, np.asarray(col.codes),
                                out=codes[cp:cp + k])
                ep += m
                cp += k
            out[key] = PropColumn(rows, kind, num, str_offs, codes,
                                  st["dict"])
        return out

    def _finish_ids(self) -> Optional["EventIdColumn"]:
        if not self._ids_ok:
            return None
        total = sum(int(ids.offs[-1]) for _, ids, *_ in self._parts)
        blob = np.empty(total, np.uint8)
        offs = np.empty(self._rows + 1, np.int64)
        offs[0] = 0
        rp = bp = 0
        for _b, ids, *_ in self._parts:
            m, k = len(ids), int(ids.offs[-1])
            np.add(ids.offs[1:], bp, out=offs[rp + 1:rp + m + 1])
            blob[bp:bp + k] = ids.blob
            rp += m
            bp += k
        return EventIdColumn(blob, offs)

    def finish(self) -> Tuple[EventBatch, Optional["EventIdColumn"]]:
        """Phase B: preallocate + gather → (batch, ids-or-None)."""
        n = self._rows
        ev = np.empty(n, np.int32)
        et = np.empty(n, np.int32)
        ei = np.empty(n, np.int32)
        ti = np.empty(n, np.int32)
        ts = np.empty(n, np.int64)
        rt = np.empty(n, np.float32)
        at = 0
        native = _ncore.scan_enabled()
        if native:
            _ncore.note_call("scan")
        for b, _ids, ev_map, et_map, ei_map, ti_map in self._parts:
            m = len(b)
            if m:
                for out_col, codes, cmap in (
                    (ev, b.event_codes, ev_map),
                    (et, b.entity_type_codes, et_map),
                    (ei, b.entity_ids, ei_map),
                ):
                    if cmap is None:
                        out_col[at:at + m] = codes
                    elif not (native and _ncore.take_i32(
                            cmap, codes, out_col[at:at + m], False)):
                        np.take(cmap, np.asarray(codes),
                                out=out_col[at:at + m])
                sl = ti[at:at + m]
                if ti_map is None:
                    sl[:] = b.target_ids
                elif not (native and _ncore.take_i32(
                        ti_map, b.target_ids, sl, True)):
                    # -1 sentinel rides the gather: code -1 hits the
                    # appended last slot, which holds -1
                    ti_ext = np.append(ti_map, np.int32(-1))
                    np.take(ti_ext, np.asarray(b.target_ids), out=sl)
                ts[at:at + m] = b.times_us
                rt[at:at + m] = b.ratings
            at += m
        batch = EventBatch(
            ev, et, ei, ti, ts, rt,
            self.event_dict, self.entity_type_dict, self.entity_dict,
            self.target_dict, prop_columns=self._finish_props())
        return batch, self._finish_ids()


# -- persisted columnar container (snapshot files) ---------------------------
#
# Layout (all little-endian):
#   bytes 0..7    magic  b"PIOCOL01"
#   bytes 8..15   uint64 header length H
#   bytes 16..16+H JSON header (column dtypes/offsets, string dictionaries,
#                  per-key property columns, opaque meta)
#   data blobs, each 64-byte aligned, at header-recorded offsets
#
# Loads are np.memmap views into the file — no parse, no copy; the OS pages
# columns in at device-fill speed.  String dictionaries live in the JSON
# header (they must become Python strings anyway to rebuild IdDicts).

_COLUMNAR_MAGIC = b"PIOCOL01"
_ALIGN = 64


def _spec(arrays: List[np.ndarray], pos: int, arr: np.ndarray,
          dtype: str) -> Tuple[Dict, int]:
    arr = np.ascontiguousarray(arr)
    pos = (pos + _ALIGN - 1) // _ALIGN * _ALIGN
    arrays.append(arr)
    return {"dtype": dtype, "n": int(arr.shape[0]), "off": pos}, pos + arr.nbytes


def write_batch(path, batch: EventBatch,
                event_ids: Optional[EventIdColumn] = None,
                meta: Optional[Dict] = None) -> None:
    """Serialize ``batch`` (+ optional id column) into one columnar file.

    The write is flush+fsync'd but NOT atomic — callers own the tmp +
    rename two-phase (see storage.snapshot)."""
    arrays: List[np.ndarray] = []
    pos = 0
    cols = {}
    for name, arr, dt in (
        ("event_codes", batch.event_codes, "<i4"),
        ("entity_type_codes", batch.entity_type_codes, "<i4"),
        ("entity_ids", batch.entity_ids, "<i4"),
        ("target_ids", batch.target_ids, "<i4"),
        ("times_us", batch.times_us, "<i8"),
        ("ratings", batch.ratings, "<f4"),
    ):
        cols[name], pos = _spec(arrays, pos, np.asarray(arr).astype(dt), dt)
    ids_entry = None
    if event_ids is not None:
        blob_spec, pos = _spec(arrays, pos,
                               np.asarray(event_ids.blob, np.uint8), "|u1")
        offs_spec, pos = _spec(arrays, pos,
                               np.asarray(event_ids.offs).astype("<i8"), "<i8")
        ids_entry = {"blob": blob_spec, "offs": offs_spec}
    props_entry = []
    for key, col in (batch.prop_columns or {}).items():
        entry: Dict = {"dict": col.dict.to_state()}
        for name, arr, dt in (
            ("rows", col.rows, "<i8"), ("kind", col.kind, "|i1"),
            ("num", col.num, "<f8"), ("str_offs", col.str_offs, "<i8"),
            ("codes", col.codes, "<i4"),
        ):
            entry[name], pos = _spec(arrays, pos,
                                     np.asarray(arr).astype(dt), dt)
        props_entry.append([key, entry])
    header = {
        "rows": len(batch),
        "cols": cols,
        "ids": ids_entry,
        "dicts": {
            "event": batch.event_dict.to_state(),
            "entity_type": batch.entity_type_dict.to_state(),
            "entity": batch.entity_dict.to_state(),
            "target": batch.target_dict.to_state(),
        },
        "props": props_entry,
        "meta": meta or {},
    }
    import json as _json
    import os as _os

    hdr = _json.dumps(header, separators=(",", ":")).encode()
    data_base = 16 + len(hdr)
    with open(path, "wb") as f:
        f.write(_COLUMNAR_MAGIC)
        f.write(len(hdr).to_bytes(8, "little"))
        f.write(hdr)
        at = data_base
        for arr in arrays:
            # specs recorded offsets relative to the data region start;
            # pad from the current absolute position to the next one
            spec_off = (at - data_base + _ALIGN - 1) // _ALIGN * _ALIGN
            f.write(b"\0" * (data_base + spec_off - at))
            # contiguous arrays write straight from their buffer — no
            # tobytes() copy of the whole column
            f.write(arr.data if arr.flags.c_contiguous else arr.tobytes())
            at = data_base + spec_off + arr.nbytes
        f.flush()
        _os.fsync(f.fileno())


def read_batch(path, mmap: bool = True
               ) -> Tuple[EventBatch, Optional[EventIdColumn], Dict]:
    """Load a columnar file → (batch, ids-or-None, meta).

    ``mmap=True`` returns lazy views (GB/s cold loads); columns are
    read-only.  Raises ValueError on a torn/corrupt file — callers
    quarantine and rebuild."""
    import json as _json
    import mmap as _mmap

    # raw mmap + frombuffer instead of np.memmap: identical lazy views,
    # minus np.memmap's realpath() walk (≈1 ms of lstat calls per open —
    # material when a cross-shard scan opens one file per shard)
    with open(path, "rb") as _f:
        try:
            _raw = _mmap.mmap(_f.fileno(), 0, access=_mmap.ACCESS_READ)
        except ValueError as e:       # empty file — torn write
            raise ValueError(f"{path}: not a columnar snapshot: {e}") from None
    mm = np.frombuffer(_raw, dtype=np.uint8)
    if mm.shape[0] < 16 or bytes(mm[:8]) != _COLUMNAR_MAGIC:
        raise ValueError(f"{path}: not a columnar snapshot (bad magic)")
    hlen = int.from_bytes(bytes(mm[8:16]), "little")
    if 16 + hlen > mm.shape[0]:
        raise ValueError(f"{path}: truncated header")
    hdr_bytes = bytes(mm[16:16 + hlen])
    if _ncore.scan_enabled():
        # native header parse: the JSON decode (including every
        # dictionary string unescape) runs in C with the GIL dropped,
        # and the dictionaries come back as undecoded blobs — per-shard
        # reads in the scan fan-out overlap for real.  A declined header
        # (unknown extension / corrupt) falls through to json.loads,
        # which either handles it or raises the oracle's ValueError.
        nh = _ncore.ColumnarHeader.parse(hdr_bytes)
        if nh is not None:
            try:
                out = _read_batch_native(path, mm, nh, hdr_bytes, 16 + hlen,
                                         mmap)
                _ncore.note_call("scan")
                return out
            except ValueError:
                raise               # oracle-shape errors (truncation etc.)
            except Exception:
                _ncore.note_fallback("error")
        else:
            _ncore.note_fallback("unsupported")
    try:
        header = _json.loads(hdr_bytes)
    except (UnicodeDecodeError, _json.JSONDecodeError) as e:
        raise ValueError(f"{path}: corrupt header: {e}") from None
    data_base = 16 + hlen

    def view(spec) -> np.ndarray:
        dt = np.dtype(spec["dtype"])
        a, b = data_base + spec["off"], data_base + spec["off"] + spec["n"] * dt.itemsize
        if b > mm.shape[0]:
            raise ValueError(f"{path}: truncated column data")
        arr = mm[a:b].view(dt)
        return arr if mmap else np.array(arr)

    c = header["cols"]
    d = header["dicts"]
    props: Dict[str, PropColumn] = {}
    for key, entry in header.get("props", []):
        props[key] = PropColumn(
            rows=view(entry["rows"]), kind=view(entry["kind"]),
            num=view(entry["num"]), str_offs=view(entry["str_offs"]),
            codes=view(entry["codes"]),
            dict=IdDict.from_state(entry["dict"]))
    batch = EventBatch(
        event_codes=view(c["event_codes"]),
        entity_type_codes=view(c["entity_type_codes"]),
        entity_ids=view(c["entity_ids"]),
        target_ids=view(c["target_ids"]),
        times_us=view(c["times_us"]),
        ratings=view(c["ratings"]),
        event_dict=IdDict.from_state(d["event"]),
        entity_type_dict=IdDict.from_state(d["entity_type"]),
        entity_dict=IdDict.from_state(d["entity"]),
        target_dict=IdDict.from_state(d["target"]),
        prop_columns=props,
    )
    if len(batch) != header["rows"]:
        raise ValueError(f"{path}: row-count mismatch")
    ids = None
    if header.get("ids"):
        ids = EventIdColumn(view(header["ids"]["blob"]),
                            view(header["ids"]["offs"]))
        if len(ids) != len(batch):
            raise ValueError(f"{path}: id column length mismatch")
    return batch, ids, header.get("meta", {})


_NATIVE_COL_DTYPES = ("<i4", "<i4", "<i4", "<i4", "<i8", "<f4")
_NATIVE_PROP_DTYPES = ("<i8", "|i1", "<f8", "<i8", "<i4")


def _read_batch_native(path, mm: np.ndarray, nh, hdr_bytes: bytes,
                       data_base: int, want_mmap: bool):
    """The native twin of ``read_batch``'s body: specs/dicts/meta come
    from the C header parse (``nh``), columns are the same zero-copy
    ``frombuffer`` views, dictionaries stay undecoded blobs.  Raises the
    oracle's ValueErrors for truncated data / length mismatches."""
    import json as _json

    def view(spec, dtype) -> np.ndarray:
        dt = np.dtype(dtype)
        n, off = spec
        a, b = data_base + off, data_base + off + n * dt.itemsize
        if b > mm.shape[0]:
            raise ValueError(f"{path}: truncated column data")
        arr = mm[a:b].view(dt)
        return arr if want_mmap else np.array(arr)

    cols = [view(nh.spec(i), dt)
            for i, dt in enumerate(_NATIVE_COL_DTYPES)]
    props: Dict[str, PropColumn] = {}
    for i in range(nh.nprops):
        arrs = [view(nh.prop_spec(i, w), dt)
                for w, dt in enumerate(_NATIVE_PROP_DTYPES)]
        props[nh.prop_key(i)] = PropColumn(
            rows=arrs[0], kind=arrs[1], num=arrs[2], str_offs=arrs[3],
            codes=arrs[4], dict=IdDict.from_blob(*nh.prop_dict_blob(i)))
    batch = EventBatch(
        event_codes=cols[0], entity_type_codes=cols[1], entity_ids=cols[2],
        target_ids=cols[3], times_us=cols[4], ratings=cols[5],
        event_dict=IdDict.from_blob(*nh.dict_blob(0)),
        entity_type_dict=IdDict.from_blob(*nh.dict_blob(1)),
        entity_dict=IdDict.from_blob(*nh.dict_blob(2)),
        target_dict=IdDict.from_blob(*nh.dict_blob(3)),
        prop_columns=props,
    )
    if len(batch) != nh.rows:
        raise ValueError(f"{path}: row-count mismatch")
    ids = None
    blob_spec = nh.spec(6)
    if blob_spec is not None:
        ids = EventIdColumn(view(blob_spec, "|u1"), view(nh.spec(7), "<i8"))
        if len(ids) != len(batch):
            raise ValueError(f"{path}: id column length mismatch")
    span = nh.meta_span()
    meta = (_json.loads(hdr_bytes[span[0]:span[0] + span[1]])
            if span is not None else {})
    return batch, ids, meta


# -- generic named-array container (model-plane arenas) ----------------------
#
# Same container discipline as the snapshot files above (magic + JSON
# header + 64-aligned blobs, mmap loads), generalized to an arbitrary
# dict of n-D arrays: the shared-memory model plane persists each model
# generation through this so N prefork workers map ONE copy read-only.

_ARRAYS_MAGIC = b"PIOARR01"


def write_arrays(path, arrays: Dict[str, np.ndarray],
                 meta: Optional[Dict] = None) -> None:
    """Serialize named n-D arrays into one columnar container file.

    Flush+fsync'd but NOT atomic — callers own the tmp + rename
    two-phase (the model plane renames under its publish lock)."""
    import json as _json
    import os as _os

    entries: Dict[str, Dict] = {}
    blobs: List[np.ndarray] = []
    pos = 0
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        pos = (pos + _ALIGN - 1) // _ALIGN * _ALIGN
        entries[name] = {"dtype": arr.dtype.str, "shape": list(arr.shape),
                         "off": pos}
        blobs.append(arr)
        pos += arr.nbytes
    header = {"version": 1, "arrays": entries, "meta": meta or {}}
    hdr = _json.dumps(header, separators=(",", ":")).encode()
    data_base = 16 + len(hdr)
    with open(path, "wb") as f:
        f.write(_ARRAYS_MAGIC)
        f.write(len(hdr).to_bytes(8, "little"))
        f.write(hdr)
        at = data_base
        for arr in blobs:
            spec_off = (at - data_base + _ALIGN - 1) // _ALIGN * _ALIGN
            f.write(b"\0" * (data_base + spec_off - at))
            # no tobytes() copy: the model plane writes full keyframe
            # arenas through here — hundreds of MB at million-item
            # catalogs — and delta blobs at fold-tick rates
            f.write(arr.data if arr.flags.c_contiguous else arr.tobytes())
            at = data_base + spec_off + arr.nbytes
        f.flush()
        _os.fsync(f.fileno())


def read_arrays(path, mmap: bool = True
                ) -> Tuple[Dict[str, np.ndarray], Dict]:
    """Load a :func:`write_arrays` container → ``(arrays, meta)``.

    ``mmap=True`` returns READ-ONLY lazy views (``mmap`` +
    ``np.frombuffer``, so every process mapping the same file shares
    page cache — the model plane's N×→1× resident-bytes mechanism;
    ``arr.flags.writeable`` is False, so a worker cannot corrupt the
    shared mapping).  The views keep the mapping alive through their
    ``.base`` chain — the file truly unmaps only when the last array
    (i.e. the model generation holding them) is garbage collected.
    Raises ValueError on a torn/corrupt file — callers quarantine."""
    import json as _json
    import mmap as _mmap

    with open(path, "rb") as _f:
        try:
            _raw = _mmap.mmap(_f.fileno(), 0, access=_mmap.ACCESS_READ)
        except ValueError as e:       # empty file — torn write
            raise ValueError(f"{path}: not an array container: {e}") from None
    mm = np.frombuffer(_raw, dtype=np.uint8)
    if mm.shape[0] < 16 or bytes(mm[:8]) != _ARRAYS_MAGIC:
        raise ValueError(f"{path}: not an array container (bad magic)")
    hlen = int.from_bytes(bytes(mm[8:16]), "little")
    if 16 + hlen > mm.shape[0]:
        raise ValueError(f"{path}: truncated header")
    try:
        header = _json.loads(bytes(mm[16:16 + hlen]))
    except (UnicodeDecodeError, _json.JSONDecodeError) as e:
        raise ValueError(f"{path}: corrupt header: {e}") from None
    data_base = 16 + hlen
    out: Dict[str, np.ndarray] = {}
    for name, spec in header.get("arrays", {}).items():
        dt = np.dtype(spec["dtype"])
        shape = tuple(spec["shape"])
        n = int(np.prod(shape)) if shape else 1
        a = data_base + spec["off"]
        b = a + n * dt.itemsize
        if b > mm.shape[0]:
            raise ValueError(f"{path}: truncated array data ({name})")
        arr = mm[a:b].view(dt).reshape(shape)
        out[name] = arr if mmap else np.array(arr)
    return out, header.get("meta", {})


def fold_properties(batch: EventBatch, entity_type: Optional[str] = None):
    """Columnar $set/$unset/$delete folding over a native-scanned batch —
    the C++-path analogue of events.event.aggregate_properties (reference:
    LEventAggregator.aggregateProperties): events apply in (eventTime,
    row) order; $set merges keys, $unset removes named keys, $delete drops
    the snapshot.  Only the special-event rows are touched in Python; the
    scan/parse/encode of everything else stayed native."""
    from predictionio_tpu.events.event import (
        DELETE_EVENT, SET_EVENT, SPECIAL_EVENTS, UNSET_EVENT, PropertyMap,
    )

    if batch.prop_columns is None:
        raise ValueError("fold_properties requires a batch with prop_columns")
    special_codes = [batch.event_dict.id(n) for n in SPECIAL_EVENTS]
    special_codes = np.asarray(
        [c for c in special_codes if c is not None], np.int32)
    sel = np.isin(batch.event_codes, special_codes)
    if entity_type is not None:
        et = batch.entity_type_dict.id(entity_type)
        sel &= batch.entity_type_codes == (et if et is not None else -2)
    rows = np.flatnonzero(sel)
    if not len(rows):
        return {}
    order = np.lexsort((rows, batch.times_us[rows]))
    rows = rows[order]
    # per-selected-row property entries, gathered column-wise (col.rows is
    # ascending, so searchsorted finds each row's entry in O(log n))
    row_props: Dict[int, list] = {int(r): [] for r in rows}
    for key, col in batch.prop_columns.items():
        if len(col) == 0:   # key exists only on filtered-out rows
            continue
        pos = np.searchsorted(col.rows, rows)
        hit = (pos < len(col)) & (col.rows[np.minimum(pos, len(col) - 1)] == rows)
        for r, j in zip(rows[hit], pos[hit]):
            row_props[int(r)].append((key, col, int(j)))
    import datetime as _dt

    def ts(r):
        return _dt.datetime.fromtimestamp(
            batch.times_us[r] / 1e6, tz=_dt.timezone.utc)

    set_c = batch.event_dict.id(SET_EVENT)
    unset_c = batch.event_dict.id(UNSET_EVENT)
    del_c = batch.event_dict.id(DELETE_EVENT)
    snap: Dict[str, PropertyMap] = {}
    for r in rows:
        code = batch.event_codes[r]
        eid = batch.entity_dict.str(int(batch.entity_ids[r]))
        if code == del_c:
            snap.pop(eid, None)
            continue
        cur = snap.get(eid)
        when = ts(r)
        if code == set_c:
            if cur is None:
                cur = PropertyMap({}, first_updated=when, last_updated=when)
                snap[eid] = cur
            for key, col, j in row_props[int(r)]:
                cur[key] = col.value_at(j)
            cur.last_updated = max(cur.last_updated, when)
        elif code == unset_c:
            if cur is None:
                continue
            for key, _col, _j in row_props[int(r)]:
                cur.pop(key, None)
            cur.last_updated = max(cur.last_updated, when)
    return snap


def category_masks(item_categories, item_dict: "IdDict"):
    """(category IdDict, [C, n_items] bool matrix) from per-item category
    lists — the device-resident form of an engine's category business
    rules (items are columns so a query ORs a few mask ROWS on device)."""
    import numpy as _np

    names = sorted({c for cats in item_categories.values() for c in cats})
    cat_dict = IdDict(names)
    masks = _np.zeros((len(names), len(item_dict)), bool)
    for item, cats in item_categories.items():
        iid = item_dict.id(item)
        if iid is None:
            continue
        for c in cats:
            masks[cat_dict.id(c), iid] = True
    return cat_dict, masks
