from predictionio_tpu.store.columnar import EventBatch, IdDict  # noqa: F401
from predictionio_tpu.store.event_store import LEventStore, PEventStore  # noqa: F401
