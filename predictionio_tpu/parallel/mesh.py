"""Device-mesh management.

The reference's parallel substrate is Spark: RDD partitions + Netty shuffle +
Akka control (SURVEY.md §2 'Parallelism & comms').  The TPU-native substrate
is a `jax.sharding.Mesh` over the chip slice: GSPMD inserts XLA collectives
(all-reduce / all-gather / reduce-scatter / all-to-all) over ICI within a
slice and DCN across slices, driven purely by sharding annotations.

Axis convention used across the framework:
- ``dp``   — batch/data parallelism (events, users, queries)
- ``mp``   — model parallelism (item/feature dimension of factor matrices)

For classical-ML workloads (ALS, CCO, logreg) a 2-D ``(dp, mp)`` mesh covers
everything; templates reshape it as needed.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Iterable, Iterator, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape; resolved against available devices."""

    dp: int = -1  # -1 = fill with remaining devices
    mp: int = 1

    def resolve(self, n_devices: int) -> Tuple[int, int]:
        mp = self.mp if self.mp > 0 else 1
        if n_devices % mp != 0:
            raise ValueError(f"mp={mp} does not divide device count {n_devices}")
        dp = self.dp if self.dp > 0 else n_devices // mp
        if dp * mp != n_devices:
            raise ValueError(f"mesh {dp}x{mp} != {n_devices} devices")
        return dp, mp


def create_mesh(
    spec: Optional[MeshSpec] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_names: Tuple[str, str] = ("dp", "mp"),
) -> Mesh:
    """Build a 2-D mesh over the given (default: all) devices.

    On multi-host slices, `jax.devices()` already enumerates the global
    device set after `jax.distributed.initialize()`; mesh axes laid out so
    that `dp` is the outer (DCN-crossing) axis and `mp` stays within a host's
    ICI domain where possible — collectives on `mp` ride ICI.
    """
    devices = list(devices if devices is not None else jax.devices())
    dp, mp = (spec or MeshSpec()).resolve(len(devices))
    arr = np.asarray(devices).reshape(dp, mp)
    return Mesh(arr, axis_names)


def default_mesh() -> Mesh:
    """Process-wide default mesh: all devices on a (dp, mp=1) mesh, with the
    shape overridable via PIO_MESH (e.g. 'dp=4,mp=2')."""
    conf = os.environ.get("PIO_MESH", "")
    spec = MeshSpec()
    if conf:
        kv = dict(part.split("=") for part in conf.split(",") if "=" in part)
        spec = MeshSpec(dp=int(kv.get("dp", -1)), mp=int(kv.get("mp", 1)))
    return create_mesh(spec)


def host_staging_iterator(
    arrays: Iterable[np.ndarray],
    mesh: Mesh,
    axis: str = "dp",
) -> Iterator[jax.Array]:
    """Double-buffered host→device staging of row-sharded batches.

    Replaces the reference's HBase-scan→RDD ingest (HBPEvents via
    TableInputFormat): each numpy batch is placed row-sharded over ``axis``
    while the previous batch is being consumed, overlapping H2D DMA with
    compute (device dispatch is async in JAX).
    """
    from predictionio_tpu.parallel.sharding import shard_rows

    pending: Optional[jax.Array] = None
    for arr in arrays:
        from predictionio_tpu.parallel.sharding import stage_global

        staged = stage_global(arr, shard_rows(mesh, axis, arr.ndim))
        if pending is not None:
            yield pending
        pending = staged
    if pending is not None:
        yield pending


def pad_rows_for_mesh(n_rows: int, mesh: Mesh, axis: str = "dp", multiple: int = 8) -> int:
    """Rows padded so each shard is a multiple of `multiple` (MXU-friendly)."""
    shards = mesh.shape[axis]
    per = math.ceil(n_rows / shards)
    per = ((per + multiple - 1) // multiple) * multiple
    return per * shards
