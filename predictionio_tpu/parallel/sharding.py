"""Sharding helpers shared by templates and the workflow."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_rows(mesh: Mesh, axis: str = "dp", ndim: int = 2) -> NamedSharding:
    """Rows over `axis`, everything else replicated."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def pad_to_multiple(
    arr: np.ndarray, multiple: int, axis: int = 0, fill: Union[int, float] = 0
) -> np.ndarray:
    """Pad `axis` up to a multiple (static shapes keep XLA happy; pad rows are
    masked out downstream)."""
    n = arr.shape[axis]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return arr
    pad_width = [(0, 0)] * arr.ndim
    pad_width[axis] = (0, target - n)
    return np.pad(arr, pad_width, constant_values=fill)


def device_put_sharded_rows(
    arr: np.ndarray, mesh: Mesh, axis: str = "dp"
) -> jax.Array:
    """Pad rows to the dp extent and place row-sharded on the mesh
    (multi-process-safe via stage_global)."""
    dp = mesh.shape[axis]
    arr = pad_to_multiple(arr, dp, axis=0)
    return stage_global(np.asarray(arr), shard_rows(mesh, axis, arr.ndim))


def stage_global(arr: np.ndarray, sharding: NamedSharding) -> jax.Array:
    """Place a host array under ``sharding`` — including on meshes that SPAN
    PROCESSES, where plain ``jax.device_put`` fails on non-addressable
    devices.  Every process holds the full host array (the sharedfs event
    log is reachable from every host, so each re-derives the same layout)
    and ships only the shards its own devices own; the result is one global
    jax.Array usable by pjit/shard_map exactly like the single-process case.
    (Reference analogue: Spark broadcast + per-executor partition reads.)
    """
    if len(sharding.device_set) == len(sharding.addressable_devices):
        return jax.device_put(arr, sharding)
    idx_map = sharding.addressable_devices_indices_map(arr.shape)
    locals_ = [jax.device_put(arr[idx], d) for d, idx in idx_map.items()]
    return jax.make_array_from_single_device_arrays(arr.shape, sharding, locals_)
