from predictionio_tpu.parallel.distributed import (  # noqa: F401
    DistributedConfig,
    init_distributed,
    process_local_rows,
    shard_segments,
)
from predictionio_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    create_mesh,
    default_mesh,
    host_staging_iterator,
)
from predictionio_tpu.parallel.sharding import (  # noqa: F401
    named_sharding,
    pad_to_multiple,
    replicated,
    shard_rows,
)
