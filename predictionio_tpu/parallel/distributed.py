"""Multi-host runtime (reference: Spark cluster deployment — the driver/
executor topology configured via spark-submit/sparkConf; SURVEY.md §2
'Distributed comm backend' and §5).

The reference scales out by submitting to a Spark cluster: Netty shuffle +
Akka RPC between JVMs.  The TPU-native equivalent is much thinner — every
host runs the SAME program, `jax.distributed.initialize()` wires the hosts
into one runtime, and after that `jax.devices()` enumerates the global chip
set, so the mesh/GSPMD programs in this package run unchanged: collectives
ride ICI within a slice and DCN across slices, placed by XLA.

What this module adds on top of raw `jax.distributed`:

- env-driven initialization matching the pio-env.sh config convention
  (`PIO_COORDINATOR_ADDRESS`, `PIO_NUM_PROCESSES`, `PIO_PROCESS_ID`), with
  TPU-pod autodetection when unset (JAX reads the TPU metadata itself);
- host-sharded ingest: deterministic assignment of event-log segments to
  processes so each host scans only its share of the append-only log
  (replaces the reference's HBase-region → Spark-partition locality);
- `process_local_rows`: the row range of a globally dp-sharded array that
  this process must materialize (for `jax.make_array_from_single_device_arrays`
  -style per-host staging).
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import List, Optional, Sequence, Tuple, TypeVar

import jax

log = logging.getLogger("pio.distributed")

T = TypeVar("T")


@dataclasses.dataclass(frozen=True)
class DistributedConfig:
    """Multi-process topology, from env (conf/pio-env.sh convention)."""

    coordinator_address: Optional[str]  # host:port of process 0
    num_processes: int
    process_id: int

    @classmethod
    def from_env(cls) -> "DistributedConfig":
        return cls(
            coordinator_address=os.environ.get("PIO_COORDINATOR_ADDRESS") or None,
            num_processes=int(os.environ.get("PIO_NUM_PROCESSES", "1")),
            process_id=int(os.environ.get("PIO_PROCESS_ID", "0")),
        )

    @property
    def is_multi_process(self) -> bool:
        return self.num_processes > 1 or self.coordinator_address is not None


_initialized = False


def init_distributed(config: Optional[DistributedConfig] = None) -> DistributedConfig:
    """Idempotently initialize the multi-host JAX runtime.

    Single-process configs are a no-op, so every entry point (CLI train,
    servers, tests) can call this unconditionally.  On TPU pods where the
    env vars are unset, `jax.distributed.initialize()` autodetects the
    topology from the TPU metadata service; the explicit env path exists for
    CPU/GPU fleets and for pinning the coordinator in containerized deploys.
    """
    global _initialized
    config = config or DistributedConfig.from_env()
    if _initialized:
        return config
    if config.is_multi_process:
        jax.distributed.initialize(
            coordinator_address=config.coordinator_address,
            num_processes=config.num_processes,
            process_id=config.process_id,
        )
        log.info(
            "distributed runtime up: process %d/%d, %d global devices",
            config.process_id, config.num_processes, len(jax.devices()),
        )
        _initialized = True
    return config


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def shard_segments(segments: Sequence[T],
                   n_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> List[T]:
    """This process's share of the event-log segments, strided round-robin.

    Segments are immutable once rotated (storage/localfs.py), so a static
    assignment is safe; striding (rather than contiguous blocks) balances
    load when segment sizes trend over time — the same reason HBase scans in
    the reference spread regions over executors.
    """
    n = n_processes if n_processes is not None else process_count()
    i = process_id if process_id is not None else process_index()
    if not 0 <= i < n:
        raise ValueError(f"process_id {i} out of range for {n} processes")
    return list(segments[i::n])


def process_local_rows(n_rows: int, mesh) -> Tuple[int, int]:
    """[start, stop) of the dp-sharded global row space owned by this
    process's addressable devices — what host-side staging must load.

    Assumes the mesh's dp axis is the leading axis and rows divide evenly
    over it (use mesh.pad_rows_for_mesh first).
    """
    import numpy as np

    dp = mesh.shape["dp"]
    if n_rows % dp != 0:
        raise ValueError(f"{n_rows} rows do not divide over dp={dp}")
    rows_per_shard = n_rows // dp
    me = process_index()
    dp_positions = sorted({
        int(pos[0])
        for pos, dev in np.ndenumerate(mesh.devices)
        if dev.process_index == me
    })  # set: with mp > 1 each dp position appears once per mp column
    if not dp_positions:
        return (0, 0)
    if dp_positions != list(range(dp_positions[0], dp_positions[-1] + 1)):
        raise ValueError(
            f"this process's dp positions {dp_positions} are not contiguous; "
            "build the mesh with hosts laid out contiguously along dp "
            "(the default jax.devices() order) to use per-host row staging"
        )
    return (dp_positions[0] * rows_per_shard, (dp_positions[-1] + 1) * rows_per_shard)
