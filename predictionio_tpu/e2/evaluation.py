"""Cross-validation helpers (reference: e2/.../evaluation/CrossValidation —
splits an RDD into k folds of (training, testing))."""

from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple, TypeVar

import numpy as np

T = TypeVar("T")


def k_fold_split(
    data: Sequence[T], k: int, seed: int = 0
) -> Iterator[Tuple[List[T], List[T]]]:
    """Yield (training, testing) per fold; fold assignment is uniform random
    like the reference's `zipWithUniqueId % k`."""
    if k < 2:
        raise ValueError("k must be >= 2")
    rng = np.random.default_rng(seed)
    fold_of = rng.integers(0, k, size=len(data))
    for f in range(k):
        train = [d for d, g in zip(data, fold_of) if g != f]
        test = [d for d, g in zip(data, fold_of) if g == f]
        yield train, test
