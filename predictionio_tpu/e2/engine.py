"""Reusable engine helpers (reference: e2/src/main/scala/io/prediction/e2/engine/
— CategoricalNaiveBayes.scala, MarkovChain.scala, BinaryVectorizer.scala;
SURVEY.md §2 'e2 library').  The reference builds these on Spark RDDs; here
they are jitted segment/count ops.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class BinaryVectorizer:
    """Maps (field, value) pairs to a fixed-width binary feature vector
    (reference: BinaryVectorizer.fromPropertyAndAttributeNames)."""

    def __init__(self, index: Dict[Tuple[str, str], int]):
        self.index = dict(index)

    @classmethod
    def fit(cls, rows: Sequence[Dict[str, str]], fields: Sequence[str]) -> "BinaryVectorizer":
        index: Dict[Tuple[str, str], int] = {}
        for row in rows:
            for f in fields:
                if f in row:
                    key = (f, str(row[f]))
                    if key not in index:
                        index[key] = len(index)
        return cls(index)

    @property
    def width(self) -> int:
        return len(self.index)

    def transform(self, row: Dict[str, str]) -> np.ndarray:
        v = np.zeros(self.width, np.float32)
        for f, val in row.items():
            j = self.index.get((f, str(val)))
            if j is not None:
                v[j] = 1.0
        return v

    def transform_many(self, rows: Sequence[Dict[str, str]]) -> np.ndarray:
        return np.stack([self.transform(r) for r in rows]) if rows else np.zeros((0, self.width), np.float32)


@dataclasses.dataclass
class CategoricalNBModel:
    labels: List[str]
    prior: np.ndarray                     # [C] log prior
    log_likelihood: List[np.ndarray]      # per feature: [C, cardinality_f]
    feature_values: List[Dict[str, int]]  # per feature: value -> column


class CategoricalNaiveBayes:
    """Naive Bayes over categorical string features (reference:
    CategoricalNaiveBayes.train on LabeledPoints of string features)."""

    @staticmethod
    def train(
        points: Sequence[Tuple[str, Sequence[str]]], alpha: float = 1.0
    ) -> CategoricalNBModel:
        if not points:
            raise ValueError("no labeled points")
        n_features = len(points[0][1])
        labels: List[str] = []
        label_of: Dict[str, int] = {}
        feature_values: List[Dict[str, int]] = [dict() for _ in range(n_features)]
        for label, feats in points:
            if len(feats) != n_features:
                raise ValueError("inconsistent feature arity")
            if label not in label_of:
                label_of[label] = len(labels)
                labels.append(label)
            for f, v in enumerate(feats):
                fv = feature_values[f]
                if str(v) not in fv:
                    fv[str(v)] = len(fv)
        y = np.asarray([label_of[l] for l, _ in points], np.int32)
        C = len(labels)
        counts = np.bincount(y, minlength=C).astype(np.float32)
        prior = np.log(counts / counts.sum())
        log_likelihood = []
        for f in range(n_features):
            card = len(feature_values[f])
            x = np.asarray([feature_values[f][str(feats[f])] for _, feats in points], np.int32)
            tab = np.zeros((C, card), np.float32)
            np.add.at(tab, (y, x), 1.0)
            tab += alpha
            log_likelihood.append(np.log(tab / tab.sum(axis=1, keepdims=True)))
        return CategoricalNBModel(labels, prior, log_likelihood, feature_values)

    @staticmethod
    def log_score(
        model: CategoricalNBModel,
        features: Sequence[str],
        default_likelihood=lambda ll: -math.inf,
    ) -> Optional[np.ndarray]:
        """Per-class log score; unseen feature values use default_likelihood
        (reference: logScore with defaultLikelihood)."""
        score = model.prior.copy()
        for f, v in enumerate(features):
            col = model.feature_values[f].get(str(v))
            if col is None:
                score += np.asarray([default_likelihood(model.log_likelihood[f][c])
                                     for c in range(len(model.labels))])
            else:
                score += model.log_likelihood[f][:, col]
        return score

    @staticmethod
    def predict(model: CategoricalNBModel, features: Sequence[str]) -> str:
        scores = CategoricalNaiveBayes.log_score(
            model, features, default_likelihood=lambda ll: float(ll.min()) - 1.0
        )
        return model.labels[int(np.argmax(scores))]


class MarkovChain:
    """First-order Markov chain over state transitions (reference:
    MarkovChain.train on a transition-count matrix, keeping top-K next
    states per state)."""

    def __init__(self, transition_prob: np.ndarray, top_k_idx: np.ndarray, top_k_prob: np.ndarray):
        self.transition_prob = transition_prob
        self.top_k_idx = top_k_idx
        self.top_k_prob = top_k_prob

    @classmethod
    def train(cls, transitions: Sequence[Tuple[int, int]], n_states: int, top_k: int = 10) -> "MarkovChain":
        counts = np.zeros((n_states, n_states), np.float32)
        for a, b in transitions:
            counts[a, b] += 1.0
        row = counts.sum(axis=1, keepdims=True)
        prob = counts / np.maximum(row, 1.0)
        k = min(top_k, n_states)
        p, i = jax.lax.top_k(jnp.asarray(prob), k)
        return cls(prob, np.asarray(i), np.asarray(p))

    def next_states(self, state: int) -> List[Tuple[int, float]]:
        return [
            (int(j), float(p))
            for j, p in zip(self.top_k_idx[state], self.top_k_prob[state])
            if p > 0
        ]
