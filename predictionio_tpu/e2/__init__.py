from predictionio_tpu.e2.engine import (  # noqa: F401
    BinaryVectorizer,
    CategoricalNaiveBayes,
    MarkovChain,
)
from predictionio_tpu.e2.evaluation import k_fold_split  # noqa: F401
