"""Engine abstraction (reference: core/.../controller/Engine.scala).

An ``Engine`` wires one DataSource, one Preparator, a named set of
Algorithms, and one Serving class.  ``Engine.train`` chains
``read_training → prepare → algorithm.train`` per algorithm
(reference: Engine.train calling trainBase over algo list);
``Engine.eval`` runs the DASE chain over eval folds.

``EngineParams`` carries the per-component params (bound from engine.json);
``EngineFactory`` is the user entry point named in engine.json's
``engineFactory`` key.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Type

from predictionio_tpu.controller.params import EmptyParams, Params
from predictionio_tpu.core.base import (
    BaseAlgorithm,
    BaseDataSource,
    BaseEngine,
    BasePreparator,
    BaseServing,
    doer_name,
)

#: fleet default for serving micro-batch size; engines tighten it via a
#: ``serve_batch_max`` class attribute
DEFAULT_SERVE_BATCH = 64


@dataclasses.dataclass
class EngineParams:
    """Per-component parameter bundle (reference: EngineParams in Engine.scala)."""

    data_source_params: Params = dataclasses.field(default_factory=EmptyParams)
    preparator_params: Params = dataclasses.field(default_factory=EmptyParams)
    algorithm_params_list: List[Tuple[str, Params]] = dataclasses.field(default_factory=list)
    serving_params: Params = dataclasses.field(default_factory=EmptyParams)

    def to_json(self) -> Dict[str, Any]:
        def pj(p):  # Params object or a plain dict from engine.json binding
            return p.to_json() if hasattr(p, "to_json") else p

        return {
            "dataSourceParams": pj(self.data_source_params),
            "preparatorParams": pj(self.preparator_params),
            "algorithmParamsList": [
                {"name": name, "params": pj(p)} for name, p in self.algorithm_params_list
            ],
            "servingParams": pj(self.serving_params),
        }


class Engine(BaseEngine):
    """DASE engine (reference: Engine.scala).

    ``algorithm_classes`` maps algorithm names (referenced from engine.json's
    ``algorithms[].name``) to BaseAlgorithm subclasses.
    """

    def __init__(
        self,
        data_source_class: Type[BaseDataSource],
        preparator_class: Type[BasePreparator],
        algorithm_classes: Dict[str, Type[BaseAlgorithm]],
        serving_class: Type[BaseServing],
    ):
        self.data_source_class = data_source_class
        self.preparator_class = preparator_class
        self.algorithm_classes = dict(algorithm_classes)
        self.serving_class = serving_class

    # -- component instantiation --------------------------------------------

    def make_components(
        self, engine_params: EngineParams
    ) -> Tuple[BaseDataSource, BasePreparator, List[BaseAlgorithm], BaseServing]:
        data_source = self.data_source_class(engine_params.data_source_params)
        preparator = self.preparator_class(engine_params.preparator_params)
        algorithms: List[BaseAlgorithm] = []
        for name, params in engine_params.algorithm_params_list or self._default_algo_list():
            if name not in self.algorithm_classes:
                raise ValueError(
                    f"unknown algorithm {name!r}; engine defines {sorted(self.algorithm_classes)}"
                )
            algorithms.append(self.algorithm_classes[name](params))
        serving = self.serving_class(engine_params.serving_params)
        return data_source, preparator, algorithms, serving

    def _default_algo_list(self) -> List[Tuple[str, Params]]:
        return [
            (name, cls.params_class())
            for name, cls in list(self.algorithm_classes.items())[:1]
        ]

    # -- train ---------------------------------------------------------------

    def train(self, engine_params: EngineParams) -> List[Any]:
        """Run D→P→A over all algorithms; returns the list of trained models.

        Reference: Engine.train — readTraining, prepare, then trainBase per
        algorithm (order preserved; serving combines their predictions).
        """
        data_source, preparator, algorithms, _ = self.make_components(engine_params)
        td = data_source.read_training()
        pd = preparator.prepare(td)
        return [algo.train(pd) for algo in algorithms]

    # -- eval ----------------------------------------------------------------

    def eval(self, engine_params: EngineParams) -> List[Tuple[Any, List[Tuple[Any, Any, Any]]]]:
        """Run evaluation folds.

        Returns per-fold ``(eval_info, [(query, prediction, actual), ...])``
        matching the reference's ``Engine.eval`` RDD of (Q, P, A) triples.
        """
        data_source, preparator, algorithms, serving = self.make_components(engine_params)
        results = []
        for fold in data_source.read_eval():
            td, eval_info, qa_pairs = _unpack_fold(fold)
            pd = preparator.prepare(td)
            models = [algo.train(pd) for algo in algorithms]
            queries = [q for q, _ in qa_pairs]
            per_algo_preds = [
                algo.batch_predict(model, queries) for algo, model in zip(algorithms, models)
            ]
            qpa = []
            for i, (q, a) in enumerate(qa_pairs):
                preds = [per_algo_preds[j][i] for j in range(len(algorithms))]
                qpa.append((q, serving.serve(q, preds), a))
            results.append((eval_info, qpa))
        return results

    # -- serving -------------------------------------------------------------

    def predictor(
        self, engine_params: EngineParams, models: Sequence[Any]
    ) -> Callable[[Any], Any]:
        """Build the deploy-time query→prediction function.

        Reference: CreateServer's ServerActor closing over (engine, models);
        each query runs every algorithm's predict then serving.serve.
        """
        return self.serving_bundle(engine_params, models)[0]

    def _serving_components(self, engine_params: EngineParams,
                            models: Sequence[Any]):
        """Shared deploy prologue: build components, validate the model
        count, and pre-stage serving state to device (warm) so the first
        query never pays the host→device model transfer."""
        _, _, algorithms, serving = self.make_components(engine_params)
        if len(models) != len(algorithms):
            raise ValueError(
                f"{len(models)} model(s) for {len(algorithms)} algorithm(s)"
            )
        for algo, model in zip(algorithms, models):
            warm = getattr(algo, "warm", None)
            if warm is not None:
                warm(model)
        return algorithms, serving

    def batch_predictor(
        self, engine_params: EngineParams, models: Sequence[Any]
    ) -> Optional[Callable[[Sequence[Any]], List[Any]]]:
        """Build a queries→predictions function that scores a whole batch
        in ONE device program, or None when any algorithm lacks
        ``predict_batch``.

        The reference has no analogue (spray served queries one actor
        message at a time); on an accelerator one [B, …] dispatch
        amortizes the per-dispatch overhead — and, behind a tunneled
        device, the per-readback round trip — across the batch, which is
        what lets a single chip serve concurrent load (see
        create_server's micro-batching).  Serving still runs per query.

        Engages when every algorithm offers a serving-correct batch path:
        either an explicit ``serve_batch_predict`` (UR — its plain
        batch_predict is eval-only semantics) or ``serving_batchable``
        marking batch_predict itself as deploy-safe.
        """
        return self.serving_bundle(engine_params, models)[1]

    def serving_bundle(
        self, engine_params: EngineParams, models: Sequence[Any]
    ) -> Tuple[Callable[[Any], Any],
               Optional[Callable[[Sequence[Any]], List[Any]]]]:
        """(predict, predict_batch-or-None) built from ONE component
        construction + warm pass — deploy/hot-reload should call this
        rather than predictor()+batch_predictor(), which would build and
        warm everything twice."""
        algorithms, serving = self._serving_components(engine_params, models)

        def predict(query: Any) -> Any:
            preds = [algo.predict(model, query)
                     for algo, model in zip(algorithms, models)]
            return serving.serve(query, preds)

        def batch_fn(algo):
            fn = getattr(algo, "serve_batch_predict", None)
            if fn is not None:
                return fn
            if getattr(algo, "serving_batchable", False):
                return algo.batch_predict
            return None

        fns = [batch_fn(a) for a in algorithms]
        if any(f is None for f in fns):
            return predict, None

        max_batch = min(
            (getattr(a, "serve_batch_max", DEFAULT_SERVE_BATCH)
             for a in algorithms), default=DEFAULT_SERVE_BATCH)

        def _run_slice(queries: Sequence[Any]) -> List[Any]:
            per_algo = []
            for fn, algo, model in zip(fns, algorithms, models):
                col = fn(model, queries)
                if len(col) != len(queries):
                    raise RuntimeError(
                        f"{type(algo).__name__}'s serving batch path "
                        f"returned {len(col)} results for {len(queries)} "
                        "queries — it must be 1:1")
                per_algo.append(col)
            return [serving.serve(q, [col[i] for col in per_algo])
                    for i, q in enumerate(queries)]

        def predict_batch(queries: Sequence[Any]) -> List[Any]:
            # the cap is ENFORCED here, not just advised: any consumer
            # (micro-batcher or a direct batch_predictor() caller) stays
            # inside the per-slice memory bound engines declared (e.g.
            # UR's [B, I_p, K] scoring gather transient)
            out: List[Any] = []
            for s in range(0, len(queries), max_batch):
                out.extend(_run_slice(queries[s: s + max_batch]))
            return out

        predict_batch.max_batch = max_batch
        return predict, predict_batch

    # -- params binding (engine.json) ----------------------------------------

    def engine_params_from_variant(self, variant: Dict[str, Any]) -> EngineParams:
        """Bind an engine.json document to typed EngineParams.

        Reference: WorkflowUtils/JsonExtractor extracting dataSourceParams /
        preparatorParams / algorithms[] / servingParams blocks.
        """
        dsp = self.data_source_class.params_class.from_json(
            _params_block(variant.get("datasource"))
        )
        pp = self.preparator_class.params_class.from_json(
            _params_block(variant.get("preparator"))
        )
        algo_list: List[Tuple[str, Params]] = []
        for entry in variant.get("algorithms", []):
            name = entry.get("name")
            if name not in self.algorithm_classes:
                raise ValueError(
                    f"engine.json names unknown algorithm {name!r}; "
                    f"engine defines {sorted(self.algorithm_classes)}"
                )
            algo_list.append(
                (name, self.algorithm_classes[name].params_class.from_json(entry.get("params", {})))
            )
        sp = self.serving_class.params_class.from_json(_params_block(variant.get("serving")))
        return EngineParams(dsp, pp, algo_list, sp)


def _params_block(block: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    if block is None:
        return {}
    # engine.json wraps component params as {"params": {...}}; tolerate bare maps.
    if "params" in block and isinstance(block["params"], dict):
        return block["params"]
    return block


def _unpack_fold(fold: Any) -> Tuple[Any, Any, List[Tuple[Any, Any]]]:
    """Accept (td, qa_pairs) or (td, eval_info, qa_pairs) fold shapes."""
    if len(fold) == 2:
        td, qa = fold
        return td, None, list(qa)
    td, info, qa = fold
    return td, info, list(qa)


class EngineFactory:
    """User entry point named by engine.json's ``engineFactory``
    (reference: EngineFactory trait). Subclass and override ``apply``."""

    @classmethod
    def apply(cls) -> Engine:
        raise NotImplementedError

    @classmethod
    def engine_id(cls) -> str:
        return doer_name(cls)


def serialize_engine_params(engine_params: EngineParams) -> Dict[str, str]:
    """Stringify params for EngineInstance metadata records."""
    return {
        "data_source_params": json.dumps(engine_params.data_source_params.to_json()),
        "preparator_params": json.dumps(engine_params.preparator_params.to_json()),
        "algorithms_params": json.dumps(
            [{"name": n, "params": p.to_json()} for n, p in engine_params.algorithm_params_list]
        ),
        "serving_params": json.dumps(engine_params.serving_params.to_json()),
    }
