"""User-facing DASE component classes (reference: core/.../controller/).

These are the classes engine templates subclass.  The reference's
P/L/P2L split (PAlgorithm vs LAlgorithm vs P2LAlgorithm etc.) collapses to a
single variant under JAX — see predictionio_tpu/core/base.py for rationale.
Aliases ``PAlgorithm``/``LAlgorithm``/``P2LAlgorithm`` (and P/L data sources
and preparators) are provided for naming parity so reference templates map
1:1 onto this API.
"""

from __future__ import annotations

import pickle
from typing import Any, Optional, Sequence

from predictionio_tpu.core.base import (
    BaseAlgorithm,
    BaseDataSource,
    BasePreparator,
    BaseServing,
)


class DataSource(BaseDataSource):
    """Reads training (and optionally evaluation) data from the event store."""


class Preparator(BasePreparator):
    """Transforms TrainingData into the algorithm-ready PreparedData."""


class IdentityPreparator(Preparator):
    """Reference: IdentityPreparator / PIdentityPreparator."""

    def prepare(self, training_data):
        return training_data


class Algorithm(BaseAlgorithm):
    """train(prepared_data) -> model; predict(model, query) -> prediction."""


class Serving(BaseServing):
    """Combines/post-processes algorithm predictions for a query."""


class FirstServing(Serving):
    """Reference: FirstServing — returns the first algorithm's prediction."""

    def serve(self, query: Any, predictions: Sequence[Any]) -> Any:
        return predictions[0]


class AverageServing(Serving):
    """Reference: AverageServing — averages numeric predictions."""

    def serve(self, query: Any, predictions: Sequence[Any]) -> Any:
        return sum(predictions) / len(predictions)


# -- persistence ------------------------------------------------------------


class PersistentModel:
    """Models that manage their own persistence
    (reference: PersistentModel / PersistentModelLoader).

    Default implementation pickles the whole object; large array-valued
    models override save/load to use the orbax-backed model store
    (predictionio_tpu/workflow/persistence.py) instead.
    """

    def save(self) -> bytes:
        return pickle.dumps(self)

    @classmethod
    def load(cls, blob: bytes) -> "PersistentModel":
        obj = pickle.loads(blob)
        if not isinstance(obj, cls):
            raise TypeError(f"model blob holds {type(obj).__name__}, expected {cls.__name__}")
        return obj


# -- naming-parity aliases ---------------------------------------------------

PDataSource = DataSource
LDataSource = DataSource
PPreparator = Preparator
LPreparator = Preparator
PAlgorithm = Algorithm
LAlgorithm = Algorithm
P2LAlgorithm = Algorithm
LServing = Serving
