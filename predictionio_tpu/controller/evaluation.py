"""Evaluation API (reference: core/.../controller/{Evaluation,Metric,
MetricEvaluator}.scala + e2/.../evaluation/CrossValidation).

``Evaluation`` pairs an Engine with a Metric and candidate EngineParams;
``MetricEvaluator.evaluate`` scores every candidate over the engine's eval
folds and picks the best — the reference's hyperparameter-tuning loop
(`pio eval`).
"""

from __future__ import annotations

import abc
import dataclasses
import json
import math
import statistics
from typing import Any, Callable, Dict, Generic, List, Optional, Sequence, Tuple, TypeVar

from predictionio_tpu.controller.engine import Engine, EngineParams

Q = TypeVar("Q")
P = TypeVar("P")
A = TypeVar("A")


class Metric(abc.ABC, Generic[Q, P, A]):
    """Scores a set of (query, prediction, actual) triples.

    Reference: Metric.scala — ``calculate(sc, evalDataSet)``; subclasses
    AverageMetric / OptionAverageMetric / SumMetric / ZeroMetric map to
    overriding ``score_one`` or all of ``calculate``.
    """

    #: larger is better (reference: Metric's Ordering)
    higher_is_better: bool = True

    def header(self) -> str:
        return type(self).__name__

    def score_one(self, query: Q, prediction: P, actual: A) -> Optional[float]:
        raise NotImplementedError

    def calculate(self, eval_data: Sequence[Tuple[Any, Sequence[Tuple[Q, P, A]]]]) -> float:
        """Default: mean of per-triple scores over all folds, ignoring None
        (reference: OptionAverageMetric semantics)."""
        scores: List[float] = []
        for _info, qpa in eval_data:
            for q, p, a in qpa:
                s = self.score_one(q, p, a)
                if s is not None:
                    scores.append(float(s))
        if not scores:
            return -math.inf if self.higher_is_better else math.inf
        return statistics.fmean(scores)

    def compare(self, a: float, b: float) -> int:
        if a == b:
            return 0
        better = a > b if self.higher_is_better else a < b
        return 1 if better else -1


class AverageMetric(Metric[Q, P, A]):
    """score_one must return a float for every triple."""


class OptionAverageMetric(Metric[Q, P, A]):
    """score_one may return None to skip a triple."""


class SumMetric(Metric[Q, P, A]):
    def calculate(self, eval_data):
        total = 0.0
        for _info, qpa in eval_data:
            for q, p, a in qpa:
                s = self.score_one(q, p, a)
                if s is not None:
                    total += float(s)
        return total


class ZeroMetric(Metric[Q, P, A]):
    """Reference: ZeroMetric — always 0; used when only side metrics matter."""

    def calculate(self, eval_data):
        return 0.0


@dataclasses.dataclass
class MetricEvaluatorResult:
    best_score: float
    best_engine_params: EngineParams
    best_index: int
    metric_header: str
    other_metric_headers: List[str]
    engine_params_scores: List[Tuple[EngineParams, float, List[float]]]

    def to_json(self) -> Dict[str, Any]:
        return {
            "bestScore": self.best_score,
            "bestIndex": self.best_index,
            "bestEngineParams": self.engine_params_scores[self.best_index][0].to_json(),
            "metricHeader": self.metric_header,
            "otherMetricHeaders": self.other_metric_headers,
            "engineParamsScores": [
                {"engineParams": ep.to_json(), "score": s, "otherScores": o}
                for ep, s, o in self.engine_params_scores
            ],
        }


class MetricEvaluator:
    """Reference: MetricEvaluator.scala — evaluates each EngineParams candidate
    with the primary metric (+ optional side metrics), returns the best."""

    def __init__(self, metric: Metric, other_metrics: Sequence[Metric] = ()):
        self.metric = metric
        self.other_metrics = list(other_metrics)

    def evaluate(
        self,
        engine: Engine,
        engine_params_list: Sequence[EngineParams],
        eval_runner: Optional[Callable[[Engine, EngineParams], Any]] = None,
    ) -> MetricEvaluatorResult:
        if not engine_params_list:
            raise ValueError("engine_params_list must be non-empty")
        run = eval_runner or (lambda eng, ep: eng.eval(ep))
        scored: List[Tuple[EngineParams, float, List[float]]] = []
        for ep in engine_params_list:
            eval_data = run(engine, ep)
            score = self.metric.calculate(eval_data)
            others = [m.calculate(eval_data) for m in self.other_metrics]
            scored.append((ep, score, others))
        best_index = 0
        for i in range(1, len(scored)):
            if self.metric.compare(scored[i][1], scored[best_index][1]) > 0:
                best_index = i
        return MetricEvaluatorResult(
            best_score=scored[best_index][1],
            best_engine_params=scored[best_index][0],
            best_index=best_index,
            metric_header=self.metric.header(),
            other_metric_headers=[m.header() for m in self.other_metrics],
            engine_params_scores=scored,
        )


class Evaluation:
    """Binds an engine + metric + candidate params (reference: Evaluation.scala).

    Subclass and set ``engine``, ``metric`` (and optionally ``other_metrics``,
    ``engine_params_list``) as class attributes, or pass to __init__.
    """

    engine: Optional[Engine] = None
    metric: Optional[Metric] = None
    other_metrics: Sequence[Metric] = ()
    engine_params_list: Sequence[EngineParams] = ()

    def __init__(
        self,
        engine: Optional[Engine] = None,
        metric: Optional[Metric] = None,
        engine_params_list: Optional[Sequence[EngineParams]] = None,
        other_metrics: Optional[Sequence[Metric]] = None,
    ):
        if engine is not None:
            self.engine = engine
        if metric is not None:
            self.metric = metric
        if engine_params_list is not None:
            self.engine_params_list = engine_params_list
        if other_metrics is not None:
            self.other_metrics = other_metrics

    def run(self, eval_runner=None) -> MetricEvaluatorResult:
        if self.engine is None or self.metric is None:
            raise ValueError("Evaluation requires both an engine and a metric")
        evaluator = MetricEvaluator(self.metric, self.other_metrics)
        params = list(self.engine_params_list) or [EngineParams()]
        return evaluator.evaluate(self.engine, params, eval_runner)


class EngineParamsGenerator:
    """Supplies the candidate EngineParams for an Evaluation (reference:
    EngineParamsGenerator.scala, passed to `pio eval` alongside the
    Evaluation).  Subclass and set ``engine_params_list`` — usually via
    ``params_grid`` — or pass it to __init__."""

    engine_params_list: Sequence[EngineParams] = ()

    def __init__(self, engine_params_list: Optional[Sequence[EngineParams]] = None):
        if engine_params_list is not None:
            self.engine_params_list = engine_params_list


def params_grid(
    base: EngineParams,
    algorithm: str,
    grid: Dict[str, Sequence[Any]],
) -> List[EngineParams]:
    """Cartesian hyperparameter grid over one algorithm's params.

    The reference's engine-params-list workflows build candidate lists by
    hand (e.g. copying a baseParams and varying appId/rank per candidate);
    this is the generator for the common case: every combination of
    ``grid`` values overlaid on ``algorithm``'s params in ``base``.

        params_grid(ep, "als", {"rank": [8, 16], "reg": [0.01, 0.1]})
        → 4 EngineParams candidates
    """
    import itertools

    if not grid:
        return [base]
    names = [n for n, _ in base.algorithm_params_list]
    if algorithm not in names:
        raise ValueError(f"algorithm {algorithm!r} not in {names}")
    keys = list(grid)
    out: List[EngineParams] = []
    for combo in itertools.product(*(grid[k] for k in keys)):
        override = dict(zip(keys, combo))
        apl = []
        for name, p in base.algorithm_params_list:
            if name == algorithm:
                if dataclasses.is_dataclass(p):
                    p = dataclasses.replace(p, **override)
                elif isinstance(p, dict):
                    p = {**p, **override}
                else:
                    raise TypeError(
                        f"cannot overlay grid on params of type {type(p).__name__}")
            apl.append((name, p))
        out.append(dataclasses.replace(base, algorithm_params_list=apl))
    return out
