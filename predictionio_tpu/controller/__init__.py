from predictionio_tpu.controller.dase import (  # noqa: F401
    Algorithm,
    AverageServing,
    DataSource,
    FirstServing,
    IdentityPreparator,
    LAlgorithm,
    LDataSource,
    LPreparator,
    LServing,
    P2LAlgorithm,
    PAlgorithm,
    PDataSource,
    PersistentModel,
    PPreparator,
    Preparator,
    Serving,
)
from predictionio_tpu.controller.engine import (  # noqa: F401
    Engine,
    EngineFactory,
    EngineParams,
)
from predictionio_tpu.controller.evaluation import (  # noqa: F401
    AverageMetric,
    Evaluation,
    Metric,
    MetricEvaluator,
    MetricEvaluatorResult,
    OptionAverageMetric,
    SumMetric,
    ZeroMetric,
)
from predictionio_tpu.controller.params import EmptyParams, Params  # noqa: F401
