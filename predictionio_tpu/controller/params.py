"""Engine-parameter binding.

Reference: the ``Params`` marker trait (core/.../controller/Params.scala) plus
``JsonExtractor`` (core/.../workflow/JsonExtractor.scala), which binds the
``engine.json`` params blocks to Scala case classes.  Here ``Params`` is a
dataclass base with ``from_json``/``to_json`` doing the same field-checked
binding (unknown keys rejected, missing non-default keys rejected — matching
the reference's strict extraction mode).
"""

from __future__ import annotations

import dataclasses
import json
import typing
from typing import Any, Dict, Mapping, Type, TypeVar

T = TypeVar("T", bound="Params")


@dataclasses.dataclass
class Params:
    """Base class for all DASE component parameter sets."""

    @classmethod
    def from_json(cls: Type[T], data: Any) -> T:
        if data is None:
            data = {}
        if isinstance(data, str):
            data = json.loads(data) if data.strip() else {}
        if not isinstance(data, Mapping):
            raise TypeError(f"{cls.__name__} params must be a JSON object, got {type(data).__name__}")
        fields = {f.name: f for f in dataclasses.fields(cls)}
        # The reference's engine.json uses camelCase keys ("appName",
        # "numIterations", "lambda"); accept both spellings.
        data = {_match_key(k, fields, cls.__name__): v for k, v in data.items()}
        unknown = set(data) - set(fields)
        if unknown:
            raise ValueError(f"{cls.__name__}: unknown parameter(s) {sorted(unknown)}")
        kwargs: Dict[str, Any] = {}
        for name, f in fields.items():
            if name in data:
                kwargs[name] = _coerce(data[name], f.type, f"{cls.__name__}.{name}")
            elif (
                f.default is dataclasses.MISSING
                and f.default_factory is dataclasses.MISSING  # type: ignore[misc]
            ):
                raise ValueError(f"{cls.__name__}: required parameter {name!r} missing")
        return cls(**kwargs)

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json_str(self) -> str:
        return json.dumps(self.to_json(), sort_keys=True)

    def replace(self: T, **changes: Any) -> T:
        return dataclasses.replace(self, **changes)


@dataclasses.dataclass
class EmptyParams(Params):
    """Reference: EmptyParams — for components that take no parameters."""


def _snake(name: str) -> str:
    out = []
    for ch in name:
        if ch.isupper():
            out.append("_")
            out.append(ch.lower())
        else:
            out.append(ch)
    return "".join(out)


def _match_key(key: str, fields: Mapping[str, Any], cls_name: str) -> str:
    if key in fields:
        return key
    snake = _snake(key)
    if snake in fields:
        return snake
    if snake + "_" in fields:  # reserved words: lambda -> lambda_
        return snake + "_"
    return key


def _coerce(value: Any, annot: Any, where: str) -> Any:
    """Best-effort typed coercion from JSON values to the annotated type."""
    if isinstance(annot, str):
        # String annotations (from __future__ annotations): resolve builtins only.
        annot = {"int": int, "float": float, "str": str, "bool": bool}.get(annot, None)
        if annot is None:
            return value
    origin = typing.get_origin(annot)
    if origin is typing.Union:
        args = [a for a in typing.get_args(annot) if a is not type(None)]
        if value is None:
            return None
        if len(args) == 1:
            return _coerce(value, args[0], where)
        return value
    if origin in (list, tuple):
        (item_t, *_rest) = typing.get_args(annot) or (Any,)
        seq = [_coerce(v, item_t, where) for v in value]
        return tuple(seq) if origin is tuple else seq
    if origin is dict:
        return dict(value)
    if annot is float:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise TypeError(f"{where}: expected float, got {value!r}")
        return float(value)
    if annot is int:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise TypeError(f"{where}: expected int, got {value!r}")
        if isinstance(value, float) and not value.is_integer():
            raise TypeError(f"{where}: expected int, got {value!r}")
        return int(value)
    if annot is bool and not isinstance(value, bool):
        raise TypeError(f"{where}: expected bool, got {value!r}")
    if annot is str and not isinstance(value, str):
        raise TypeError(f"{where}: expected str, got {value!r}")
    return value
