// Native event-log scanner: JSONL segments -> columnar arrays.
//
// Role: the host-side ingest hot path (SURVEY.md §2 'TPU-equivalent mapping':
// the reference's HBase scan -> Spark RDD ingest becomes sharded sequential
// segment reads staged to device).  The reference has no C++ (it rides
// HBase/Spark JVM I/O); this is its TPU-native equivalent: parse+encode at
// memory bandwidth so the TPU is never input-bound.
//
// Contract: segments are written by Event.to_json_line() — compact JSON, one
// object per line.  The parser is a minimal but correct JSON tokenizer: it
// extracts event/entityId/entityType/targetEntityId/eventTime and
// properties.rating, skipping everything else structurally.
//
// Threading: one worker per segment file (they are immutable once rotated),
// then a single-threaded merge that dictionary-encodes strings.
//
// C ABI (used from Python via ctypes):
//   scan_new() -> handle
//   scan_add_file(h, path)
//   scan_run(h, n_threads) -> row count or -1
//   scan_rows/scan_col_*/scan_dict_* accessors
//   scan_error(h) -> last error message
//   scan_free(h)

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct RawEvent {
  std::string event;
  std::string entity_type;
  std::string entity_id;
  std::string target_id;  // empty = none
  int64_t time_us = 0;
  float rating = NAN;
  bool valid = false;
};

// ---------------------------------------------------------------------- JSON

struct Parser {
  const char* p;
  const char* end;
  bool ok = true;

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r' || *p == '\n')) p++;
  }

  bool expect(char c) {
    skip_ws();
    if (p < end && *p == c) { p++; return true; }
    ok = false;
    return false;
  }

  // Parse a JSON string (assumes *p == '"'), appending the decoded value.
  bool parse_string(std::string* out) {
    skip_ws();
    if (p >= end || *p != '"') { ok = false; return false; }
    p++;
    while (p < end) {
      char c = *p++;
      if (c == '"') return true;
      if (c == '\\') {
        if (p >= end) break;
        char e = *p++;
        switch (e) {
          case '"': if (out) out->push_back('"'); break;
          case '\\': if (out) out->push_back('\\'); break;
          case '/': if (out) out->push_back('/'); break;
          case 'b': if (out) out->push_back('\b'); break;
          case 'f': if (out) out->push_back('\f'); break;
          case 'n': if (out) out->push_back('\n'); break;
          case 'r': if (out) out->push_back('\r'); break;
          case 't': if (out) out->push_back('\t'); break;
          case 'u': {
            if (end - p < 4) { ok = false; return false; }
            unsigned code = 0;
            for (int i = 0; i < 4; i++) {
              char h = *p++;
              code <<= 4;
              if (h >= '0' && h <= '9') code |= h - '0';
              else if (h >= 'a' && h <= 'f') code |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') code |= h - 'A' + 10;
              else { ok = false; return false; }
            }
            // surrogate pair
            if (code >= 0xD800 && code <= 0xDBFF && end - p >= 6 &&
                p[0] == '\\' && p[1] == 'u') {
              unsigned lo = 0;
              const char* q = p + 2;
              for (int i = 0; i < 4; i++) {
                char h = *q++;
                lo <<= 4;
                if (h >= '0' && h <= '9') lo |= h - '0';
                else if (h >= 'a' && h <= 'f') lo |= h - 'a' + 10;
                else if (h >= 'A' && h <= 'F') lo |= h - 'A' + 10;
                else { lo = 0xFFFFFFFF; break; }
              }
              if (lo >= 0xDC00 && lo <= 0xDFFF) {
                code = 0x10000 + ((code - 0xD800) << 10) + (lo - 0xDC00);
                p += 6;
              }
            }
            if (out) {  // encode UTF-8
              if (code < 0x80) out->push_back((char)code);
              else if (code < 0x800) {
                out->push_back((char)(0xC0 | (code >> 6)));
                out->push_back((char)(0x80 | (code & 0x3F)));
              } else if (code < 0x10000) {
                out->push_back((char)(0xE0 | (code >> 12)));
                out->push_back((char)(0x80 | ((code >> 6) & 0x3F)));
                out->push_back((char)(0x80 | (code & 0x3F)));
              } else {
                out->push_back((char)(0xF0 | (code >> 18)));
                out->push_back((char)(0x80 | ((code >> 12) & 0x3F)));
                out->push_back((char)(0x80 | ((code >> 6) & 0x3F)));
                out->push_back((char)(0x80 | (code & 0x3F)));
              }
            }
            break;
          }
          default: ok = false; return false;
        }
      } else if (out) {
        out->push_back(c);
      }
    }
    ok = false;
    return false;
  }

  bool skip_value();  // forward decl

  bool skip_object() {
    if (!expect('{')) return false;
    skip_ws();
    if (p < end && *p == '}') { p++; return true; }
    while (p < end) {
      if (!parse_string(nullptr)) return false;
      if (!expect(':')) return false;
      if (!skip_value()) return false;
      skip_ws();
      if (p < end && *p == ',') { p++; continue; }
      return expect('}');
    }
    ok = false;
    return false;
  }

  bool skip_array() {
    if (!expect('[')) return false;
    skip_ws();
    if (p < end && *p == ']') { p++; return true; }
    while (p < end) {
      if (!skip_value()) return false;
      skip_ws();
      if (p < end && *p == ',') { p++; continue; }
      return expect(']');
    }
    ok = false;
    return false;
  }

  bool parse_number(double* out) {
    skip_ws();
    char* numend = nullptr;
    double v = strtod(p, &numend);
    if (numend == p) { ok = false; return false; }
    if (out) *out = v;
    p = numend;
    return true;
  }

  bool skip_literal(const char* lit) {
    size_t n = strlen(lit);
    if ((size_t)(end - p) >= n && strncmp(p, lit, n) == 0) { p += n; return true; }
    ok = false;
    return false;
  }
};

bool Parser::skip_value() {
  skip_ws();
  if (p >= end) { ok = false; return false; }
  switch (*p) {
    case '"': return parse_string(nullptr);
    case '{': return skip_object();
    case '[': return skip_array();
    case 't': return skip_literal("true");
    case 'f': return skip_literal("false");
    case 'n': return skip_literal("null");
    default: return parse_number(nullptr);
  }
}

// days since epoch for a civil date (Howard Hinnant's algorithm)
int64_t days_from_civil(int y, unsigned m, unsigned d) {
  y -= m <= 2;
  const int era = (y >= 0 ? y : y - 399) / 400;
  const unsigned yoe = (unsigned)(y - era * 400);
  const unsigned doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  const unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return (int64_t)era * 146097 + (int64_t)doe - 719468;
}

// ISO-8601 -> epoch microseconds. Handles "YYYY-MM-DDTHH:MM:SS[.ffffff]"
// with "Z" or "+HH:MM"/"-HH:MM" offset.
bool parse_iso8601_us(const std::string& s, int64_t* out) {
  int y, mo, d, h, mi;
  double sec = 0;
  if (s.size() < 19) return false;
  if (sscanf(s.c_str(), "%d-%d-%dT%d:%d:%lf", &y, &mo, &d, &h, &mi, &sec) != 6)
    return false;
  // find timezone offset after the seconds field
  int64_t offset_s = 0;
  size_t tzpos = s.find_first_of("Z+-", 19);
  // (a '-' inside fractional seconds can't occur; offsets start at/after pos 19)
  if (tzpos != std::string::npos) {
    char c = s[tzpos];
    if (c == '+' || c == '-') {
      int oh = 0, om = 0;
      if (sscanf(s.c_str() + tzpos + 1, "%d:%d", &oh, &om) >= 1) {
        offset_s = (int64_t)oh * 3600 + (int64_t)om * 60;
        if (c == '-') offset_s = -offset_s;
      }
    }
  }
  int64_t days = days_from_civil(y, (unsigned)mo, (unsigned)d);
  double total = (double)days * 86400.0 + h * 3600.0 + mi * 60.0 + sec - (double)offset_s;
  *out = (int64_t)(total * 1e6);
  return true;
}

bool parse_line(const char* line, const char* line_end, RawEvent* ev) {
  Parser ps{line, line_end};
  if (!ps.expect('{')) return false;
  ps.skip_ws();
  if (ps.p < ps.end && *ps.p == '}') { return false; }
  std::string key, sval;
  std::string event_time;
  while (ps.p < ps.end) {
    key.clear();
    if (!ps.parse_string(&key)) return false;
    if (!ps.expect(':')) return false;
    if (key == "event") {
      if (!ps.parse_string(&ev->event)) return false;
    } else if (key == "entityType") {
      if (!ps.parse_string(&ev->entity_type)) return false;
    } else if (key == "entityId") {
      if (!ps.parse_string(&ev->entity_id)) return false;
    } else if (key == "targetEntityId") {
      if (!ps.parse_string(&ev->target_id)) return false;
    } else if (key == "eventTime") {
      if (!ps.parse_string(&event_time)) return false;
    } else if (key == "properties") {
      // walk the object keeping only "rating" if numeric
      ps.skip_ws();
      if (ps.p < ps.end && *ps.p == '{') {
        ps.p++;
        ps.skip_ws();
        if (ps.p < ps.end && *ps.p == '}') { ps.p++; }
        else {
          std::string pk;
          while (ps.p < ps.end) {
            pk.clear();
            if (!ps.parse_string(&pk)) return false;
            if (!ps.expect(':')) return false;
            if (pk == "rating") {
              ps.skip_ws();
              if (ps.p < ps.end && (*ps.p == '-' || (*ps.p >= '0' && *ps.p <= '9'))) {
                double v;
                if (!ps.parse_number(&v)) return false;
                ev->rating = (float)v;
              } else if (!ps.skip_value()) {
                return false;
              }
            } else if (!ps.skip_value()) {
              return false;
            }
            ps.skip_ws();
            if (ps.p < ps.end && *ps.p == ',') { ps.p++; continue; }
            if (!ps.expect('}')) return false;
            break;
          }
        }
      } else if (!ps.skip_value()) {
        return false;
      }
    } else {
      if (!ps.skip_value()) return false;
    }
    ps.skip_ws();
    if (ps.p < ps.end && *ps.p == ',') { ps.p++; continue; }
    if (!ps.expect('}')) return false;
    break;
  }
  if (ev->event.empty() || ev->entity_id.empty()) return false;
  if (!event_time.empty() && !parse_iso8601_us(event_time, &ev->time_us)) return false;
  ev->valid = ps.ok;
  return ps.ok;
}

// ------------------------------------------------------------------- scanner

struct Dict {
  std::unordered_map<std::string, int32_t> map;
  std::vector<std::string> strings;

  int32_t add(const std::string& s) {
    auto it = map.find(s);
    if (it != map.end()) return it->second;
    int32_t id = (int32_t)strings.size();
    map.emplace(s, id);
    strings.push_back(s);
    return id;
  }
};

struct Scanner {
  std::vector<std::string> paths;
  std::string error;

  std::vector<int32_t> event_code, entity_type_code, entity_code, target_code;
  std::vector<int64_t> time_us;
  std::vector<float> rating;
  Dict events, entity_types, entities, targets;

  // dict string export buffers
  std::vector<char> blob;
  std::vector<int64_t> offsets;
};

bool read_file(const std::string& path, std::string* out, std::string* err) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) { *err = "cannot open " + path; return false; }
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  out->resize((size_t)n);
  size_t got = n ? fread(&(*out)[0], 1, (size_t)n, f) : 0;
  fclose(f);
  if ((long)got != n) { *err = "short read on " + path; return false; }
  return true;
}

}  // namespace

extern "C" {

void* scan_new() { return new Scanner(); }

void scan_free(void* h) { delete (Scanner*)h; }

void scan_add_file(void* h, const char* path) {
  ((Scanner*)h)->paths.emplace_back(path);
}

const char* scan_error(void* h) { return ((Scanner*)h)->error.c_str(); }

// Returns row count, or -1 on error.
int64_t scan_run(void* h, int n_threads) {
  Scanner* s = (Scanner*)h;
  size_t n_files = s->paths.size();
  std::vector<std::vector<RawEvent>> per_file(n_files);
  std::vector<std::string> errors(n_files);
  std::atomic<size_t> next{0};
  if (n_threads < 1) n_threads = 1;

  auto worker = [&]() {
    for (;;) {
      size_t i = next.fetch_add(1);
      if (i >= n_files) return;
      std::string content;
      if (!read_file(s->paths[i], &content, &errors[i])) continue;
      const char* p = content.data();
      const char* end = p + content.size();
      auto& out = per_file[i];
      while (p < end) {
        const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
        const char* line_end = nl ? nl : end;
        if (line_end > p) {
          RawEvent ev;
          if (parse_line(p, line_end, &ev)) out.push_back(std::move(ev));
        }
        p = nl ? nl + 1 : end;
      }
    }
  };

  std::vector<std::thread> threads;
  int nt = std::min<int>(n_threads, (int)std::max<size_t>(n_files, 1));
  for (int t = 0; t < nt; t++) threads.emplace_back(worker);
  for (auto& t : threads) t.join();

  for (auto& e : errors) {
    if (!e.empty()) { s->error = e; return -1; }
  }

  size_t total = 0;
  for (auto& v : per_file) total += v.size();
  s->event_code.reserve(total);
  s->entity_type_code.reserve(total);
  s->entity_code.reserve(total);
  s->target_code.reserve(total);
  s->time_us.reserve(total);
  s->rating.reserve(total);
  for (auto& v : per_file) {
    for (auto& ev : v) {
      s->event_code.push_back(s->events.add(ev.event));
      s->entity_type_code.push_back(s->entity_types.add(ev.entity_type));
      s->entity_code.push_back(s->entities.add(ev.entity_id));
      s->target_code.push_back(
          ev.target_id.empty() ? -1 : s->targets.add(ev.target_id));
      s->time_us.push_back(ev.time_us);
      s->rating.push_back(ev.rating);
    }
    v.clear();
    v.shrink_to_fit();
  }
  return (int64_t)s->event_code.size();
}

int64_t scan_rows(void* h) { return (int64_t)((Scanner*)h)->event_code.size(); }

const int32_t* scan_col_event(void* h) { return ((Scanner*)h)->event_code.data(); }
const int32_t* scan_col_entity_type(void* h) { return ((Scanner*)h)->entity_type_code.data(); }
const int32_t* scan_col_entity(void* h) { return ((Scanner*)h)->entity_code.data(); }
const int32_t* scan_col_target(void* h) { return ((Scanner*)h)->target_code.data(); }
const int64_t* scan_col_time(void* h) { return ((Scanner*)h)->time_us.data(); }
const float* scan_col_rating(void* h) { return ((Scanner*)h)->rating.data(); }

static Dict* dict_by_id(Scanner* s, int which) {
  switch (which) {
    case 0: return &s->events;
    case 1: return &s->entity_types;
    case 2: return &s->entities;
    case 3: return &s->targets;
  }
  return nullptr;
}

int64_t scan_dict_size(void* h, int which) {
  Dict* d = dict_by_id((Scanner*)h, which);
  return d ? (int64_t)d->strings.size() : -1;
}

// Export a dict as (blob, offsets[n+1]); returns blob size.
int64_t scan_dict_export(void* h, int which) {
  Scanner* s = (Scanner*)h;
  Dict* d = dict_by_id(s, which);
  if (!d) return -1;
  s->blob.clear();
  s->offsets.clear();
  s->offsets.push_back(0);
  for (auto& str : d->strings) {
    s->blob.insert(s->blob.end(), str.begin(), str.end());
    s->offsets.push_back((int64_t)s->blob.size());
  }
  return (int64_t)s->blob.size();
}

const char* scan_dict_blob(void* h) { return ((Scanner*)h)->blob.data(); }
const int64_t* scan_dict_offsets(void* h) { return ((Scanner*)h)->offsets.data(); }

}  // extern "C"
