// Native event-log scanner: JSONL segments -> columnar arrays.
//
// Role: the host-side ingest hot path (SURVEY.md §2 'TPU-equivalent mapping':
// the reference's HBase scan -> Spark RDD ingest becomes sharded sequential
// segment reads staged to device).  The reference has no C++ (it rides
// HBase/Spark JVM I/O); this is its TPU-native equivalent: parse+encode at
// memory bandwidth so the TPU is never input-bound.
//
// Contract: segments are written by Event.to_json_line() — compact JSON, one
// object per line.  The parser is a minimal but correct JSON tokenizer: it
// extracts event/entityId/entityType/targetEntityId/eventTime and the FULL
// properties map into sparse per-key columns (discovered schema):
//   kind 0 = number (f64), 1 = bool (0/1 in the num facet),
//   kind 2 = string, 3 = list of strings (string facet, per-key dict;
//   numeric/bool list elements are stringified, nested containers inside
//   lists are dropped), 4 = null, 5 = nested object kept as its raw JSON
//   span — dates stay ISO strings for the Python side.
// A legacy dense `rating` column (NaN-missing) is kept as the ALS fast path.
//
// Threading: one worker per segment file (they are immutable once rotated),
// then a single-threaded merge that dictionary-encodes strings.
//
// C ABI (used from Python via ctypes):
//   scan_new() -> handle
//   scan_add_file(h, path)
//   scan_run(h, n_threads) -> row count or -1
//   scan_rows/scan_col_*/scan_dict_* accessors
//   scan_prop_* accessors (sparse property columns)
//   scan_error(h) -> last error message
//   scan_free(h)

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// One parsed property value.  kind: 0 num, 1 bool, 2 str, 3 str-list,
// 4 null (kept: $unset lists keys with null values), 5 raw JSON (nested
// object — the raw text span, decoded lazily Python-side).
struct PropValue {
  int8_t kind = -1;
  double num = NAN;
  std::vector<std::string> strs;
};

struct RawEvent {
  std::string event;
  std::string entity_type;
  std::string entity_id;
  std::string target_id;  // empty = none
  int64_t time_us = 0;
  float rating = NAN;
  bool valid = false;
  std::vector<std::pair<std::string, PropValue>> props;
};

// ---------------------------------------------------------------------- JSON

struct Parser {
  const char* p;
  const char* end;
  bool ok = true;

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r' || *p == '\n')) p++;
  }

  bool expect(char c) {
    skip_ws();
    if (p < end && *p == c) { p++; return true; }
    ok = false;
    return false;
  }

  // Parse a JSON string (assumes *p == '"'), appending the decoded value.
  bool parse_string(std::string* out) {
    skip_ws();
    if (p >= end || *p != '"') { ok = false; return false; }
    p++;
    while (p < end) {
      char c = *p++;
      if (c == '"') return true;
      if (c == '\\') {
        if (p >= end) break;
        char e = *p++;
        switch (e) {
          case '"': if (out) out->push_back('"'); break;
          case '\\': if (out) out->push_back('\\'); break;
          case '/': if (out) out->push_back('/'); break;
          case 'b': if (out) out->push_back('\b'); break;
          case 'f': if (out) out->push_back('\f'); break;
          case 'n': if (out) out->push_back('\n'); break;
          case 'r': if (out) out->push_back('\r'); break;
          case 't': if (out) out->push_back('\t'); break;
          case 'u': {
            if (end - p < 4) { ok = false; return false; }
            unsigned code = 0;
            for (int i = 0; i < 4; i++) {
              char h = *p++;
              code <<= 4;
              if (h >= '0' && h <= '9') code |= h - '0';
              else if (h >= 'a' && h <= 'f') code |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') code |= h - 'A' + 10;
              else { ok = false; return false; }
            }
            // surrogate pair
            if (code >= 0xD800 && code <= 0xDBFF && end - p >= 6 &&
                p[0] == '\\' && p[1] == 'u') {
              unsigned lo = 0;
              const char* q = p + 2;
              for (int i = 0; i < 4; i++) {
                char h = *q++;
                lo <<= 4;
                if (h >= '0' && h <= '9') lo |= h - '0';
                else if (h >= 'a' && h <= 'f') lo |= h - 'a' + 10;
                else if (h >= 'A' && h <= 'F') lo |= h - 'A' + 10;
                else { lo = 0xFFFFFFFF; break; }
              }
              if (lo >= 0xDC00 && lo <= 0xDFFF) {
                code = 0x10000 + ((code - 0xD800) << 10) + (lo - 0xDC00);
                p += 6;
              }
            }
            if (out) {  // encode UTF-8
              if (code < 0x80) out->push_back((char)code);
              else if (code < 0x800) {
                out->push_back((char)(0xC0 | (code >> 6)));
                out->push_back((char)(0x80 | (code & 0x3F)));
              } else if (code < 0x10000) {
                out->push_back((char)(0xE0 | (code >> 12)));
                out->push_back((char)(0x80 | ((code >> 6) & 0x3F)));
                out->push_back((char)(0x80 | (code & 0x3F)));
              } else {
                out->push_back((char)(0xF0 | (code >> 18)));
                out->push_back((char)(0x80 | ((code >> 12) & 0x3F)));
                out->push_back((char)(0x80 | ((code >> 6) & 0x3F)));
                out->push_back((char)(0x80 | (code & 0x3F)));
              }
            }
            break;
          }
          default: ok = false; return false;
        }
      } else if (out) {
        out->push_back(c);
      }
    }
    ok = false;
    return false;
  }

  bool skip_value();  // forward decl

  bool skip_object() {
    if (!expect('{')) return false;
    skip_ws();
    if (p < end && *p == '}') { p++; return true; }
    while (p < end) {
      if (!parse_string(nullptr)) return false;
      if (!expect(':')) return false;
      if (!skip_value()) return false;
      skip_ws();
      if (p < end && *p == ',') { p++; continue; }
      return expect('}');
    }
    ok = false;
    return false;
  }

  bool skip_array() {
    if (!expect('[')) return false;
    skip_ws();
    if (p < end && *p == ']') { p++; return true; }
    while (p < end) {
      if (!skip_value()) return false;
      skip_ws();
      if (p < end && *p == ',') { p++; continue; }
      return expect(']');
    }
    ok = false;
    return false;
  }

  bool parse_number(double* out) {
    skip_ws();
    char* numend = nullptr;
    double v = strtod(p, &numend);
    if (numend == p) { ok = false; return false; }
    if (out) *out = v;
    p = numend;
    return true;
  }

  bool skip_literal(const char* lit) {
    size_t n = strlen(lit);
    if ((size_t)(end - p) >= n && strncmp(p, lit, n) == 0) { p += n; return true; }
    ok = false;
    return false;
  }
};

bool Parser::skip_value() {
  skip_ws();
  if (p >= end) { ok = false; return false; }
  switch (*p) {
    case '"': return parse_string(nullptr);
    case '{': return skip_object();
    case '[': return skip_array();
    case 't': return skip_literal("true");
    case 'f': return skip_literal("false");
    case 'n': return skip_literal("null");
    default: return parse_number(nullptr);
  }
}

// days since epoch for a civil date (Howard Hinnant's algorithm)
int64_t days_from_civil(int y, unsigned m, unsigned d) {
  y -= m <= 2;
  const int era = (y >= 0 ? y : y - 399) / 400;
  const unsigned yoe = (unsigned)(y - era * 400);
  const unsigned doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  const unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return (int64_t)era * 146097 + (int64_t)doe - 719468;
}

// ISO-8601 -> epoch microseconds. Handles "YYYY-MM-DDTHH:MM:SS[.ffffff]"
// with "Z" or "+HH:MM"/"-HH:MM" offset.
bool parse_iso8601_us(const std::string& s, int64_t* out) {
  int y, mo, d, h, mi;
  double sec = 0;
  if (s.size() < 19) return false;
  if (sscanf(s.c_str(), "%d-%d-%dT%d:%d:%lf", &y, &mo, &d, &h, &mi, &sec) != 6)
    return false;
  // find timezone offset after the seconds field
  int64_t offset_s = 0;
  size_t tzpos = s.find_first_of("Z+-", 19);
  // (a '-' inside fractional seconds can't occur; offsets start at/after pos 19)
  if (tzpos != std::string::npos) {
    char c = s[tzpos];
    if (c == '+' || c == '-') {
      int oh = 0, om = 0;
      if (sscanf(s.c_str() + tzpos + 1, "%d:%d", &oh, &om) >= 1) {
        offset_s = (int64_t)oh * 3600 + (int64_t)om * 60;
        if (c == '-') offset_s = -offset_s;
      }
    }
  }
  int64_t days = days_from_civil(y, (unsigned)mo, (unsigned)d);
  double total = (double)days * 86400.0 + h * 3600.0 + mi * 60.0 + sec - (double)offset_s;
  *out = (int64_t)(total * 1e6);
  return true;
}

// Parse one property VALUE into pv (see PropValue kinds): nulls keep
// kind 4, nested objects keep their raw JSON span as kind 5; only nested
// containers INSIDE lists are skipped structurally — the line still parses.
bool parse_prop_value(Parser& ps, PropValue* pv) {
  ps.skip_ws();
  if (ps.p >= ps.end) { ps.ok = false; return false; }
  char c = *ps.p;
  if (c == '"') {
    pv->strs.emplace_back();
    if (!ps.parse_string(&pv->strs.back())) return false;
    pv->kind = 2;
    return true;
  }
  if (c == 't') { pv->kind = 1; pv->num = 1.0; return ps.skip_literal("true"); }
  if (c == 'f') { pv->kind = 1; pv->num = 0.0; return ps.skip_literal("false"); }
  if (c == 'n') { pv->kind = 4; return ps.skip_literal("null"); }
  if (c == '{') {
    const char* start = ps.p;
    if (!ps.skip_object()) return false;
    pv->kind = 5;
    pv->strs.emplace_back(start, (size_t)(ps.p - start));
    return true;
  }
  if (c == '[') {
    ps.p++;
    pv->kind = 3;
    ps.skip_ws();
    if (ps.p < ps.end && *ps.p == ']') { ps.p++; return true; }
    while (ps.p < ps.end) {
      ps.skip_ws();
      if (ps.p >= ps.end) break;
      char e = *ps.p;
      if (e == '"') {
        pv->strs.emplace_back();
        if (!ps.parse_string(&pv->strs.back())) return false;
      } else if (e == 't') {
        if (!ps.skip_literal("true")) return false;
        pv->strs.emplace_back("true");
      } else if (e == 'f') {
        if (!ps.skip_literal("false")) return false;
        pv->strs.emplace_back("false");
      } else if (e == 'n') {
        if (!ps.skip_literal("null")) return false;  // dropped
      } else if (e == '{' ) {
        if (!ps.skip_object()) return false;         // dropped
      } else if (e == '[') {
        if (!ps.skip_array()) return false;          // dropped
      } else {
        double v;
        if (!ps.parse_number(&v)) return false;
        char buf[32];
        snprintf(buf, sizeof buf, "%.17g", v);
        pv->strs.emplace_back(buf);
      }
      ps.skip_ws();
      if (ps.p < ps.end && *ps.p == ',') { ps.p++; continue; }
      return ps.expect(']');
    }
    ps.ok = false;
    return false;
  }
  if (!ps.parse_number(&pv->num)) return false;
  pv->kind = 0;
  return true;
}

bool parse_line(const char* line, const char* line_end, RawEvent* ev) {
  Parser ps{line, line_end};
  if (!ps.expect('{')) return false;
  ps.skip_ws();
  if (ps.p < ps.end && *ps.p == '}') { return false; }
  std::string key, sval;
  std::string event_time;
  while (ps.p < ps.end) {
    key.clear();
    if (!ps.parse_string(&key)) return false;
    if (!ps.expect(':')) return false;
    if (key == "event") {
      if (!ps.parse_string(&ev->event)) return false;
    } else if (key == "entityType") {
      if (!ps.parse_string(&ev->entity_type)) return false;
    } else if (key == "entityId") {
      if (!ps.parse_string(&ev->entity_id)) return false;
    } else if (key == "targetEntityId") {
      if (!ps.parse_string(&ev->target_id)) return false;
    } else if (key == "eventTime") {
      if (!ps.parse_string(&event_time)) return false;
    } else if (key == "properties") {
      ps.skip_ws();
      if (ps.p < ps.end && *ps.p == '{') {
        ps.p++;
        ps.skip_ws();
        if (ps.p < ps.end && *ps.p == '}') { ps.p++; }
        else {
          std::string pk;
          while (ps.p < ps.end) {
            pk.clear();
            if (!ps.parse_string(&pk)) return false;
            if (!ps.expect(':')) return false;
            PropValue pv;
            if (!parse_prop_value(ps, &pv)) return false;
            if (pv.kind == 0 && pk == "rating") ev->rating = (float)pv.num;
            if (pv.kind >= 0) ev->props.emplace_back(std::move(pk), std::move(pv));
            ps.skip_ws();
            if (ps.p < ps.end && *ps.p == ',') { ps.p++; continue; }
            if (!ps.expect('}')) return false;
            break;
          }
        }
      } else if (!ps.skip_value()) {
        return false;
      }
    } else {
      if (!ps.skip_value()) return false;
    }
    ps.skip_ws();
    if (ps.p < ps.end && *ps.p == ',') { ps.p++; continue; }
    if (!ps.expect('}')) return false;
    break;
  }
  if (ev->event.empty() || ev->entity_id.empty()) return false;
  if (!event_time.empty() && !parse_iso8601_us(event_time, &ev->time_us)) return false;
  ev->valid = ps.ok;
  return ps.ok;
}

// ------------------------------------------------------------------- scanner

struct Dict {
  std::unordered_map<std::string, int32_t> map;
  std::vector<std::string> strings;

  int32_t add(const std::string& s) {
    auto it = map.find(s);
    if (it != map.end()) return it->second;
    int32_t id = (int32_t)strings.size();
    map.emplace(s, id);
    strings.push_back(s);
    return id;
  }
};

// Sparse per-key property column: entry j is (rows[j], kind[j], num[j],
// strings codes[str_offs[j] .. str_offs[j+1])).  rows are ascending by
// construction (merge walks rows in order).
struct PropColumn {
  std::vector<int64_t> rows;
  std::vector<int8_t> kind;
  std::vector<double> num;
  std::vector<int64_t> str_offs;  // finalized to size n+1 after merge
  std::vector<int32_t> codes;
  Dict dict;
};

struct Scanner {
  std::vector<std::string> paths;
  std::string error;

  std::vector<int32_t> event_code, entity_type_code, entity_code, target_code;
  std::vector<int64_t> time_us;
  std::vector<float> rating;
  Dict events, entity_types, entities, targets;

  std::unordered_map<std::string, int> prop_index;
  std::vector<std::string> prop_keys;
  std::vector<PropColumn> prop_cols;

  // dict string export buffers
  std::vector<char> blob;
  std::vector<int64_t> offsets;

  PropColumn* prop_col(const std::string& key) {
    auto it = prop_index.find(key);
    if (it != prop_index.end()) return &prop_cols[it->second];
    int idx = (int)prop_cols.size();
    prop_index.emplace(key, idx);
    prop_keys.push_back(key);
    prop_cols.emplace_back();
    return &prop_cols[idx];
  }
};

bool read_file(const std::string& path, std::string* out, std::string* err) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) { *err = "cannot open " + path; return false; }
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  out->resize((size_t)n);
  size_t got = n ? fread(&(*out)[0], 1, (size_t)n, f) : 0;
  fclose(f);
  if ((long)got != n) { *err = "short read on " + path; return false; }
  return true;
}

}  // namespace

extern "C" {

void* scan_new() { return new Scanner(); }

void scan_free(void* h) { delete (Scanner*)h; }

void scan_add_file(void* h, const char* path) {
  ((Scanner*)h)->paths.emplace_back(path);
}

const char* scan_error(void* h) { return ((Scanner*)h)->error.c_str(); }

// Returns row count, or -1 on error.
int64_t scan_run(void* h, int n_threads) {
  Scanner* s = (Scanner*)h;
  size_t n_files = s->paths.size();
  std::vector<std::vector<RawEvent>> per_file(n_files);
  std::vector<std::string> errors(n_files);
  std::atomic<size_t> next{0};
  if (n_threads < 1) n_threads = 1;

  auto worker = [&]() {
    for (;;) {
      size_t i = next.fetch_add(1);
      if (i >= n_files) return;
      std::string content;
      if (!read_file(s->paths[i], &content, &errors[i])) continue;
      const char* p = content.data();
      const char* end = p + content.size();
      auto& out = per_file[i];
      while (p < end) {
        const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
        if (!nl) break;  // unterminated torn tail (writer killed
                         // mid-append): never acknowledged; the Python
                         // scan skips it and the owning writer truncates
                         // it on reopen — surfacing it here would make
                         // native and Python scans disagree
        if (nl > p) {
          RawEvent ev;
          if (parse_line(p, nl, &ev)) out.push_back(std::move(ev));
        }
        p = nl + 1;
      }
    }
  };

  std::vector<std::thread> threads;
  int nt = std::min<int>(n_threads, (int)std::max<size_t>(n_files, 1));
  for (int t = 0; t < nt; t++) threads.emplace_back(worker);
  for (auto& t : threads) t.join();

  for (auto& e : errors) {
    if (!e.empty()) { s->error = e; return -1; }
  }

  size_t total = 0;
  for (auto& v : per_file) total += v.size();
  s->event_code.reserve(total);
  s->entity_type_code.reserve(total);
  s->entity_code.reserve(total);
  s->target_code.reserve(total);
  s->time_us.reserve(total);
  s->rating.reserve(total);
  for (auto& v : per_file) {
    for (auto& ev : v) {
      int64_t row = (int64_t)s->event_code.size();
      s->event_code.push_back(s->events.add(ev.event));
      s->entity_type_code.push_back(s->entity_types.add(ev.entity_type));
      s->entity_code.push_back(s->entities.add(ev.entity_id));
      s->target_code.push_back(
          ev.target_id.empty() ? -1 : s->targets.add(ev.target_id));
      s->time_us.push_back(ev.time_us);
      s->rating.push_back(ev.rating);
      for (auto& kv : ev.props) {
        PropColumn* col = s->prop_col(kv.first);
        col->rows.push_back(row);
        col->kind.push_back(kv.second.kind);
        col->num.push_back(kv.second.num);
        col->str_offs.push_back((int64_t)kv.second.strs.size());  // lengths now
        for (auto& str : kv.second.strs) col->codes.push_back(col->dict.add(str));
      }
    }
    v.clear();
    v.shrink_to_fit();
  }
  // finalize lengths -> exclusive-scan offsets [n+1]
  for (auto& col : s->prop_cols) {
    int64_t acc = 0;
    col.str_offs.push_back(0);
    for (size_t j = 0; j + 1 < col.str_offs.size(); j++) {
      int64_t len = col.str_offs[j];
      col.str_offs[j] = acc;
      acc += len;
    }
    col.str_offs.back() = acc;
  }
  return (int64_t)s->event_code.size();
}

int64_t scan_rows(void* h) { return (int64_t)((Scanner*)h)->event_code.size(); }

const int32_t* scan_col_event(void* h) { return ((Scanner*)h)->event_code.data(); }
const int32_t* scan_col_entity_type(void* h) { return ((Scanner*)h)->entity_type_code.data(); }
const int32_t* scan_col_entity(void* h) { return ((Scanner*)h)->entity_code.data(); }
const int32_t* scan_col_target(void* h) { return ((Scanner*)h)->target_code.data(); }
const int64_t* scan_col_time(void* h) { return ((Scanner*)h)->time_us.data(); }
const float* scan_col_rating(void* h) { return ((Scanner*)h)->rating.data(); }

static Dict* dict_by_id(Scanner* s, int which) {
  switch (which) {
    case 0: return &s->events;
    case 1: return &s->entity_types;
    case 2: return &s->entities;
    case 3: return &s->targets;
  }
  return nullptr;
}

int64_t scan_dict_size(void* h, int which) {
  Dict* d = dict_by_id((Scanner*)h, which);
  return d ? (int64_t)d->strings.size() : -1;
}

// Export a dict as (blob, offsets[n+1]); returns blob size.
int64_t scan_dict_export(void* h, int which) {
  Scanner* s = (Scanner*)h;
  Dict* d = dict_by_id(s, which);
  if (!d) return -1;
  s->blob.clear();
  s->offsets.clear();
  s->offsets.push_back(0);
  for (auto& str : d->strings) {
    s->blob.insert(s->blob.end(), str.begin(), str.end());
    s->offsets.push_back((int64_t)s->blob.size());
  }
  return (int64_t)s->blob.size();
}

const char* scan_dict_blob(void* h) { return ((Scanner*)h)->blob.data(); }
const int64_t* scan_dict_offsets(void* h) { return ((Scanner*)h)->offsets.data(); }

// ------------------------------ sparse property columns (discovered schema)

int64_t scan_prop_count(void* h) { return (int64_t)((Scanner*)h)->prop_cols.size(); }

// Key export is length-delimited (NOT c_str): JSON keys may contain
// embedded NULs via the \u0000 escape, and truncation could silently collide two
// distinct columns on the Python side.
const char* scan_prop_key(void* h, int k) {
  Scanner* s = (Scanner*)h;
  if (k < 0 || (size_t)k >= s->prop_keys.size()) return nullptr;
  return s->prop_keys[k].data();
}

int64_t scan_prop_key_len(void* h, int k) {
  Scanner* s = (Scanner*)h;
  if (k < 0 || (size_t)k >= s->prop_keys.size()) return -1;
  return (int64_t)s->prop_keys[k].size();
}

static PropColumn* prop_by_id(void* h, int k) {
  Scanner* s = (Scanner*)h;
  if (k < 0 || (size_t)k >= s->prop_cols.size()) return nullptr;
  return &s->prop_cols[k];
}

int64_t scan_prop_len(void* h, int k) {
  PropColumn* c = prop_by_id(h, k);
  return c ? (int64_t)c->rows.size() : -1;
}

const int64_t* scan_prop_rows(void* h, int k) {
  PropColumn* c = prop_by_id(h, k);
  return c ? c->rows.data() : nullptr;
}

const int8_t* scan_prop_kind(void* h, int k) {
  PropColumn* c = prop_by_id(h, k);
  return c ? c->kind.data() : nullptr;
}

const double* scan_prop_num(void* h, int k) {
  PropColumn* c = prop_by_id(h, k);
  return c ? c->num.data() : nullptr;
}

const int64_t* scan_prop_stroffs(void* h, int k) {
  PropColumn* c = prop_by_id(h, k);
  return c ? c->str_offs.data() : nullptr;
}

const int32_t* scan_prop_codes(void* h, int k) {
  PropColumn* c = prop_by_id(h, k);
  return c ? c->codes.data() : nullptr;
}

int64_t scan_prop_codes_len(void* h, int k) {
  PropColumn* c = prop_by_id(h, k);
  return c ? (int64_t)c->codes.size() : -1;
}

int64_t scan_prop_dict_size(void* h, int k) {
  PropColumn* c = prop_by_id(h, k);
  return c ? (int64_t)c->dict.strings.size() : -1;
}

// Export a property column's dict via the shared blob/offsets buffers.
int64_t scan_prop_dict_export(void* h, int k) {
  Scanner* s = (Scanner*)h;
  PropColumn* c = prop_by_id(h, k);
  if (!c) return -1;
  s->blob.clear();
  s->offsets.clear();
  s->offsets.push_back(0);
  for (auto& str : c->dict.strings) {
    s->blob.insert(s->blob.end(), str.begin(), str.end());
    s->offsets.push_back((int64_t)s->blob.size());
  }
  return (int64_t)s->blob.size();
}

// --------------------------------------------- chunked COO layout (training)
//
// The device CCO path wants (user, item) pairs grouped into fixed-size user
// chunks, padded to a common width (ops/cco._stage_chunked).  numpy does
// argsort + fancy-indexing + a Python fill loop; this is the O(n) two-pass
// counting layout — at 1B events the layout IS the host pipeline, so it
// lives next to the scanner.
//
//   layout_width(user, n, chunk, n_chunks, pad_multiple) -> padded width
//   layout_fill(user, item, n, chunk, n_chunks, width,
//               out_lu, out_it, out_cnt) -> 0 on success
//
// out_lu/out_it are [n_chunks * width] int32 (caller-zeroed), out_cnt is
// [n_chunks] int32.

int64_t layout_width(const int32_t* user, int64_t n, int32_t chunk,
                     int32_t n_chunks, int32_t pad_multiple) {
  if (chunk <= 0 || n_chunks <= 0) return -1;
  std::vector<int64_t> counts(n_chunks, 0);
  for (int64_t i = 0; i < n; i++) {
    int32_t u = user[i];
    int32_t b = u / chunk;
    // explicit u < 0: truncating division maps [-(chunk-1), -1] to b == 0
    if (u < 0 || b >= n_chunks) return -1;  // user id out of range
    counts[b]++;
  }
  int64_t width = 1;
  for (int64_t c : counts) width = c > width ? c : width;
  if (pad_multiple > 1) width = (width + pad_multiple - 1) / pad_multiple * pad_multiple;
  return width;
}

int32_t layout_fill(const int32_t* user, const int32_t* item, int64_t n,
                    int32_t chunk, int32_t n_chunks, int64_t width,
                    int32_t* out_lu, int32_t* out_it, int32_t* out_cnt) {
  if (chunk <= 0 || n_chunks <= 0 || width <= 0) return -1;
  std::vector<int64_t> cursor(n_chunks, 0);
  for (int64_t i = 0; i < n; i++) {
    int32_t u = user[i];
    int32_t b = u / chunk;
    if (u < 0 || b >= n_chunks) return -1;
    int64_t pos = (int64_t)b * width + cursor[b];
    if (cursor[b] >= width) return -2;  // width too small for this chunk
    out_lu[pos] = u % chunk;
    out_it[pos] = item[i];
    cursor[b]++;
  }
  for (int32_t b = 0; b < n_chunks; b++) out_cnt[b] = (int32_t)cursor[b];
  return 0;
}

}  // extern "C"
