// Native GIL-releasing data-plane cores (ctypes C ABI, no Python.h).
//
// Two cores behind predictionio_tpu/native/core.py's PIO_NATIVE knob:
//
//   scan core  — columnar snapshot header parse (PIOCOL01 JSON header →
//                column specs + dictionary string blobs), string-dict
//                bulk-union handles for BatchMerger's k-way merge, and
//                the merge's code-map gathers.
//   serve core — the serve tail's hot loop (CSR posting gather, unique,
//                weighted-bincount score accumulation, composite-key
//                top-k) plus a lean HTTP/1.1 request-head parse and
//                response assembly for the query-server worker.
//
// Every entry point is called through ctypes.CDLL, so the GIL is
// dropped for the duration of the call — that, not raw single-thread
// speed, is the design goal: per-shard scans and concurrent queries
// overlap instead of serializing on the interpreter lock.
//
// Bit-exactness contracts vs the PIO_NATIVE=off Python oracle are
// spelled out per function; tests/test_native_cores.py holds them.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <string>
#include <unordered_map>
#include <vector>

#if defined(_WIN32)
#define EXPORT extern "C" __declspec(dllexport)
#else
#define EXPORT extern "C" __attribute__((visibility("default")))
#endif

namespace {

// ---------------------------------------------------------------------------
// string_view for pre-C++17-string_view-in-map portability (we target
// C++17 so std::string_view is available; alias for brevity)
using sv = std::basic_string_view<char>;

// UTF-8 encode one code point (surrogate code points use the normal
// 3-byte formula — exactly the bytes Python's "surrogatepass" codec
// round-trips, which is how json.loads-compatible lone surrogates
// survive the native path).
inline void utf8_put(std::string &out, uint32_t cp) {
    if (cp < 0x80) {
        out.push_back((char)cp);
    } else if (cp < 0x800) {
        out.push_back((char)(0xC0 | (cp >> 6)));
        out.push_back((char)(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
        out.push_back((char)(0xE0 | (cp >> 12)));
        out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
        out.push_back((char)(0x80 | (cp & 0x3F)));
    } else {
        out.push_back((char)(0xF0 | (cp >> 18)));
        out.push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
        out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
        out.push_back((char)(0x80 | (cp & 0x3F)));
    }
}

// -- minimal JSON parser (schema-directed, for the PIOCOL01 header) ---------

struct Json {
    const char *p, *end;
    bool ok = true;

    explicit Json(const char *buf, int64_t len) : p(buf), end(buf + len) {}

    void ws() { while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p; }
    bool lit(char c) { ws(); if (p < end && *p == c) { ++p; return true; } ok = false; return false; }
    bool peek(char c) { ws(); return p < end && *p == c; }

    static int hex(char c) {
        if (c >= '0' && c <= '9') return c - '0';
        if (c >= 'a' && c <= 'f') return c - 'a' + 10;
        if (c >= 'A' && c <= 'F') return c - 'A' + 10;
        return -1;
    }

    bool u16(uint32_t &v) {
        if (end - p < 4) return false;
        v = 0;
        for (int i = 0; i < 4; ++i) {
            int h = hex(p[i]);
            if (h < 0) return false;
            v = (v << 4) | (uint32_t)h;
        }
        p += 4;
        return true;
    }

    // JSON string → UTF-8 bytes appended to out (escape handling matches
    // Python json.loads: surrogate pairs combine, lone surrogates pass
    // through as their 3-byte encoding)
    bool str(std::string &out) {
        if (!lit('"')) return false;
        while (p < end) {
            unsigned char c = (unsigned char)*p;
            if (c == '"') { ++p; return true; }
            if (c == '\\') {
                ++p;
                if (p >= end) break;
                char e = *p++;
                switch (e) {
                case '"': out.push_back('"'); break;
                case '\\': out.push_back('\\'); break;
                case '/': out.push_back('/'); break;
                case 'b': out.push_back('\b'); break;
                case 'f': out.push_back('\f'); break;
                case 'n': out.push_back('\n'); break;
                case 'r': out.push_back('\r'); break;
                case 't': out.push_back('\t'); break;
                case 'u': {
                    uint32_t hi;
                    if (!u16(hi)) { ok = false; return false; }
                    if (hi >= 0xD800 && hi < 0xDC00 && end - p >= 6 &&
                        p[0] == '\\' && p[1] == 'u') {
                        const char *save = p;
                        p += 2;
                        uint32_t lo;
                        if (u16(lo) && lo >= 0xDC00 && lo < 0xE000) {
                            utf8_put(out, 0x10000 + ((hi - 0xD800) << 10) + (lo - 0xDC00));
                            break;
                        }
                        p = save;  // not a low surrogate: leave for next loop
                    }
                    utf8_put(out, hi);
                    break;
                }
                default: ok = false; return false;
                }
            } else {
                out.push_back((char)c);
                ++p;
            }
        }
        ok = false;
        return false;
    }

    bool num(double &d, int64_t &i, bool &is_int) {
        ws();
        const char *s = p;
        if (p < end && (*p == '-' || *p == '+')) ++p;
        is_int = true;
        while (p < end && ((*p >= '0' && *p <= '9') || *p == '.' || *p == 'e' ||
                           *p == 'E' || *p == '-' || *p == '+')) {
            if (*p == '.' || *p == 'e' || *p == 'E') is_int = false;
            ++p;
        }
        if (p == s) { ok = false; return false; }
        char buf[64];
        size_t n = (size_t)(p - s);
        if (n >= sizeof(buf)) { ok = false; return false; }
        memcpy(buf, s, n);
        buf[n] = 0;
        if (is_int) i = strtoll(buf, nullptr, 10);
        d = strtod(buf, nullptr);
        return true;
    }

    bool integer(int64_t &v) {
        double d; bool ii;
        if (!num(d, v, ii)) return false;
        if (!ii) v = (int64_t)d;
        return true;
    }

    bool skip() {  // skip any value
        ws();
        if (p >= end) { ok = false; return false; }
        char c = *p;
        if (c == '"') { std::string tmp; return str(tmp); }
        if (c == '{') {
            ++p;
            if (peek('}')) { ++p; return true; }
            while (ok) {
                std::string k;
                if (!str(k) || !lit(':') || !skip()) return false;
                if (peek(',')) { ++p; continue; }
                return lit('}');
            }
            return false;
        }
        if (c == '[') {
            ++p;
            if (peek(']')) { ++p; return true; }
            while (ok) {
                if (!skip()) return false;
                if (peek(',')) { ++p; continue; }
                return lit(']');
            }
            return false;
        }
        if (c == 't') { if (end - p >= 4 && !memcmp(p, "true", 4)) { p += 4; return true; } }
        else if (c == 'f') { if (end - p >= 5 && !memcmp(p, "false", 5)) { p += 5; return true; } }
        else if (c == 'n') { if (end - p >= 4 && !memcmp(p, "null", 4)) { p += 4; return true; } }
        else { double d; int64_t i; bool ii; return num(d, i, ii); }
        ok = false;
        return false;
    }
};

// -- columnar snapshot header ------------------------------------------------

struct Spec {
    int64_t n = -1, off = -1;
    std::string dtype;
    bool present = false;
};

struct StrTable {           // decoded JSON string array → blob + offsets
    std::string blob;
    std::vector<int64_t> offs{0};
    int64_t n() const { return (int64_t)offs.size() - 1; }
};

struct PropEntry {
    std::string key;
    StrTable dict;
    Spec rows, kind, num, str_offs, codes;
};

struct ColHeader {
    int64_t rows = -1;
    Spec cols[6];            // event,entity_type,entity,target,times,ratings
    bool has_ids = false;
    Spec ids_blob, ids_offs;
    StrTable dicts[4];       // event, entity_type, entity, target
    bool has_dict[4] = {false, false, false, false};
    std::vector<PropEntry> props;
    int64_t meta_off = -1, meta_len = 0;
};

const char *kColNames[6] = {"event_codes", "entity_type_codes", "entity_ids",
                            "target_ids", "times_us", "ratings"};
const char *kColDtypes[6] = {"<i4", "<i4", "<i4", "<i4", "<i8", "<f4"};
const char *kDictNames[4] = {"event", "entity_type", "entity", "target"};

bool parse_spec(Json &j, Spec &s, const char *want_dtype) {
    if (!j.lit('{')) return false;
    while (j.ok) {
        std::string k;
        if (!j.str(k) || !j.lit(':')) return false;
        if (k == "dtype") {
            s.dtype.clear();
            if (!j.str(s.dtype)) return false;
        } else if (k == "n") {
            if (!j.integer(s.n)) return false;
        } else if (k == "off") {
            if (!j.integer(s.off)) return false;
        } else if (!j.skip()) {
            return false;
        }
        if (j.peek(',')) { ++j.p; continue; }
        if (!j.lit('}')) return false;
        break;
    }
    if (s.n < 0 || s.off < 0 || s.dtype != want_dtype) return false;
    s.present = true;
    return true;
}

bool parse_str_array(Json &j, StrTable &t) {
    if (!j.lit('[')) return false;
    if (j.peek(']')) { ++j.p; return true; }
    while (j.ok) {
        if (!j.str(t.blob)) return false;
        t.offs.push_back((int64_t)t.blob.size());
        if (j.peek(',')) { ++j.p; continue; }
        return j.lit(']');
    }
    return false;
}

bool parse_prop_entry(Json &j, PropEntry &e) {
    if (!j.lit('{')) return false;
    bool have[5] = {false, false, false, false, false};
    bool have_dict = false;
    while (j.ok) {
        std::string k;
        if (!j.str(k) || !j.lit(':')) return false;
        if (k == "dict") { if (!parse_str_array(j, e.dict)) return false; have_dict = true; }
        else if (k == "rows") { if (!parse_spec(j, e.rows, "<i8")) return false; have[0] = true; }
        else if (k == "kind") { if (!parse_spec(j, e.kind, "|i1")) return false; have[1] = true; }
        else if (k == "num") { if (!parse_spec(j, e.num, "<f8")) return false; have[2] = true; }
        else if (k == "str_offs") { if (!parse_spec(j, e.str_offs, "<i8")) return false; have[3] = true; }
        else if (k == "codes") { if (!parse_spec(j, e.codes, "<i4")) return false; have[4] = true; }
        else if (!j.skip()) return false;
        if (j.peek(',')) { ++j.p; continue; }
        if (!j.lit('}')) return false;
        break;
    }
    return have_dict && have[0] && have[1] && have[2] && have[3] && have[4];
}

bool parse_header(Json &j, const char *base, ColHeader &h) {
    if (!j.lit('{')) return false;
    while (j.ok) {
        std::string k;
        if (!j.str(k) || !j.lit(':')) return false;
        if (k == "rows") {
            if (!j.integer(h.rows)) return false;
        } else if (k == "cols") {
            if (!j.lit('{')) return false;
            while (j.ok) {
                std::string name;
                if (!j.str(name) || !j.lit(':')) return false;
                int slot = -1;
                for (int i = 0; i < 6; ++i)
                    if (name == kColNames[i]) { slot = i; break; }
                if (slot >= 0) {
                    if (!parse_spec(j, h.cols[slot], kColDtypes[slot])) return false;
                } else if (!j.skip()) return false;
                if (j.peek(',')) { ++j.p; continue; }
                if (!j.lit('}')) return false;
                break;
            }
        } else if (k == "ids") {
            j.ws();
            if (j.peek('n')) { if (!j.skip()) return false; }
            else {
                if (!j.lit('{')) return false;
                while (j.ok) {
                    std::string name;
                    if (!j.str(name) || !j.lit(':')) return false;
                    if (name == "blob") { if (!parse_spec(j, h.ids_blob, "|u1")) return false; }
                    else if (name == "offs") { if (!parse_spec(j, h.ids_offs, "<i8")) return false; }
                    else if (!j.skip()) return false;
                    if (j.peek(',')) { ++j.p; continue; }
                    if (!j.lit('}')) return false;
                    break;
                }
                h.has_ids = h.ids_blob.present && h.ids_offs.present;
                if (!h.has_ids) return false;
            }
        } else if (k == "dicts") {
            if (!j.lit('{')) return false;
            while (j.ok) {
                std::string name;
                if (!j.str(name) || !j.lit(':')) return false;
                int slot = -1;
                for (int i = 0; i < 4; ++i)
                    if (name == kDictNames[i]) { slot = i; break; }
                if (slot >= 0) {
                    if (!parse_str_array(j, h.dicts[slot])) return false;
                    h.has_dict[slot] = true;
                } else if (!j.skip()) return false;
                if (j.peek(',')) { ++j.p; continue; }
                if (!j.lit('}')) return false;
                break;
            }
        } else if (k == "props") {
            if (!j.lit('[')) return false;
            if (j.peek(']')) { ++j.p; }
            else while (j.ok) {
                // each entry is [key, {...}]
                if (!j.lit('[')) return false;
                PropEntry e;
                if (!j.str(e.key) || !j.lit(',') || !parse_prop_entry(j, e)) return false;
                if (!j.lit(']')) return false;
                h.props.push_back(std::move(e));
                if (j.peek(',')) { ++j.p; continue; }
                if (!j.lit(']')) return false;
                break;
            }
        } else if (k == "meta") {
            j.ws();
            const char *s = j.p;
            if (!j.skip()) return false;
            h.meta_off = (int64_t)(s - base);
            h.meta_len = (int64_t)(j.p - s);
        } else if (!j.skip()) {
            return false;
        }
        if (j.peek(',')) { ++j.p; continue; }
        if (!j.lit('}')) return false;
        break;
    }
    if (h.rows < 0) return false;
    for (int i = 0; i < 6; ++i)
        if (!h.cols[i].present) return false;
    for (int i = 0; i < 4; ++i)
        if (!h.has_dict[i]) return false;
    return j.ok;
}

// -- string dictionary handle ------------------------------------------------

struct Dict {
    std::unordered_map<sv, int32_t> map;
    std::deque<std::string> store;   // stable addresses for map keys
    std::string exp_blob;
    std::vector<int64_t> exp_offs;
};

}  // namespace

// ===========================================================================
// C ABI
// ===========================================================================

EXPORT int64_t dp_abi_version() { return 1; }

// -- scan core: snapshot header ---------------------------------------------

EXPORT void *dp_col_parse(const char *buf, int64_t len) {
    auto *h = new ColHeader();
    Json j(buf, len);
    if (!parse_header(j, buf, *h)) {
        delete h;
        return nullptr;
    }
    return h;
}

EXPORT void dp_col_free(void *p) { delete (ColHeader *)p; }

EXPORT int64_t dp_col_rows(void *p) { return ((ColHeader *)p)->rows; }

// which: 0..5 fixed columns, 6 ids blob, 7 ids offs.  out = [n, off].
// returns 0, or -1 when absent (ids on an id-less snapshot).
EXPORT int dp_col_spec(void *p, int which, int64_t *out) {
    auto *h = (ColHeader *)p;
    const Spec *s = nullptr;
    if (which >= 0 && which < 6) s = &h->cols[which];
    else if (which == 6) s = h->has_ids ? &h->ids_blob : nullptr;
    else if (which == 7) s = h->has_ids ? &h->ids_offs : nullptr;
    if (s == nullptr || !s->present) return -1;
    out[0] = s->n;
    out[1] = s->off;
    return 0;
}

EXPORT int64_t dp_col_dict_n(void *p, int which) {
    return ((ColHeader *)p)->dicts[which].n();
}

EXPORT int64_t dp_col_dict_bytes(void *p, int which) {
    return (int64_t)((ColHeader *)p)->dicts[which].blob.size();
}

EXPORT void dp_col_dict_copy(void *p, int which, char *out_blob, int64_t *out_offs) {
    auto &t = ((ColHeader *)p)->dicts[which];
    if (!t.blob.empty()) memcpy(out_blob, t.blob.data(), t.blob.size());
    memcpy(out_offs, t.offs.data(), t.offs.size() * sizeof(int64_t));
}

EXPORT int64_t dp_col_nprops(void *p) { return (int64_t)((ColHeader *)p)->props.size(); }

EXPORT int64_t dp_col_prop_key_bytes(void *p, int64_t i) {
    return (int64_t)((ColHeader *)p)->props[i].key.size();
}

EXPORT void dp_col_prop_key_copy(void *p, int64_t i, char *out) {
    auto &k = ((ColHeader *)p)->props[i].key;
    if (!k.empty()) memcpy(out, k.data(), k.size());
}

// which: 0 rows, 1 kind, 2 num, 3 str_offs, 4 codes.  out = [n, off].
EXPORT int dp_col_prop_spec(void *p, int64_t i, int which, int64_t *out) {
    auto &e = ((ColHeader *)p)->props[i];
    const Spec *s = which == 0 ? &e.rows : which == 1 ? &e.kind
                  : which == 2 ? &e.num : which == 3 ? &e.str_offs
                  : which == 4 ? &e.codes : nullptr;
    if (s == nullptr || !s->present) return -1;
    out[0] = s->n;
    out[1] = s->off;
    return 0;
}

EXPORT int64_t dp_col_prop_dict_n(void *p, int64_t i) {
    return ((ColHeader *)p)->props[i].dict.n();
}

EXPORT int64_t dp_col_prop_dict_bytes(void *p, int64_t i) {
    return (int64_t)((ColHeader *)p)->props[i].dict.blob.size();
}

EXPORT void dp_col_prop_dict_copy(void *p, int64_t i, char *out_blob, int64_t *out_offs) {
    auto &t = ((ColHeader *)p)->props[i].dict;
    if (!t.blob.empty()) memcpy(out_blob, t.blob.data(), t.blob.size());
    memcpy(out_offs, t.offs.data(), t.offs.size() * sizeof(int64_t));
}

// out = [off, len] of the raw "meta" JSON value inside the header bytes
// (-1 length 0 when absent)
EXPORT void dp_col_meta_span(void *p, int64_t *out) {
    auto *h = (ColHeader *)p;
    out[0] = h->meta_off;
    out[1] = h->meta_len;
}

// -- scan core: dictionary union handles ------------------------------------

EXPORT void *dp_dict_new() { return new Dict(); }
EXPORT void dp_dict_free(void *p) { delete (Dict *)p; }
EXPORT int64_t dp_dict_len(void *p) { return (int64_t)((Dict *)p)->map.size(); }

// Bulk-union n strings (utf-8 blob + n+1 offsets) into the dict.  Codes
// are assigned in first-appearance order — the BatchMerger bit-exactness
// contract.  out_map[i] = code of string i.  Returns the number of NEW
// strings appended (they get codes [old_len, old_len + new)).
EXPORT int64_t dp_dict_union(void *p, const char *blob, const int64_t *offs,
                             int64_t n, int32_t *out_map) {
    auto *d = (Dict *)p;
    int64_t nnew = 0;
    for (int64_t i = 0; i < n; ++i) {
        sv s(blob + offs[i], (size_t)(offs[i + 1] - offs[i]));
        auto it = d->map.find(s);
        if (it != d->map.end()) {
            out_map[i] = it->second;
        } else {
            d->store.emplace_back(s);
            const std::string &owned = d->store.back();
            int32_t id = (int32_t)d->map.size();
            d->map.emplace(sv(owned.data(), owned.size()), id);
            out_map[i] = id;
            ++nnew;
        }
    }
    return nnew;
}

// Export strings [from, len) as blob+offsets (the strings appended by
// the unions since `from`).  Call _bytes to build (returns blob size),
// then read the pointers.
EXPORT int64_t dp_dict_export(void *p, int64_t from) {
    auto *d = (Dict *)p;
    int64_t n = (int64_t)d->map.size();
    if (from < 0 || from > n) return -1;
    d->exp_blob.clear();
    d->exp_offs.assign(1, 0);
    for (int64_t i = from; i < n; ++i) {
        const std::string &s = d->store[(size_t)i];
        d->exp_blob.append(s);
        d->exp_offs.push_back((int64_t)d->exp_blob.size());
    }
    return (int64_t)d->exp_blob.size();
}

EXPORT const char *dp_dict_export_blob(void *p) { return ((Dict *)p)->exp_blob.data(); }
EXPORT const int64_t *dp_dict_export_offs(void *p) { return ((Dict *)p)->exp_offs.data(); }

// -- scan core: merge gathers ------------------------------------------------

// out[i] = cmap[codes[i]]; with sentinel != 0 the semantics are exactly
// numpy's take over cmap with -1 appended (the target_ids merge): code
// -1 maps to -1, other negative codes index from the END of the
// extended map (numpy wrap-around — corrupt input, but bit-exact).
// Returns 0, or -1 on a code numpy would raise IndexError for (caller
// falls back to the numpy path, which raises the oracle's error).
EXPORT int dp_take_i32(const int32_t *cmap, int64_t n_map, const int32_t *codes,
                       int64_t n, int32_t *out, int sentinel) {
    for (int64_t i = 0; i < n; ++i) {
        int64_t c = codes[i];
        if (sentinel && c < 0) c += n_map + 1;   // index into cmap + [-1]
        if (c < 0 || c > n_map || (c == n_map && !sentinel)) return -1;
        out[i] = (c == n_map) ? -1 : cmap[c];
    }
    return 0;
}

// -- serve core: CSR gather / score / top-k ---------------------------------

// Total gathered element count for the in-range, non-empty segments of
// ids — pass 1 of the two-pass gather (both passes run without the GIL).
EXPORT int64_t dp_csr_gather_size(const int64_t *indptr, int64_t n_rows,
                                  const int64_t *ids, int64_t m) {
    int64_t total = 0;
    for (int64_t i = 0; i < m; ++i) {
        int64_t id = ids[i];
        if (id < 0 || id >= n_rows) continue;
        total += indptr[id + 1] - indptr[id];
    }
    return total;
}

// Pass 2: concatenate segments in id order, elements in storage order —
// identical element order to models.common.gather_csr_rows, so float
// accumulation downstream sees the same addition order.  c1/o1 may be
// null (unweighted).  Returns elements written.
EXPORT int64_t dp_csr_gather(const int64_t *indptr, int64_t n_rows,
                             const int64_t *ids, int64_t m,
                             const int32_t *c0, const float *c1,
                             int32_t *o0, float *o1) {
    int64_t at = 0;
    for (int64_t i = 0; i < m; ++i) {
        int64_t id = ids[i];
        if (id < 0 || id >= n_rows) continue;
        int64_t a = indptr[id], b = indptr[id + 1];
        if (b <= a) continue;
        int64_t len = b - a;
        memcpy(o0 + at, c0 + a, (size_t)len * sizeof(int32_t));
        if (c1 != nullptr) memcpy(o1 + at, c1 + a, (size_t)len * sizeof(float));
        at += len;
    }
    return at;
}

// Ascending unique of int32 values: out must hold n; returns the unique
// count (np.unique parity: same sorted unique set).
EXPORT int64_t dp_unique_i32(const int32_t *in, int64_t n, int32_t *out) {
    if (n == 0) return 0;
    memcpy(out, in, (size_t)n * sizeof(int32_t));
    std::sort(out, out + n);
    return std::unique(out, out + n) - out;
}

// One event type's score accumulation over the compacted candidate
// space, bit-exact vs the numpy oracle:
//   rel = np.searchsorted(cand, rows)           (lower_bound)
//   score = np.bincount(rel, weights=w)         (float64 accumulate in
//                                                input order) or counts
//   score = score.astype(np.float32)
//   score *= weight (float32 math) when weight != 1.0
//   out = score (first) or out += score (float32 adds)
// scratch is a caller-provided float64[nc] workspace.
EXPORT void dp_score_accum(const int32_t *cand, int64_t nc, const int32_t *rows,
                           int64_t n, const float *w, float weight,
                           double *scratch, float *out, int first) {
    memset(scratch, 0, (size_t)nc * sizeof(double));
    if (w != nullptr) {
        for (int64_t i = 0; i < n; ++i) {
            int64_t rel = std::lower_bound(cand, cand + nc, rows[i]) - cand;
            scratch[rel] += (double)w[i];
        }
    } else {
        for (int64_t i = 0; i < n; ++i) {
            int64_t rel = std::lower_bound(cand, cand + nc, rows[i]) - cand;
            scratch[rel] += 1.0;
        }
    }
    for (int64_t jj = 0; jj < nc; ++jj) {
        float s = (float)scratch[jj];
        if (weight != 1.0f) s = s * weight;
        out[jj] = first ? s : out[jj] + s;
    }
}

// Top-k of a float32 vector under host_topk_desc's total order: the
// composite int64 key — float's monotone int32 image (sign-magnitude →
// two's-complement) in the high word, descending index in the low
// word — makes every key distinct, so (value desc, index asc) order is
// deterministic including -0.0 < +0.0 and k-th boundary ties.
EXPORT void dp_topk_f32(const float *s, int64_t n, int64_t k, float *out_vals,
                        int32_t *out_idx) {
    if (k > n) k = n;
    if (k <= 0) return;
    std::vector<int64_t> keys((size_t)n);
    for (int64_t i = 0; i < n; ++i) {
        int32_t bits;
        memcpy(&bits, &s[i], 4);
        int32_t m = bits >> 31;
        m &= 0x7FFFFFFF;
        bits ^= m;
        keys[(size_t)i] = ((int64_t)bits << 32) + (0xFFFFFFFFLL - i);
    }
    auto desc = std::greater<int64_t>();
    if (k < n) std::nth_element(keys.begin(), keys.begin() + k, keys.end(), desc);
    std::sort(keys.begin(), keys.begin() + k, desc);
    for (int64_t j = 0; j < k; ++j) {
        int64_t idx = 0xFFFFFFFFLL - (keys[(size_t)j] & 0xFFFFFFFFLL);
        out_idx[j] = (int32_t)idx;
        out_vals[j] = s[idx];
    }
}

// -- serve core: HTTP request-head parse / response assembly -----------------

namespace {

// Python str.strip()'s whitespace set restricted to latin-1: the exact
// byte values `.decode("latin-1").strip()` removes — parity with the
// oracle parser requires this set, not isspace().
inline bool py_space(unsigned char c) {
    return (c >= 0x09 && c <= 0x0D) || (c >= 0x1C && c <= 0x1F) || c == 0x20 ||
           c == 0x85 || c == 0xA0;
}

inline unsigned char ascii_lower(unsigned char c) {
    return (c >= 'A' && c <= 'Z') ? (unsigned char)(c + 32) : c;
}

// ascii-case-insensitive equality vs a lowercase ascii literal.  A name
// equals "content-length" after Python's latin-1 .lower() iff it equals
// it after ascii-lower (non-ascii letters can never map into ascii).
inline bool name_is(const unsigned char *s, int64_t n, const char *lit) {
    for (int64_t i = 0; i < n; ++i) {
        if (lit[i] == 0 || ascii_lower(s[i]) != (unsigned char)lit[i]) return false;
    }
    return lit[n] == 0;
}

}  // namespace

// Parse one HTTP/1.1 request head (the bytes BEFORE the \r\n\r\n
// terminator, stray leading CRLFs already stripped by the caller).
//
// Returns 0 ok, or the refusal case — numbered to match the Python
// parser's refusals exactly, first-error-wins in the same order:
//   1 malformed request line          (400)
//   2 too many headers                (400)
//   3 obsolete header line folding    (400)
//   4 conflicting Content-Length      (400)
//   5 Transfer-Encoding present       (501)
//   6 bad Content-Length              (400)
//
// out[0] = n_headers
// out[1..6] = cmd_off, cmd_len, path_off, path_len, ver_off, ver_len
// out[7] = content-length state: 0 absent, 1 valid (value in out[8])
// out[8] = content-length value (saturated ~4.6e18)
// spans: 4 int32 per header — name_off, name_len, value_off, value_len
//        (strip bounds applied; name NOT lowercased — the wrapper's
//        latin-1 .lower() matches the oracle exactly)
EXPORT int dp_http_parse(const unsigned char *buf, int64_t len,
                         int64_t max_headers, int64_t *out, int32_t *spans) {
    // split on exact CRLF pairs (bytes.split(b"\r\n") parity)
    int64_t line_start[2] = {0, 0};  // current line bounds while scanning
    int64_t n_lines = 0;

    // request line: first CRLF (or end)
    int64_t l0_end = len;
    for (int64_t i = 0; i + 1 < len; ++i) {
        if (buf[i] == '\r' && buf[i + 1] == '\n') { l0_end = i; break; }
    }
    // command/path/version: need >= 2 spaces (split(" ", 2) into 3)
    int64_t sp1 = -1, sp2 = -1;
    for (int64_t i = 0; i < l0_end; ++i) {
        if (buf[i] == ' ') {
            if (sp1 < 0) sp1 = i;
            else { sp2 = i; break; }
        }
    }
    if (sp1 < 0 || sp2 < 0) return 1;
    out[1] = 0; out[2] = sp1;
    out[3] = sp1 + 1; out[4] = sp2 - sp1 - 1;
    out[5] = sp2 + 1; out[6] = l0_end - sp2 - 1;

    // count header lines first (the Python parser checks the cap before
    // walking the headers)
    int64_t count = 0;
    for (int64_t i = l0_end; i + 1 < len; ++i) {
        if (buf[i] == '\r' && buf[i + 1] == '\n') { ++count; ++i; }
    }
    if (count > max_headers) return 2;

    int64_t n_headers = 0;
    int64_t cl_off = -1, cl_len = -1;   // last content-length value span
    bool te_seen = false;
    int64_t pos = l0_end + 2;
    (void)line_start;
    while (pos <= len) {
        if (pos >= len) break;
        int64_t lend = len;
        for (int64_t i = pos; i + 1 < len; ++i) {
            if (buf[i] == '\r' && buf[i + 1] == '\n') { lend = i; break; }
        }
        int64_t llen = lend - pos;
        if (llen > 0 && (buf[pos] == ' ' || buf[pos] == '\t')) return 3;
        // partition at first ':'
        int64_t colon = lend;
        for (int64_t i = pos; i < lend; ++i) {
            if (buf[i] == ':') { colon = i; break; }
        }
        int64_t ns = pos, ne = colon;
        while (ns < ne && py_space(buf[ns])) ++ns;
        while (ne > ns && py_space(buf[ne - 1])) --ne;
        int64_t vs = colon < lend ? colon + 1 : lend, ve = lend;
        while (vs < ve && py_space(buf[vs])) ++vs;
        while (ve > vs && py_space(buf[ve - 1])) --ve;
        if (name_is(buf + ns, ne - ns, "content-length")) {
            if (cl_off >= 0) {
                // repeated differing Content-Length (bytewise compare of
                // the stripped latin-1 values == the oracle's str compare)
                if (cl_len != ve - vs ||
                    memcmp(buf + cl_off, buf + vs, (size_t)cl_len) != 0)
                    return 4;
            }
            cl_off = vs;
            cl_len = ve - vs;
        } else if (name_is(buf + ns, ne - ns, "transfer-encoding")) {
            te_seen = true;
        }
        spans[n_headers * 4 + 0] = (int32_t)ns;
        spans[n_headers * 4 + 1] = (int32_t)(ne - ns);
        spans[n_headers * 4 + 2] = (int32_t)vs;
        spans[n_headers * 4 + 3] = (int32_t)(ve - vs);
        ++n_headers;
        if (lend >= len) break;
        pos = lend + 2;
        if (pos == len) {
            // head ended exactly on a CRLF: split() yields a trailing ""
            // line, which the oracle records as an empty-name header
            spans[n_headers * 4 + 0] = (int32_t)len;
            spans[n_headers * 4 + 1] = 0;
            spans[n_headers * 4 + 2] = (int32_t)len;
            spans[n_headers * 4 + 3] = 0;
            ++n_headers;
            break;
        }
    }
    out[0] = n_headers;
    if (te_seen) return 5;
    if (cl_off < 0) {
        out[7] = 0;
        out[8] = 0;
    } else {
        if (cl_len <= 0) return 6;
        int64_t v = 0;
        for (int64_t i = 0; i < cl_len; ++i) {
            unsigned char c = buf[cl_off + i];
            if (c < '0' || c > '9') return 6;
            if (v < (int64_t)460000000000000000LL) v = v * 10 + (c - '0');
        }
        out[7] = 1;
        out[8] = v;
    }
    return 0;
}

// Assemble one response into a caller-sized buffer:
//   prefix | "X-Request-ID: " rid "\r\n" (when ridlen) |
//   "Content-Length: <blen>\r\n" | tail | body
// Returns bytes written, or -1 when cap is too small.
EXPORT int64_t dp_http_assemble(const unsigned char *prefix, int64_t plen,
                                const unsigned char *rid, int64_t ridlen,
                                const unsigned char *tail, int64_t tlen,
                                const unsigned char *body, int64_t blen,
                                unsigned char *outbuf, int64_t cap) {
    char clbuf[40];
    int cln = snprintf(clbuf, sizeof(clbuf), "Content-Length: %lld\r\n",
                       (long long)blen);
    int64_t total = plen + (ridlen > 0 ? 14 + ridlen + 2 : 0) + cln + tlen + blen;
    if (total > cap) return -1;
    unsigned char *o = outbuf;
    memcpy(o, prefix, (size_t)plen); o += plen;
    if (ridlen > 0) {
        memcpy(o, "X-Request-ID: ", 14); o += 14;
        memcpy(o, rid, (size_t)ridlen); o += ridlen;
        memcpy(o, "\r\n", 2); o += 2;
    }
    memcpy(o, clbuf, (size_t)cln); o += cln;
    memcpy(o, tail, (size_t)tlen); o += tlen;
    if (blen > 0) memcpy(o, body, (size_t)blen); o += blen;
    return total;
}
