"""Shared lazy in-tree build for the native cores.

One helper both bindings modules (``scanner``, ``core``) go through: the
``.so`` artifact under ``native/_build`` is keyed by a SHA-256 of the
C++ source *content* — an mtime key can silently serve a stale library
after a checkout, a copy, or an edit that lands in the same clock
second, and a stale data-plane core is a parity bug, not a perf bug.

``scripts/build_native.sh`` calls :func:`build` eagerly; everything else
builds lazily on first use and degrades to the pure-Python path when no
toolchain exists (``compiler()`` is None).
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import shutil
import subprocess
from pathlib import Path
from typing import Optional

log = logging.getLogger("pio.native")

BUILD_DIR = Path(__file__).parent / "_build"

_CXX_CANDIDATES = ("g++", "c++", "clang++")


def compiler() -> Optional[str]:
    """First available C++ compiler on PATH, or None (no toolchain)."""
    for cxx in _CXX_CANDIDATES:
        if shutil.which(cxx):
            return cxx
    return None


def source_key(src: Path) -> str:
    """Content hash of ``src`` — the build-cache key (first 16 hex
    chars: enough to never collide between edits of one file)."""
    return hashlib.sha256(src.read_bytes()).hexdigest()[:16]


def artifact_path(src: Path, stem: str) -> Path:
    return BUILD_DIR / f"{stem}-{source_key(src)}.so"


def build(src: Path, stem: str, timeout: int = 300) -> Path:
    """Compile ``src`` into its content-keyed artifact (no-op when the
    artifact already exists).  Raises on any build failure — callers
    that want graceful degradation wrap this (``load``)."""
    so = artifact_path(src, stem)
    if so.exists():
        return so
    cxx = compiler()
    if cxx is None:
        raise RuntimeError("no C++ compiler on PATH")
    BUILD_DIR.mkdir(exist_ok=True)
    for old in BUILD_DIR.glob(f"{stem}-*.so"):
        old.unlink(missing_ok=True)
    tmp = so.with_suffix(".so.tmp")
    cmd = [cxx, "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           str(src), "-o", str(tmp)]
    subprocess.run(cmd, check=True, capture_output=True, timeout=timeout)
    # rename-into-place: a concurrent builder (two processes racing the
    # first use) never loads a half-written .so
    tmp.replace(so)
    return so


def load(src: Path, stem: str) -> Optional[ctypes.CDLL]:
    """Build-if-needed and dlopen; None when the toolchain is missing or
    the build/load fails (logged once by the caller)."""
    try:
        return ctypes.CDLL(str(build(src, stem)))
    except Exception as e:  # compiler missing, build error, load error
        log.warning("native %s unavailable (%s); using Python path", stem, e)
        return None
