"""ctypes bindings for the native event-log scanner.

Builds ``libeventscan.so`` from eventlog_scanner.cpp on first use via
:mod:`predictionio_tpu.native.build` (artifact keyed by a SHA-256 of the
source *content* — an mtime key could silently serve a stale ``.so``)
and exposes ``scan_segments(paths) -> EventBatch``.  Falls back
gracefully: callers check ``native_available()`` and use the pure-Python
path otherwise.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from pathlib import Path
from typing import List, Optional, Sequence

import numpy as np

from predictionio_tpu.native import build as _native_build

log = logging.getLogger("pio.native")

_SRC = Path(__file__).parent / "eventlog_scanner.cpp"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _load_failed:
            return None
        try:
            lib = ctypes.CDLL(str(_native_build.build(_SRC, "libeventscan")))
            lib.scan_new.restype = ctypes.c_void_p
            lib.scan_add_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.scan_run.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.scan_run.restype = ctypes.c_int64
            lib.scan_rows.argtypes = [ctypes.c_void_p]
            lib.scan_rows.restype = ctypes.c_int64
            lib.scan_error.argtypes = [ctypes.c_void_p]
            lib.scan_error.restype = ctypes.c_char_p
            for name, typ in [
                ("scan_col_event", ctypes.POINTER(ctypes.c_int32)),
                ("scan_col_entity_type", ctypes.POINTER(ctypes.c_int32)),
                ("scan_col_entity", ctypes.POINTER(ctypes.c_int32)),
                ("scan_col_target", ctypes.POINTER(ctypes.c_int32)),
                ("scan_col_time", ctypes.POINTER(ctypes.c_int64)),
                ("scan_col_rating", ctypes.POINTER(ctypes.c_float)),
            ]:
                fn = getattr(lib, name)
                fn.argtypes = [ctypes.c_void_p]
                fn.restype = typ
            lib.scan_dict_size.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.scan_dict_size.restype = ctypes.c_int64
            lib.scan_dict_export.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.scan_dict_export.restype = ctypes.c_int64
            lib.scan_dict_blob.argtypes = [ctypes.c_void_p]
            lib.scan_dict_blob.restype = ctypes.POINTER(ctypes.c_char)
            lib.scan_dict_offsets.argtypes = [ctypes.c_void_p]
            lib.scan_dict_offsets.restype = ctypes.POINTER(ctypes.c_int64)
            lib.scan_prop_count.argtypes = [ctypes.c_void_p]
            lib.scan_prop_count.restype = ctypes.c_int64
            lib.scan_prop_key.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.scan_prop_key.restype = ctypes.POINTER(ctypes.c_char)
            lib.scan_prop_key_len.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.scan_prop_key_len.restype = ctypes.c_int64
            for name, typ in [
                ("scan_prop_rows", ctypes.POINTER(ctypes.c_int64)),
                ("scan_prop_kind", ctypes.POINTER(ctypes.c_int8)),
                ("scan_prop_num", ctypes.POINTER(ctypes.c_double)),
                ("scan_prop_stroffs", ctypes.POINTER(ctypes.c_int64)),
                ("scan_prop_codes", ctypes.POINTER(ctypes.c_int32)),
            ]:
                fn = getattr(lib, name)
                fn.argtypes = [ctypes.c_void_p, ctypes.c_int]
                fn.restype = typ
            for name in ("scan_prop_len", "scan_prop_codes_len",
                         "scan_prop_dict_size", "scan_prop_dict_export"):
                fn = getattr(lib, name)
                fn.argtypes = [ctypes.c_void_p, ctypes.c_int]
                fn.restype = ctypes.c_int64
            lib.layout_width.argtypes = [
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32]
            lib.layout_width.restype = ctypes.c_int64
            lib.layout_fill.argtypes = [
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32)]
            lib.layout_fill.restype = ctypes.c_int32
            lib.scan_free.argtypes = [ctypes.c_void_p]
            _lib = lib
            return lib
        except Exception as e:  # compiler missing, build error, load error
            log.warning("native scanner unavailable (%s); using Python path", e)
            _load_failed = True
            return None


def native_available() -> bool:
    return _build_and_load() is not None


def _export_dict(lib, handle, which: int) -> List[str]:
    n = lib.scan_dict_size(handle, which)
    blob_len = lib.scan_dict_export(handle, which)
    if n <= 0 or blob_len < 0:
        return []
    offsets = np.ctypeslib.as_array(lib.scan_dict_offsets(handle), shape=(n + 1,)).copy()
    blob = ctypes.string_at(lib.scan_dict_blob(handle), blob_len)
    # surrogatepass: JSON may legally carry lone surrogates (Python's own
    # json emits them); anything else malformed falls back to replacement
    return [_decode(blob[offsets[i]:offsets[i + 1]]) for i in range(n)]


def _decode(b: bytes) -> str:
    try:
        return b.decode("utf-8", "surrogatepass")
    except UnicodeDecodeError:
        return b.decode("utf-8", "replace")


def scan_segments(paths: Sequence[os.PathLike], n_threads: int = 0):
    """Parse JSONL event segments into an EventBatch (native path)."""
    from predictionio_tpu.store.columnar import EventBatch, IdDict, PropColumn

    lib = _build_and_load()
    if lib is None:
        raise RuntimeError("native scanner unavailable")
    if n_threads <= 0:
        n_threads = min(os.cpu_count() or 4, 16)
    handle = lib.scan_new()
    try:
        for p in paths:
            lib.scan_add_file(handle, str(p).encode())
        rows = lib.scan_run(handle, n_threads)
        if rows < 0:
            raise RuntimeError(lib.scan_error(handle).decode())

        def col(fn, dtype):
            if rows == 0:
                return np.empty(0, dtype)
            return np.ctypeslib.as_array(fn(handle), shape=(rows,)).astype(dtype, copy=True)

        def arr(ptr, n, dtype):
            if n == 0:
                return np.empty(0, dtype)
            return np.ctypeslib.as_array(ptr, shape=(n,)).astype(dtype, copy=True)

        props = {}
        for k in range(lib.scan_prop_count(handle)):
            key = ctypes.string_at(lib.scan_prop_key(handle, k),
                                   lib.scan_prop_key_len(handle, k))
            n = lib.scan_prop_len(handle, k)
            nc = lib.scan_prop_codes_len(handle, k)
            nd = lib.scan_prop_dict_size(handle, k)
            blob_len = lib.scan_prop_dict_export(handle, k)
            if nd > 0 and blob_len >= 0:
                offsets = np.ctypeslib.as_array(
                    lib.scan_dict_offsets(handle), shape=(nd + 1,)).copy()
                blob = ctypes.string_at(lib.scan_dict_blob(handle), blob_len)
                strings = [_decode(blob[offsets[i]:offsets[i + 1]]) for i in range(nd)]
            else:
                strings = []
            props[_decode(key)] = PropColumn(
                rows=arr(lib.scan_prop_rows(handle, k), n, np.int64),
                kind=arr(lib.scan_prop_kind(handle, k), n, np.int8),
                num=arr(lib.scan_prop_num(handle, k), n, np.float64),
                str_offs=arr(lib.scan_prop_stroffs(handle, k),
                             n + 1 if n else 0, np.int64)
                if n else np.zeros(1, np.int64),
                codes=arr(lib.scan_prop_codes(handle, k), nc, np.int32),
                dict=IdDict.from_state(strings),
            )

        batch = EventBatch(
            event_codes=col(lib.scan_col_event, np.int32),
            entity_type_codes=col(lib.scan_col_entity_type, np.int32),
            entity_ids=col(lib.scan_col_entity, np.int32),
            target_ids=col(lib.scan_col_target, np.int32),
            times_us=col(lib.scan_col_time, np.int64),
            ratings=col(lib.scan_col_rating, np.float32),
            event_dict=IdDict.from_state(_export_dict(lib, handle, 0)),
            entity_type_dict=IdDict.from_state(_export_dict(lib, handle, 1)),
            entity_dict=IdDict.from_state(_export_dict(lib, handle, 2)),
            target_dict=IdDict.from_state(_export_dict(lib, handle, 3)),
            prop_columns=props,
        )
        return batch
    finally:
        lib.scan_free(handle)


def layout_chunks(user, item, chunk: int, n_chunks: int, pad_multiple: int = 8):
    """Chunk-grouped COO layout via the native O(n) counting pass:
    (lu [n_chunks, width], it [n_chunks, width], cnt [n_chunks]).

    Returns None ONLY when the native library is unavailable (callers fall
    back to numpy); invalid input — length mismatch, user ids outside
    [0, chunk*n_chunks) — raises ValueError loudly on this path just as
    callers validate for the numpy path."""
    lib = _build_and_load()
    if lib is None:
        return None
    user = np.ascontiguousarray(user, np.int32)
    item = np.ascontiguousarray(item, np.int32)
    if len(user) != len(item):
        raise ValueError(
            f"user/item length mismatch: {len(user)} vs {len(item)}")
    n = len(user)
    p32 = ctypes.POINTER(ctypes.c_int32)
    u_ptr = user.ctypes.data_as(p32)
    width = lib.layout_width(u_ptr, n, chunk, n_chunks, pad_multiple)
    if width < 0:
        raise ValueError(
            f"user ids outside [0, {chunk * n_chunks}) in layout_chunks")
    lu = np.zeros((n_chunks, int(width)), np.int32)
    it = np.zeros((n_chunks, int(width)), np.int32)
    cnt = np.zeros(n_chunks, np.int32)
    rc = lib.layout_fill(
        u_ptr, item.ctypes.data_as(p32), n, chunk, n_chunks, width,
        lu.ctypes.data_as(p32), it.ctypes.data_as(p32), cnt.ctypes.data_as(p32))
    if rc != 0:
        raise ValueError(f"native layout_fill failed (rc={rc})")
    return lu, it, cnt
