"""``PIO_NATIVE`` knob + ctypes bindings for the GIL-releasing data-plane
cores (``data_plane.cpp``).

Two cores behind ONE knob, same kill-switch discipline as
``PIO_FOLLOW_RELLR_PRUNE`` / ``PIO_MODEL_PLANE_DELTA``:

- ``PIO_NATIVE=auto`` (default): use the native library when it builds
  and loads; silently fall back to the pure-Python oracle otherwise.
- ``PIO_NATIVE=on``: prefer native, and count every denied use as a
  ``pio_native_fallback_total{reason="no_build"}`` so an operator who
  *expected* native can see it never engaged.
- ``PIO_NATIVE=off``: the exact-parity Python oracle, always.

Every call crosses through ``ctypes.CDLL``, which releases the GIL for
the duration of the C call — that is the point: per-shard columnar scans
and concurrent serve-tail queries overlap on real cores instead of
serializing on the interpreter lock.

The library builds lazily on first use (content-hash-keyed artifact via
:mod:`predictionio_tpu.native.build`); with no C++ toolchain every
``*_enabled()`` gate answers False and callers stay on the Python path —
tier-1 must be green either way.

Observability:

- ``pio_native_active``                 gauge, 1 while native is engaged
- ``pio_native_calls_total{core}``      logical native operations served
- ``pio_native_fallback_total{reason}`` Python-path fallbacks and why
  (``no_build`` = wanted but not loadable, counted once per core;
  ``error`` = native raised and the oracle answered; ``unsupported`` =
  input shape the native core declines, e.g. an extension header)
"""

from __future__ import annotations

import ctypes
import os
import threading
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from predictionio_tpu.native import build as _build
from predictionio_tpu.obs import metrics as obs_metrics

_SRC = Path(__file__).parent / "data_plane.cpp"
_STEM = "libdataplane"
_ABI_VERSION = 1

_M_ACTIVE = obs_metrics.get_registry().gauge(
    "pio_native_active",
    "1 while the native data-plane cores are loaded and engaged")
_M_CALLS = obs_metrics.get_registry().counter(
    "pio_native_calls_total",
    "Logical operations served by a native core, by core (scan/serve/http)")
_M_FALLBACK = obs_metrics.get_registry().counter(
    "pio_native_fallback_total",
    "Data-plane operations answered by the Python oracle instead of a "
    "native core, by reason (no_build/error/unsupported)")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False
_no_build_counted: set = set()
_active_state: Optional[bool] = None

_c_p = ctypes.c_void_p
_c_i64 = ctypes.c_int64
_c_i32 = ctypes.c_int32
_c_int = ctypes.c_int
_c_f32 = ctypes.c_float
_c_char_p = ctypes.c_char_p


def mode() -> str:
    """Resolved knob value: "auto" | "on" | "off" (re-read per call, so
    a test or an operator can flip it live)."""
    v = os.environ.get("PIO_NATIVE", "auto").strip().lower()
    if v in ("off", "0", "false", "no"):
        return "off"
    if v in ("on", "1", "true", "yes"):
        return "on"
    return "auto"


def _bind(lib: ctypes.CDLL) -> None:
    """Declare the C ABI (argtypes/restype) once at load."""
    lib.dp_abi_version.restype = _c_i64
    # scan: columnar header
    lib.dp_col_parse.argtypes = [_c_char_p, _c_i64]
    lib.dp_col_parse.restype = _c_p
    lib.dp_col_free.argtypes = [_c_p]
    lib.dp_col_rows.argtypes = [_c_p]
    lib.dp_col_rows.restype = _c_i64
    lib.dp_col_spec.argtypes = [_c_p, _c_int, _c_p]
    lib.dp_col_spec.restype = _c_int
    lib.dp_col_dict_n.argtypes = [_c_p, _c_int]
    lib.dp_col_dict_n.restype = _c_i64
    lib.dp_col_dict_bytes.argtypes = [_c_p, _c_int]
    lib.dp_col_dict_bytes.restype = _c_i64
    lib.dp_col_dict_copy.argtypes = [_c_p, _c_int, _c_p, _c_p]
    lib.dp_col_nprops.argtypes = [_c_p]
    lib.dp_col_nprops.restype = _c_i64
    lib.dp_col_prop_key_bytes.argtypes = [_c_p, _c_i64]
    lib.dp_col_prop_key_bytes.restype = _c_i64
    lib.dp_col_prop_key_copy.argtypes = [_c_p, _c_i64, _c_p]
    lib.dp_col_prop_spec.argtypes = [_c_p, _c_i64, _c_int, _c_p]
    lib.dp_col_prop_spec.restype = _c_int
    lib.dp_col_prop_dict_n.argtypes = [_c_p, _c_i64]
    lib.dp_col_prop_dict_n.restype = _c_i64
    lib.dp_col_prop_dict_bytes.argtypes = [_c_p, _c_i64]
    lib.dp_col_prop_dict_bytes.restype = _c_i64
    lib.dp_col_prop_dict_copy.argtypes = [_c_p, _c_i64, _c_p, _c_p]
    lib.dp_col_meta_span.argtypes = [_c_p, _c_p]
    # scan: dict handles + merge gathers
    lib.dp_dict_new.restype = _c_p
    lib.dp_dict_free.argtypes = [_c_p]
    lib.dp_dict_len.argtypes = [_c_p]
    lib.dp_dict_len.restype = _c_i64
    lib.dp_dict_union.argtypes = [_c_p, _c_char_p, _c_p, _c_i64, _c_p]
    lib.dp_dict_union.restype = _c_i64
    lib.dp_dict_export.argtypes = [_c_p, _c_i64]
    lib.dp_dict_export.restype = _c_i64
    lib.dp_dict_export_blob.argtypes = [_c_p]
    lib.dp_dict_export_blob.restype = _c_p
    lib.dp_dict_export_offs.argtypes = [_c_p]
    lib.dp_dict_export_offs.restype = _c_p
    lib.dp_take_i32.argtypes = [_c_p, _c_i64, _c_p, _c_i64, _c_p, _c_int]
    lib.dp_take_i32.restype = _c_int
    # serve
    lib.dp_csr_gather_size.argtypes = [_c_p, _c_i64, _c_p, _c_i64]
    lib.dp_csr_gather_size.restype = _c_i64
    lib.dp_csr_gather.argtypes = [_c_p, _c_i64, _c_p, _c_i64,
                                  _c_p, _c_p, _c_p, _c_p]
    lib.dp_csr_gather.restype = _c_i64
    lib.dp_unique_i32.argtypes = [_c_p, _c_i64, _c_p]
    lib.dp_unique_i32.restype = _c_i64
    lib.dp_score_accum.argtypes = [_c_p, _c_i64, _c_p, _c_i64, _c_p,
                                   _c_f32, _c_p, _c_p, _c_int]
    lib.dp_topk_f32.argtypes = [_c_p, _c_i64, _c_i64, _c_p, _c_p]
    # http
    lib.dp_http_parse.argtypes = [_c_char_p, _c_i64, _c_i64, _c_p, _c_p]
    lib.dp_http_parse.restype = _c_int
    lib.dp_http_assemble.argtypes = [_c_char_p, _c_i64, _c_char_p, _c_i64,
                                     _c_char_p, _c_i64, _c_char_p, _c_i64,
                                     _c_p, _c_i64]
    lib.dp_http_assemble.restype = _c_i64


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first use; None when no
    toolchain / build failure (callers then stay on the Python path)."""
    global _lib, _lib_tried
    if not _lib_tried:
        with _lock:
            if not _lib_tried:
                loaded = _build.load(_SRC, _STEM)
                if loaded is not None:
                    try:
                        _bind(loaded)
                        if loaded.dp_abi_version() != _ABI_VERSION:
                            loaded = None
                    except Exception:
                        loaded = None
                _lib = loaded
                _lib_tried = True
    return _lib


def reset_for_tests() -> None:
    """Forget the loaded library so a test can simulate a missing
    toolchain (monkeypatching ``build.load``) or force a rebuild."""
    global _lib, _lib_tried, _active_state
    with _lock:
        _lib = None
        _lib_tried = False
        _active_state = None
        _no_build_counted.clear()


def _enabled(core: str) -> bool:
    global _active_state
    m = mode()
    if m == "off":
        if _active_state is not False:
            _active_state = False
            _M_ACTIVE.set(0.0)
        return False
    ok = lib() is not None
    if not ok and core not in _no_build_counted:
        # wanted native (auto/on) but it never loaded: one fallback mark
        # per core per process, not one per call
        _no_build_counted.add(core)
        _M_FALLBACK.inc(reason="no_build")
    if _active_state is not ok:
        _active_state = ok
        _M_ACTIVE.set(1.0 if ok else 0.0)
    return ok


def scan_enabled() -> bool:
    return _enabled("scan")


def serve_enabled() -> bool:
    return _enabled("serve")


def http_enabled() -> bool:
    return _enabled("http")


def note_call(core: str) -> None:
    _M_CALLS.inc(core=core)


def note_fallback(reason: str) -> None:
    _M_FALLBACK.inc(reason=reason)


def _ptr(arr: np.ndarray):
    return _c_p(arr.ctypes.data)


# ---------------------------------------------------------------------------
# scan core wrappers
# ---------------------------------------------------------------------------


class ColumnarHeader:
    """Parsed PIOCOL01 JSON header (native).  ``parse`` returns None when
    the C parser declines the header (unknown extension / corrupt) — the
    caller falls back to ``json.loads``, which either handles it or
    raises the oracle's error."""

    __slots__ = ("_h", "_lib")

    def __init__(self, handle, lib_):
        self._h = handle
        self._lib = lib_

    @classmethod
    def parse(cls, header_bytes: bytes) -> Optional["ColumnarHeader"]:
        L = lib()
        if L is None:
            return None
        h = L.dp_col_parse(header_bytes, len(header_bytes))
        if not h:
            return None
        return cls(h, L)

    def __del__(self):
        try:
            if self._h:
                self._lib.dp_col_free(self._h)
                self._h = None
        except Exception:
            pass

    @property
    def rows(self) -> int:
        return int(self._lib.dp_col_rows(self._h))

    def spec(self, which: int) -> Optional[Tuple[int, int]]:
        """(n, off) of fixed column 0..5, ids blob 6, ids offs 7."""
        out = np.empty(2, np.int64)
        if self._lib.dp_col_spec(self._h, which, _ptr(out)) != 0:
            return None
        return int(out[0]), int(out[1])

    def dict_blob(self, which: int) -> Tuple[bytes, np.ndarray]:
        n = int(self._lib.dp_col_dict_n(self._h, which))
        nb = int(self._lib.dp_col_dict_bytes(self._h, which))
        blob = ctypes.create_string_buffer(nb if nb else 1)
        offs = np.empty(n + 1, np.int64)
        self._lib.dp_col_dict_copy(self._h, which, blob, _ptr(offs))
        return blob.raw[:nb], offs

    @property
    def nprops(self) -> int:
        return int(self._lib.dp_col_nprops(self._h))

    def prop_key(self, i: int) -> str:
        nb = int(self._lib.dp_col_prop_key_bytes(self._h, i))
        buf = ctypes.create_string_buffer(nb if nb else 1)
        self._lib.dp_col_prop_key_copy(self._h, i, buf)
        return buf.raw[:nb].decode("utf-8", "surrogatepass")

    def prop_spec(self, i: int, which: int) -> Optional[Tuple[int, int]]:
        """(n, off): 0 rows, 1 kind, 2 num, 3 str_offs, 4 codes."""
        out = np.empty(2, np.int64)
        if self._lib.dp_col_prop_spec(self._h, i, which, _ptr(out)) != 0:
            return None
        return int(out[0]), int(out[1])

    def prop_dict_blob(self, i: int) -> Tuple[bytes, np.ndarray]:
        n = int(self._lib.dp_col_prop_dict_n(self._h, i))
        nb = int(self._lib.dp_col_prop_dict_bytes(self._h, i))
        blob = ctypes.create_string_buffer(nb if nb else 1)
        offs = np.empty(n + 1, np.int64)
        self._lib.dp_col_prop_dict_copy(self._h, i, blob, _ptr(offs))
        return blob.raw[:nb], offs

    def meta_span(self) -> Optional[Tuple[int, int]]:
        out = np.empty(2, np.int64)
        self._lib.dp_col_meta_span(self._h, _ptr(out))
        if out[0] < 0:
            return None
        return int(out[0]), int(out[1])


class DictHandle:
    """Native string-dictionary union handle (BatchMerger's k-way merge):
    codes assigned in first-appearance order across bulk unions — the
    exact code-assignment order of the Python oracle."""

    __slots__ = ("_h", "_lib")

    def __init__(self):
        L = lib()
        if L is None:
            raise RuntimeError("native library unavailable")
        self._lib = L
        self._h = L.dp_dict_new()

    def __del__(self):
        try:
            if self._h:
                self._lib.dp_dict_free(self._h)
                self._h = None
        except Exception:
            pass

    def __len__(self) -> int:
        return int(self._lib.dp_dict_len(self._h))

    def union(self, blob: bytes, offs: np.ndarray) -> Tuple[np.ndarray, int]:
        """Bulk-union n strings; → (int32 code map [n], n_new)."""
        n = len(offs) - 1
        out = np.empty(n, np.int32)
        offs = np.ascontiguousarray(offs, np.int64)
        nnew = self._lib.dp_dict_union(self._h, blob, _ptr(offs), n, _ptr(out))
        return out, int(nnew)

    def export(self, start: int) -> Tuple[bytes, np.ndarray]:
        """Strings [start, len) as (utf-8 blob, int64 offsets)."""
        nb = int(self._lib.dp_dict_export(self._h, start))
        if nb < 0:
            raise ValueError("bad export range")
        n = len(self) - start
        blob = ctypes.string_at(self._lib.dp_dict_export_blob(self._h), nb)
        offs = np.ctypeslib.as_array(
            ctypes.cast(self._lib.dp_dict_export_offs(self._h),
                        ctypes.POINTER(ctypes.c_int64)), shape=(n + 1,)).copy()
        return blob, offs


def take_i32(cmap: np.ndarray, codes: np.ndarray, out: np.ndarray,
             sentinel: bool) -> bool:
    """``out[i] = cmap[codes[i]]`` with the GIL dropped; with sentinel,
    negative codes pass through as -1 (the merged target_ids contract).
    False on an out-of-range code — caller re-runs the numpy oracle,
    which raises the identical IndexError."""
    L = lib()
    cmap = np.ascontiguousarray(cmap, np.int32)
    codes = np.ascontiguousarray(codes, np.int32)
    rc = L.dp_take_i32(_ptr(cmap), len(cmap), _ptr(codes), len(codes),
                       _ptr(out), 1 if sentinel else 0)
    return rc == 0


# ---------------------------------------------------------------------------
# serve core wrappers
# ---------------------------------------------------------------------------


def csr_gather(indptr: np.ndarray, ids: np.ndarray, rows: np.ndarray,
               w: Optional[np.ndarray] = None
               ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Native twin of ``models.common.gather_csr_rows`` for the serve
    tail's (int32 rows[, float32 weights]) column shapes — identical
    element order, GIL dropped for both passes."""
    L = lib()
    indptr = np.ascontiguousarray(indptr, np.int64)
    ids = np.ascontiguousarray(ids, np.int64)
    rows = np.ascontiguousarray(rows, np.int32)
    n_rows = len(indptr) - 1
    total = int(L.dp_csr_gather_size(_ptr(indptr), n_rows, _ptr(ids), len(ids)))
    o0 = np.empty(total, np.int32)
    o1 = None
    w_ptr = o1_ptr = None
    if w is not None:
        w = np.ascontiguousarray(w, np.float32)
        o1 = np.empty(total, np.float32)
        w_ptr, o1_ptr = _ptr(w), _ptr(o1)
    if total:
        L.dp_csr_gather(_ptr(indptr), n_rows, _ptr(ids), len(ids),
                        _ptr(rows), w_ptr, _ptr(o0), o1_ptr)
    return o0, o1


def unique_i32(values: np.ndarray) -> np.ndarray:
    """Ascending unique int32 (``np.unique`` parity), GIL dropped."""
    L = lib()
    values = np.ascontiguousarray(values, np.int32)
    out = np.empty(len(values), np.int32)
    n = int(L.dp_unique_i32(_ptr(values), len(values), _ptr(out)))
    return out[:n].copy()


def score_accum(cand: np.ndarray, rows: np.ndarray, w: Optional[np.ndarray],
                weight: float, scratch: np.ndarray, out: np.ndarray,
                first: bool) -> None:
    """One event type's serve-tail score accumulation over the compacted
    candidate space — bit-exact vs searchsorted + float64 bincount +
    f32 cast + f32 weight multiply + f32 total add (see data_plane.cpp)."""
    L = lib()
    rows = np.ascontiguousarray(rows, np.int32)
    w_ptr = None
    if w is not None:
        w = np.ascontiguousarray(w, np.float32)
        w_ptr = _ptr(w)
    L.dp_score_accum(_ptr(cand), len(cand), _ptr(rows), len(rows), w_ptr,
                     _c_f32(weight), _ptr(scratch), _ptr(out),
                     1 if first else 0)


def topk_f32(s: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray]:
    """``host_topk_desc`` for a contiguous float32 vector (same composite
    key, same total order incl. -0.0 and boundary ties), GIL dropped."""
    L = lib()
    k = min(int(k), len(s))
    vals = np.empty(k, np.float32)
    idx = np.empty(k, np.int32)
    if k:
        L.dp_topk_f32(_ptr(s), len(s), k, _ptr(vals), _ptr(idx))
    return vals, idx


# ---------------------------------------------------------------------------
# http core wrappers
# ---------------------------------------------------------------------------

_HTTP_MAX_HEADERS = 100


def http_parse_head(head: bytes) -> Tuple[int, np.ndarray, np.ndarray]:
    """Parse one request head (bytes before the CRLFCRLF) natively.

    → (rc, out int64[9], spans int32[4 per header]); rc numbers the
    oracle's refusals in its exact first-error-wins order (see
    data_plane.cpp); rc 0 is a parsed request."""
    L = lib()
    out = np.empty(9, np.int64)
    # worst case one header per 3 bytes ("a:\r\n" is 4); +2 slots for the
    # request line edge and the trailing-empty-line edge
    max_spans = (len(head) // 3 + 2) * 4
    spans = np.empty(max(max_spans, 8), np.int32)
    rc = L.dp_http_parse(head, len(head), _HTTP_MAX_HEADERS,
                         _ptr(out), _ptr(spans))
    return int(rc), out, spans


def http_assemble(prefix: bytes, request_id: Optional[bytes], tail: bytes,
                  body: bytes) -> Optional[bytearray]:
    """Native response assembly: prefix + optional X-Request-ID line +
    Content-Length line + tail + body, one pre-sized buffer, GIL
    dropped.  Value-equal to the oracle's ``bytes`` join (a bytearray
    compares and sends identically)."""
    L = lib()
    rid = request_id or b""
    cap = len(prefix) + len(rid) + len(tail) + len(body) + 64
    buf = bytearray(cap)
    cbuf = (ctypes.c_char * cap).from_buffer(buf)
    n = L.dp_http_assemble(prefix, len(prefix), rid, len(rid),
                           tail, len(tail), body, len(body),
                           ctypes.addressof(cbuf), cap)
    del cbuf
    if n < 0:
        return None
    del buf[n:]
    return buf
