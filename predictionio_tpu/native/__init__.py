from predictionio_tpu.native.scanner import (  # noqa: F401
    layout_chunks,
    native_available,
    scan_segments,
)
