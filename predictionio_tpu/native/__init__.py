from predictionio_tpu.native.scanner import (  # noqa: F401
    native_available,
    scan_segments,
)
