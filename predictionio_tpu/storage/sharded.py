"""Sharded, replicated event store — scale-out past one node.

The reference's production story is pluggable scale-out storage (HBase /
Elasticsearch: entity-keyed regions, replicated event data).  This backend
reproduces that shape on top of the segment-file machinery every other
subsystem already speaks:

- **Sharding**: entities are hashed (stable CRC32 of entityType+entityId)
  across N shards; each shard is a full ``FSEvents`` store — its own tagged
  group-commit segments, tombstones, and columnar snapshot (PR 3's builder
  runs per shard).  The serving hot path (``find`` by entity) touches ONE
  shard; bulk scans fan out and merge.
- **Replication**: with ``replicas=2`` each shard has two node directories
  (``a``/``b``).  Writes go to the primary; a follower tails the primary's
  group-commit segments byte-for-byte into the replica, acknowledging only
  complete, durable lines (``repl/acked.json``, fsynced).  The group-commit
  leader blocks on that acknowledgement (semi-sync, ``_post_commit`` hook in
  localfs) — so **an acked event is on both nodes by construction**, and a
  SIGKILLed primary / yanked directory cannot lose one.
- **Failover**: when a primary turns unusable (I/O error, missing
  directory), the shard promotes — ``topology.json`` flips primary and bumps
  the epoch (fsynced), writers on the old epoch are fenced at their next
  commit, and the un-acked tail on the old node is healed away when it
  rejoins as the replica (truncated back to the acknowledged offsets seeded
  at promotion).  Ingestion and scans retry once onto the new primary.

Layout::

    <root>/meta, models/           shared metadata (localfs, unsharded)
    <root>/shard_00/topology.json  {"primary": "a"|"b", "epoch": N}
    <root>/shard_00/repl.lock      flock: which process runs the follower
    <root>/shard_00/a/events/...   a full FSEvents tree per node
    <root>/shard_00/b/events/...
    <root>/shard_00/b/repl/acked.json  replicated-offset watermark (+ head
                                       fingerprints), lives on the REPLICA

Configured via the locator: ``PIO_STORAGE_SOURCES_<NAME>_TYPE=sharded``
plus ``_SHARDS=N`` and ``_REPLICAS=1|2``.  Knobs: ``PIO_STORE_ACK_REPLICAS``
(0 = async replication, acks don't wait), ``PIO_STORE_ACK_TIMEOUT_S``,
``PIO_STORE_REPL_POLL_S``.

Delta protocol: ``snapshot_scan`` / ``scan_tail_from`` / ``scan_events_up_to``
namespace per-segment watermarks as ``"<shard>|<segment>"``, so PR 3's
delta staging and PR 8's follow-trainer run unchanged on a sharded store.
"""

from __future__ import annotations

import datetime as _dt
import heapq
import json
import logging
import os
import threading
import time
import zlib
from concurrent.futures import ThreadPoolExecutor
from itertools import islice
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Sequence

from predictionio_tpu.events.event import Event
from predictionio_tpu.obs.metrics import get_registry
from predictionio_tpu.storage import base, localfs
from predictionio_tpu.storage.snapshot import (
    _fsync_write,
    _last_newline_boundary,
)
from predictionio_tpu.store.columnar import BatchMerger, EventBatch

log = logging.getLogger("pio.sharded")

TOPOLOGY = "topology.json"
REPL_LOCK = "repl.lock"
ACKED = "acked.json"
NODES = ("a", "b")

_REG = get_registry()
_M_SHARD_EVENTS = _REG.counter(
    "pio_store_shard_events_total",
    "Events acknowledged into the sharded event store, by shard")
_M_REPL_LAG = _REG.gauge(
    "pio_store_replica_lag_events",
    "Complete event lines on a shard primary not yet acknowledged by its "
    "replica, by shard (0 = fully caught up)")
_M_REPL_BYTES = _REG.counter(
    "pio_store_replicated_bytes_total",
    "Bytes copied from shard primaries to their replicas, by shard")
_M_REPL_HEALS = _REG.counter(
    "pio_store_replica_heals_total",
    "Replica tails truncated back to the acknowledged offset (torn or "
    "un-acked bytes healed away), by shard")
_M_PROMOTIONS = _REG.counter(
    "pio_store_promotions_total",
    "Shard failovers — replica promoted to primary, by shard and reason")
_M_SHARDS = _REG.gauge(
    "pio_store_shards", "Configured shard count of the sharded event store")
_M_SCAN_SHARD_S = _REG.gauge(
    "pio_store_scan_shard_duration_seconds",
    "Per-shard scan+parse wall seconds of the last cross-shard merged "
    "scan, by shard — the scan pipeline's straggler view")
_M_SCAN_WORKERS = _REG.gauge(
    "pio_store_scan_workers",
    "Thread-pool width used by the last cross-shard merged scan "
    "(1 = the serial legacy path, the parallel pipeline's parity oracle)")
_M_SCAN_RATE = _REG.gauge(
    "pio_store_scan_merged_events_per_sec",
    "Merged events/second over the last cross-shard merged cold scan "
    "(per-shard fan-out + k-way merge, wall clock)")


def shard_of(entity_type: str, entity_id: str, n: int) -> int:
    """Stable entity → shard routing (CRC32, process-independent — the
    reference's HBase rowkey-prefix partitioning analogue)."""
    if n <= 1:
        return 0
    key = f"{entity_type}\x00{entity_id}".encode("utf-8", "surrogatepass")
    return zlib.crc32(key) % n


def _ack_replicas() -> int:
    """PIO_STORE_ACK_REPLICAS: replicas that must acknowledge a group
    commit before its events are acked to clients (semi-sync).  0 = async
    replication — acks return on the primary write alone, trading the
    zero-acked-loss guarantee for latency."""
    try:
        return int(os.environ.get("PIO_STORE_ACK_REPLICAS", "1"))
    except ValueError:
        return 1


def _ack_timeout() -> float:
    try:
        return float(os.environ.get("PIO_STORE_ACK_TIMEOUT_S", "10"))
    except ValueError:
        return 10.0


def _poll_s() -> float:
    try:
        return float(os.environ.get("PIO_STORE_REPL_POLL_S", "0.05"))
    except ValueError:
        return 0.05


def _scan_workers(n_shards: int) -> int:
    """PIO_SCAN_WORKERS: thread-pool width for cross-shard merged scans
    (``snapshot_scan`` / ``scan_tail_from`` / ``scan_events_up_to`` and
    everything riding them — ``find_batches``, delta staging, the
    ``--follow`` bootstrap).  Default ≈ cores, capped at the shard
    count; ``1`` forces the serial legacy path (the parity oracle)."""
    try:
        w = int(os.environ.get("PIO_SCAN_WORKERS", "0") or "0")
    except ValueError:
        w = 0
    if w <= 0:
        w = os.cpu_count() or 1
    return max(1, min(w, n_shards))


class _Fenced(OSError):
    """A writer discovered at commit time that its node lost the primary
    role (epoch moved on) — the group is NACKed and NOT retried with a
    promotion (the topology already changed under us)."""


class _AckTimeout(OSError):
    """The semi-sync barrier expired: the REPLICA failed to acknowledge,
    not the primary.  The group NACKs but must never trigger a failover —
    promoting would install the node that is provably behind (and, when
    the replica's disk is the broken part, ping-pong the primary onto it
    at one ack-timeout per write)."""


class _NodeEvents(localfs.FSEvents):
    """One shard node's event store: a plain FSEvents whose group-commit
    leader runs the shard's replication barrier before acking."""

    def __init__(self, root: Path, writer_tag: Optional[str],
                 node: str, shard: "_Shard"):
        super().__init__(root, writer_tag=writer_tag)
        self._node_name = node
        self._node_root = Path(root)
        self._shard = shard

    def _commit_point(self, key: tuple, writer):
        # fstat, not tell(): segments are opened in text mode and the
        # write was flushed inside append(), so st_size is the exact
        # committed byte offset
        return (writer._path, os.fstat(writer._f.fileno()).st_size)

    def _post_commit(self, key: tuple, info) -> None:
        self._shard.after_commit(self._node_name, info[0], info[1])


class _ShardFollower:
    """Replication worker for one shard: tails the primary node's segment
    and tombstone files byte-for-byte into the replica node.

    Exactly one process replicates a shard at a time (flock on
    ``repl.lock``); ownership floats — every process's follower thread
    keeps trying the lock, so a SIGKILLed owner's role is picked up by any
    survivor.  Only complete lines are copied, and an offset is
    acknowledged (fsynced into ``repl/acked.json`` on the replica, with a
    head fingerprint against recreated files) only after the bytes are
    durably on the replica — the offset the semi-sync commit barrier
    waits on."""

    def __init__(self, shard: "_Shard"):
        self.shard = shard
        self.cond = threading.Condition()
        self._stop = False
        self._lockf = None
        self._owned = False
        self._acked: Dict[str, dict] = {}
        self._acked_node: Optional[str] = None
        self._dirty = False   # in-memory acked state not yet persisted
        # state as of the last durable _save: what the commit barrier
        # waits on (the docstring contract — an ack means the offset is
        # fsynced in repl/acked.json, not merely advanced in memory)
        self._saved: Dict[str, dict] = {}
        self._lag_cache: Optional[tuple] = None   # (monotonic, value)
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"pio-repl-shard{shard.index}")
        self._thread.start()

    # -- lifecycle -----------------------------------------------------------

    def kick(self) -> None:
        with self.cond:
            self.cond.notify_all()

    def stop(self) -> None:
        self._stop = True
        self.kick()
        self._thread.join(timeout=5)
        if self._lockf is not None:
            try:
                self._lockf.close()   # releases the flock
            except OSError:
                pass
            self._lockf = None
            self._owned = False

    def _try_own(self) -> bool:
        if self._owned:
            return True
        import fcntl

        lockf = None
        try:
            self.shard.root.mkdir(parents=True, exist_ok=True)
            lockf = open(self.shard.root / REPL_LOCK, "a")
            fcntl.flock(lockf.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            if lockf is not None:
                lockf.close()
            return False
        self._lockf = lockf
        self._owned = True
        return True

    def _run(self) -> None:
        while not self._stop:
            with self.cond:
                self.cond.wait(_poll_s())
            if self._stop:
                break
            try:
                if self._try_own():
                    self.sync()
            except Exception:
                log.warning("replica sync failed for shard %d",
                            self.shard.index, exc_info=True)

    # -- acked-offset state (lives on the replica node) ----------------------

    def _state_path(self, replica: str) -> Path:
        return self.shard.node_root(replica) / "repl" / ACKED

    @staticmethod
    def read_state(path: Path) -> Dict[str, dict]:
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            return {}
        out: Dict[str, dict] = {}
        if isinstance(doc, dict):
            for rel, ent in doc.items():
                if isinstance(ent, dict) and "off" in ent:
                    out[str(rel)] = {"off": int(ent["off"]),
                                     "head": ent.get("head")}
        return out

    def _load(self, replica: str) -> None:
        if self._acked_node == replica:
            return
        self._acked = self.read_state(self._state_path(replica))
        self._acked_node = replica
        self._dirty = False   # any unsaved state belonged to the other node
        self._saved = dict(self._acked)

    def _save(self, replica: str) -> None:
        p = self._state_path(replica)
        p.parent.mkdir(parents=True, exist_ok=True)
        _fsync_write(p, json.dumps(self._acked, indent=1, sort_keys=True))
        self._saved = dict(self._acked)

    # -- the copy loop -------------------------------------------------------

    @staticmethod
    def _repl_files(node_root: Path) -> Iterator[Path]:
        evroot = node_root / "events"
        if not evroot.exists():
            return
        for chan in sorted(evroot.glob("app_*/*")):
            if not chan.is_dir():
                continue
            yield from sorted(chan.glob("seg-*.jsonl"))
            yield from sorted(chan.glob("tombstones*.txt"))

    @staticmethod
    def _fd_boundary(f, size: int) -> int:
        """_last_newline_boundary over an already-open handle (the held fd
        stays valid through a concurrent rename/unlink of the path)."""
        pos = size
        while pos > 0:
            step = min(64 * 1024, pos)
            f.seek(pos - step)
            chunk = f.read(step)
            nl = chunk.rfind(b"\n")
            if nl >= 0:
                return pos - step + nl + 1
            pos -= step
        return 0

    @staticmethod
    def _fd_head(f, consumed: int) -> Optional[Dict[str, int]]:
        """_segment_head over an already-open handle."""
        import zlib

        n = min(64, consumed)
        if n <= 0:
            return None
        f.seek(0)
        return {"n": n, "crc": zlib.crc32(f.read(n))}

    def _sync_one(self, f, rel: str, rroot: Path, shard_label: str) -> int:
        """Replicate one open primary file.  Every read goes through the
        held fd ``f``, so a mid-pass partition (the path renamed or
        unlinked underneath us) can neither masquerade as a recreated
        file nor feed us a different generation's bytes — the handle
        pins one file identity for the whole decision.  Returns
        (events copied, caught-up) — caught-up False means acked is
        still behind this file's boundary; mutations mark
        ``self._dirty``."""
        import zlib

        size = os.fstat(f.fileno()).st_size
        end = self._fd_boundary(f, size)
        ent = self._acked.get(rel) or {"off": 0, "head": None}
        acked = int(ent["off"])
        head = ent.get("head")
        if acked and head:
            f.seek(0)
            cur = f.read(int(head["n"]))
            if len(cur) < int(head["n"]) or zlib.crc32(cur) != head["crc"]:
                # the primary file was genuinely recreated under the same
                # name (data-delete + re-import): offsets into it are
                # meaningless — restart this file's replication
                acked = 0
                ent = {"off": 0, "head": None}
                self._dirty = True
        dst = rroot / rel
        try:
            rsize = dst.stat().st_size
        except OSError:
            rsize = 0
        if rsize > acked:
            # un-acked replica bytes (torn copy, or the healed tail of a
            # demoted primary): truncate back to what was acknowledged
            with open(dst, "rb+") as df:
                df.truncate(acked)
            _M_REPL_HEALS.inc(1, shard=shard_label)
            self._dirty = True
        elif rsize < acked:
            # replica lost acknowledged bytes (external tear): fall back
            # to its own last complete line and re-copy
            bnd = _last_newline_boundary(dst, rsize) if rsize else 0
            if bnd < rsize:
                with open(dst, "rb+") as df:
                    df.truncate(bnd)
                _M_REPL_HEALS.inc(1, shard=shard_label)
            acked = bnd
            ent = {"off": bnd, "head": self._fd_head(f, bnd)}
            self._dirty = True
        copied = 0
        if end > acked:
            f.seek(acked)
            data = f.read(end - acked)
            nl = data.rfind(b"\n")
            if nl >= 0:
                data = data[: nl + 1]
                dst.parent.mkdir(parents=True, exist_ok=True)
                with open(dst, "rb+" if dst.exists() else "wb") as df:
                    df.seek(acked)
                    df.write(data)
                    df.flush()
                    if localfs._fsync_policy() == "always":
                        os.fsync(df.fileno())
                copied = data.count(b"\n")
                acked += len(data)
                ent = {"off": acked, "head": self._fd_head(f, acked)}
                _M_REPL_BYTES.inc(len(data), shard=shard_label)
                self._dirty = True
        if ent["off"]:
            self._acked[rel] = ent
        else:
            self._acked.pop(rel, None)
        return copied, acked >= end

    def sync(self) -> int:
        """One primary → replica pass.  Returns events copied."""
        shard = self.shard
        topo = shard.topology()
        primary = topo["primary"]
        replica = "b" if primary == "a" else "a"
        proot = shard.node_root(primary)
        rroot = shard.node_root(replica)
        label = str(shard.index)
        if not proot.exists():
            # primary gone: nothing to tail.  Promotion (not this loop)
            # decides what happens next; never mirror-delete on this path.
            return 0
        self._load(replica)
        copied_events = 0
        caught_up = True
        seen: set = set()
        for src in self._repl_files(proot):
            rel = str(src.relative_to(proot))
            try:
                f = open(src, "rb")
            except OSError:
                # vanished mid-pass (partition / promotion in flight):
                # skip — never touch the replica on evidence we can no
                # longer read.  NOT marked seen, so no mirror-delete.
                caught_up = False
                continue
            seen.add(rel)
            try:
                with f:
                    copied, ok = self._sync_one(f, rel, rroot, label)
                    copied_events += copied
                    caught_up &= ok
            except OSError:
                # one file failing (ENOSPC, dst perms, mid-write yank)
                # must not starve the rest of the pass — or the _save
                caught_up = False
                log.warning("replica sync of %s failed for shard %d",
                            rel, shard.index, exc_info=True)
        # mirror deletions of files we replicated, but ONLY when the
        # channel directory itself is still live on the primary
        # (compaction / tombstone rewrite) — a yanked primary must never
        # cascade deletes into the replica it is about to fail over to
        for rel in [r for r in self._acked if r not in seen]:
            src = proot / rel
            if not src.exists() and src.parent.exists():
                (rroot / rel).unlink(missing_ok=True)
                del self._acked[rel]
                self._dirty = True
        if self._dirty:
            # _dirty survives an aborted earlier pass: the in-memory state
            # may be AHEAD of acked.json (bytes copied, save missed) and a
            # no-op pass must still persist it, or lag_events read from
            # disk reports phantom lag forever
            self._save(replica)
            self._dirty = False
        with self.cond:
            self.cond.notify_all()
        # a clean pass that left every file at its boundary IS lag 0 —
        # don't pay a second full file walk every idle 50 ms poll
        lag = (0 if caught_up
               else self._pending_events(proot, self._acked))
        _M_REPL_LAG.set(lag, shard=label)
        self._lag_cache = (time.monotonic(), lag)
        return copied_events

    def _pending_events(self, proot: Path, state: Dict[str, dict]) -> int:
        lag = 0
        for src in self._repl_files(proot):
            rel = str(src.relative_to(proot))
            try:
                f = open(src, "rb")
            except OSError:
                continue     # vanished mid-walk
            with f:
                try:
                    size = os.fstat(f.fileno()).st_size
                    end = self._fd_boundary(f, size)
                    acked = int((state.get(rel) or {"off": 0})["off"])
                    if end > acked:
                        f.seek(acked)
                        lag += f.read(end - acked).count(b"\n")
                except OSError:
                    continue
        return lag

    def lag_events(self) -> int:
        """Complete primary lines not yet acknowledged by the replica —
        readable from any process (non-owners read the acked file).
        Never mutates ``self._acked``: the owner's sync thread may be
        mid-pass in it concurrently.  Walking every segment per call is
        O(segments) I/O, so results are cached briefly — /stats.json
        scrapes and tight drill polls reuse the sync loop's own figure
        instead of re-opening every file."""
        cached = self._lag_cache
        if cached is not None and time.monotonic() - cached[0] < 0.2:
            return cached[1]
        shard = self.shard
        topo = shard.topology()
        primary = topo["primary"]
        replica = "b" if primary == "a" else "a"
        proot = shard.node_root(primary)
        if not proot.exists():
            return 0
        if self._owned and self._acked_node == replica:
            state = self._acked
        else:
            state = self.read_state(self._state_path(replica))
        lag = self._pending_events(proot, state)
        self._lag_cache = (time.monotonic(), lag)
        return lag

    def wait_acked(self, rel: str, offset: int, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        while True:
            if self._owned:
                # _saved, not _acked: an ack promises the offset is
                # durable in repl/acked.json, and the in-memory dict
                # runs ahead of the end-of-pass save
                acked = self._saved if self._acked_node else {}
            else:
                topo = self.shard.topology()
                replica = "b" if topo["primary"] == "a" else "a"
                acked = self.read_state(self._state_path(replica))
            if int((acked.get(rel) or {"off": 0})["off"]) >= offset:
                return
            if time.monotonic() > deadline:
                raise _AckTimeout(
                    f"shard {self.shard.index}: replica did not acknowledge "
                    f"{rel}@{offset} within {timeout}s — events NACKed "
                    "(semi-sync barrier; set PIO_STORE_ACK_REPLICAS=0 for "
                    "async replication)")
            with self.cond:
                self.cond.wait(0.02)


class _Shard:
    """One hash partition: node directories, topology, follower."""

    def __init__(self, root: Path, index: int, replicas: int,
                 writer_tag: Optional[str]):
        self.root = Path(root)
        self.index = index
        self.replicas = replicas
        self._writer_tag = writer_tag
        self._lock = threading.RLock()
        self._nodes: Dict[str, _NodeEvents] = {}
        self._topo_cache: Optional[tuple] = None
        self.follower = _ShardFollower(self) if replicas >= 2 else None

    def close(self) -> None:
        if self.follower is not None:
            self.follower.stop()

    def node_root(self, name: str) -> Path:
        return self.root / name

    # -- topology ------------------------------------------------------------

    def topology(self, force: bool = False) -> dict:
        p = self.root / TOPOLOGY
        try:
            st = p.stat()
        except OSError:
            st = None
        with self._lock:
            if st is None:
                doc = {"primary": "a", "epoch": 0}
                self.root.mkdir(parents=True, exist_ok=True)
                try:
                    fd = os.open(p, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                    with os.fdopen(fd, "w") as f:
                        f.write(json.dumps(doc, indent=1, sort_keys=True))
                except (FileExistsError, OSError):
                    pass     # another process created it; next stat reads it
                self._topo_cache = None
                return doc
            if (not force and self._topo_cache is not None
                    and self._topo_cache[0] == st.st_mtime_ns):
                return self._topo_cache[1]
            try:
                doc = json.loads(p.read_text())
            except (OSError, json.JSONDecodeError):
                doc = {"primary": "a", "epoch": 0}
            if doc.get("primary") not in NODES:
                doc["primary"] = "a"
            doc["epoch"] = int(doc.get("epoch", 0))
            self._topo_cache = (st.st_mtime_ns, doc)
            return doc

    def active_name(self) -> str:
        return self.topology()["primary"]

    def events(self, name: Optional[str] = None) -> _NodeEvents:
        name = name or self.active_name()
        with self._lock:
            ev = self._nodes.get(name)
            if ev is None:
                ev = self._nodes[name] = _NodeEvents(
                    self.node_root(name), self._writer_tag, name, self)
            return ev

    def promote(self, reason: str,
                expect_epoch: Optional[int] = None) -> dict:
        """Flip primary ↔ replica (epoch bump, fsynced).  Seeds the new
        replica's acked state from the new primary's, so the demoted
        node's un-acked tail is healed away when it rejoins.

        ``expect_epoch`` fences the flip: when the force-read topology
        has already moved past the epoch the caller observed failing,
        another waiter promoted first and this call returns the current
        topology WITHOUT flipping — otherwise N threads unblocked by one
        NACKed group would ping-pong the primary (and the last flip can
        land it back on the node that just failed)."""
        if self.replicas < 2:
            raise OSError(
                f"shard {self.index}: cannot promote without a replica "
                "(replicas=1)")
        with self._lock:
            topo = self.topology(force=True)
            if expect_epoch is not None and topo["epoch"] != expect_epoch:
                return topo
            old = topo["primary"]
            new = "b" if old == "a" else "a"
            if not self.node_root(new).exists():
                raise OSError(
                    f"shard {self.index}: replica node {new!r} has no data "
                    "to promote")
            doc = {
                "primary": new,
                "epoch": topo["epoch"] + 1,
                "promotedAt": _dt.datetime.now(
                    _dt.timezone.utc).isoformat(),
                "reason": reason,
            }
            _fsync_write(self.root / TOPOLOGY,
                         json.dumps(doc, indent=1, sort_keys=True))
            self._topo_cache = None
            # seed <old>/repl/acked.json from <new>/repl/acked.json: every
            # byte past those offsets on the demoted node was never
            # acknowledged — the follower truncates it away on re-attach
            src = self.node_root(new) / "repl" / ACKED
            if self.node_root(old).exists():
                try:
                    dst = self.node_root(old) / "repl" / ACKED
                    dst.parent.mkdir(parents=True, exist_ok=True)
                    _fsync_write(
                        dst, src.read_text() if src.exists() else "{}")
                except OSError:
                    pass     # node is unreachable; heal happens on rejoin
            if self.follower is not None:
                with self.follower.cond:
                    self.follower._acked_node = None   # direction flipped
                self.follower.kick()
        _M_PROMOTIONS.inc(1, shard=str(self.index), reason=reason)
        log.warning("shard %d: promoted node %s (epoch %d, reason=%s)",
                    self.index, new, doc["epoch"], reason)
        return doc

    # -- commit barrier ------------------------------------------------------

    def after_commit(self, node: str, path: Path, offset: int) -> None:
        topo = self.topology()
        if topo["primary"] != node:
            raise _Fenced(
                f"shard {self.index}: writer on node {node!r} fenced — no "
                f"longer primary (epoch {topo['epoch']})")
        if self.replicas < 2 or self.follower is None:
            return
        self.follower.kick()
        if _ack_replicas() <= 0:
            return
        rel = str(Path(path).relative_to(self.node_root(node)))
        self.follower.wait_acked(rel, offset, _ack_timeout())

    def wait_replicated(self, node_events: _NodeEvents, path: Path,
                        offset: int) -> None:
        """Synchronous replication of an out-of-band append (tombstones)."""
        if self.replicas < 2 or self.follower is None or _ack_replicas() <= 0:
            return
        self.follower.kick()
        rel = str(Path(path).relative_to(node_events._node_root))
        self.follower.wait_acked(rel, offset, _ack_timeout())

    def lag_events(self) -> int:
        if self.follower is None:
            return 0
        try:
            return self.follower.lag_events()
        except OSError:
            return 0


class ShardedEvents(base.LEvents, base.PEvents):
    """Entity-hashed events across N shards, each optionally replicated.

    Read fan-out rules: entity-targeted ``find`` touches exactly one
    shard; everything else fans out and merges.  Every shard operation
    retries ONCE onto the promoted replica when the primary turns
    unusable mid-call (mid-scan partitions included — re-scanned events
    already yielded are deduped by event id)."""

    def __init__(self, root: Path, shards: int = 1, replicas: int = 1,
                 writer_tag: Optional[str] = None):
        self._root = Path(root)
        self.n_shards = max(1, int(shards))
        self.replicas = max(1, min(2, int(replicas)))
        tag = (writer_tag if writer_tag is not None
               else localfs._env_writer_tag())
        self._shards = [
            _Shard(self._root / f"shard_{k:02d}", k, self.replicas, tag)
            for k in range(self.n_shards)
        ]
        self._pool_lock = threading.Lock()
        self._scan_pool: Optional[ThreadPoolExecutor] = None
        self._scan_pool_size = 0
        _M_SHARDS.set(self.n_shards)

    def close(self) -> None:
        with self._pool_lock:
            if self._scan_pool is not None:
                self._scan_pool.shutdown(wait=False, cancel_futures=True)
                self._scan_pool = None
        for sh in self._shards:
            sh.close()

    def _pool(self, workers: int) -> ThreadPoolExecutor:
        """Persistent scan pool (resized when PIO_SCAN_WORKERS changes):
        the follow-trainer's delta scan runs every tick, so per-scan
        thread spawn/join would tax exactly the path this pipeline
        accelerates."""
        with self._pool_lock:
            if self._scan_pool is None or self._scan_pool_size != workers:
                if self._scan_pool is not None:
                    self._scan_pool.shutdown(wait=False)
                self._scan_pool = ThreadPoolExecutor(
                    max_workers=workers, thread_name_prefix="pio-scan")
                self._scan_pool_size = workers
            return self._scan_pool

    # -- routing / failover --------------------------------------------------

    def shard_for(self, entity_type: str, entity_id: str) -> _Shard:
        return self._shards[
            shard_of(str(entity_type), str(entity_id), self.n_shards)]

    def _failover(self, shard: _Shard) -> bool:
        """Try to promote ``shard``'s replica after an I/O failure on the
        primary.  False = nothing to promote (caller re-raises)."""
        if self.replicas < 2:
            return False
        topo = shard.topology(force=True)
        reason = ("primary-missing"
                  if not shard.node_root(topo["primary"]).exists()
                  else "io-error")
        try:
            # epoch-fenced: if another waiter from the same failed group
            # (or another process) already flipped, this no-ops and the
            # caller's retry lands on the promoted primary
            shard.promote(reason, expect_epoch=topo["epoch"])
            return True
        except OSError:
            return False

    def _ensure_active(self, shard: _Shard) -> None:
        """Health probe before touching a shard: a yanked primary node
        directory doesn't raise — the store just looks EMPTY — so a
        missing-primary-with-live-replica promotes eagerly instead of
        silently serving nothing."""
        if self.replicas < 2:
            return
        topo = shard.topology()
        other = "b" if topo["primary"] == "a" else "a"
        if (not shard.node_root(topo["primary"]).exists()
                and shard.node_root(other).exists()):
            try:
                shard.promote("primary-missing",
                              expect_epoch=topo["epoch"])
            except OSError:
                pass

    def _on_shard(self, shard: _Shard, fn):
        self._ensure_active(shard)
        try:
            return fn(shard.events())
        except _Fenced:
            # topology already flipped under this writer: retry on the
            # NEW primary, never promote back
            return fn(shard.events())
        except _AckTimeout:
            # the REPLICA failed, not the primary: NACK without failover
            # (promoting would install the node that is provably behind)
            raise
        except OSError:
            if not self._failover(shard):
                raise
            return fn(shard.events())

    # -- LEvents -------------------------------------------------------------

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        for shard in self._shards:
            self._on_shard(shard, lambda ev: ev.init(app_id, channel_id))
        return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        removed = False
        for shard in self._shards:
            names = NODES[: self.replicas]
            for name in names:
                try:
                    removed |= shard.events(name).remove(app_id, channel_id)
                except OSError:
                    pass
            if shard.follower is not None:
                shard.follower.kick()
        # the merged cross-shard snapshot under the virtual channel dir
        # describes data that no longer exists (validation would reject
        # it anyway — this just reclaims the disk)
        import shutil

        d = self._chan_dir(app_id, channel_id)
        if d.exists():
            shutil.rmtree(d, ignore_errors=True)
        return removed

    def insert(self, event: Event, app_id: int,
               channel_id: Optional[int] = None) -> str:
        return self.insert_batch([event], app_id, channel_id)[0]

    def insert_batch(self, events: Sequence[Event], app_id: int,
                     channel_id: Optional[int] = None) -> List[str]:
        groups: Dict[int, List[int]] = {}
        for i, e in enumerate(events):
            k = shard_of(e.entity_type, e.entity_id, self.n_shards)
            groups.setdefault(k, []).append(i)
        ids: List[Optional[str]] = [None] * len(events)
        for k, idxs in groups.items():
            sub = [events[i] for i in idxs]
            res = self._on_shard(
                self._shards[k],
                lambda ev, sub=sub: ev.insert_batch(sub, app_id, channel_id))
            _M_SHARD_EVENTS.inc(len(res), shard=str(k))
            for i, eid in zip(idxs, res):
                ids[i] = eid
        return ids  # type: ignore[return-value]

    def insert_json_batch(self, items: Sequence, app_id: int,
                          channel_id: Optional[int] = None) -> List[dict]:
        groups: Dict[int, List[int]] = {}
        for i, item in enumerate(items):
            et = eid = None
            if isinstance(item, dict):
                et, eid = item.get("entityType"), item.get("entityId")
            groups.setdefault(
                shard_of(str(et), str(eid), self.n_shards), []).append(i)
        results: List[Optional[dict]] = [None] * len(items)
        for k, idxs in groups.items():
            sub = [items[i] for i in idxs]
            res = self._on_shard(
                self._shards[k],
                lambda ev, sub=sub: ev.insert_json_batch(
                    sub, app_id, channel_id))
            _M_SHARD_EVENTS.inc(
                sum(1 for r in res if r.get("status") == 201), shard=str(k))
            for i, r in zip(idxs, res):
                results[i] = r
        return results  # type: ignore[return-value]

    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]:
        for shard in self._shards:
            e = self._on_shard(
                shard, lambda ev: ev.get(event_id, app_id, channel_id))
            if e is not None:
                return e
        return None

    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool:
        for shard in self._shards:
            ok = self._on_shard(
                shard, lambda ev: ev.delete(event_id, app_id, channel_id))
            if ok:
                # tombstones bypass the group-commit barrier; replicate
                # synchronously so a failover can't resurrect the event
                ev = shard.events()
                tp = ev._tombstone_path(ev._chan_dir(app_id, channel_id))
                try:
                    size = tp.stat().st_size
                except OSError:
                    size = 0
                shard.wait_replicated(ev, tp, size)
                return True
        return False

    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        limit: Optional[int] = None,
        reversed_order: bool = False,
    ) -> Iterator[Event]:
        kw = dict(
            channel_id=channel_id, start_time=start_time,
            until_time=until_time, entity_type=entity_type,
            entity_id=entity_id, event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id, limit=limit,
            reversed_order=reversed_order)
        if entity_type is not None and entity_id is not None:
            # serving hot path: the entity lives on exactly one shard
            shard = self.shard_for(entity_type, entity_id)
            yield from self._on_shard(
                shard, lambda ev: list(ev.find(app_id, **kw)))
            return
        # k-way merge instead of materialize-all + global re-sort: each
        # shard's find already yields time order AND honors the limit
        # (its top-`limit` is a superset of its share of the global
        # top-`limit`), so the merged stream holds at most
        # shards × limit events and stops at `limit` — a small-limit
        # query no longer pays an O(total events) sort
        parts = [self._on_shard(shard,
                                lambda ev: list(ev.find(app_id, **kw)))
                 for shard in self._shards]
        merged = heapq.merge(
            *parts, key=lambda e: (e.event_time, e.creation_time),
            reverse=reversed_order)
        if limit is not None and limit >= 0:
            merged = islice(merged, limit)
        yield from merged

    def warm_entity_index(self, app_id: int,
                          channel_id: Optional[int] = None) -> None:
        """Pre-build every shard's per-entity serving index (each shard
        is a full localfs store — see FSEvents.warm_entity_index)."""
        for shard in self._shards:
            self._on_shard(
                shard,
                lambda ev: ev.warm_entity_index(app_id, channel_id))

    # -- PEvents -------------------------------------------------------------

    def scan(self, app_id: int, channel_id: Optional[int] = None,
             **filters: Any) -> Iterator[Event]:
        """Streaming fan-out scan.  A shard whose primary dies mid-scan is
        promoted and re-scanned with already-yielded events deduped by
        id, so one scan still sees every surviving event exactly once.
        Unreplicated stores have no failover retry to dedupe against, so
        they stream without the O(events) id set."""
        track = self.replicas >= 2
        for shard in self._shards:
            yielded: set = set()
            retried = False
            while True:
                try:
                    self._ensure_active(shard)
                    for e in shard.events().scan(
                            app_id, channel_id=channel_id, **filters):
                        if track:
                            if e.event_id in yielded:
                                continue
                            yielded.add(e.event_id)
                        yield e
                    break
                except OSError as err:
                    if (isinstance(err, _Fenced) or retried
                            or not self._failover(shard)):
                        raise
                    retried = True

    def segment_paths(self, app_id: int,
                      channel_id: Optional[int] = None) -> List[Path]:
        out: List[Path] = []
        for shard in self._shards:
            out.extend(self._on_shard(
                shard, lambda ev: ev.segment_paths(app_id, channel_id)))
        return out

    def compact(self, app_id: int, channel_id: Optional[int] = None,
                before: Optional[_dt.datetime] = None) -> Dict[str, int]:
        totals = {"kept": 0, "expired": 0, "segments": 0}
        for shard in self._shards:
            res = self._on_shard(
                shard, lambda ev: ev.compact(app_id, channel_id, before))
            for k2 in totals:
                totals[k2] += res.get(k2, 0)
            if shard.follower is not None:
                shard.follower.kick()
        return totals

    def tombstone_state(self, app_id: int,
                        channel_id: Optional[int] = None) -> frozenset:
        dead: set = set()
        for shard in self._shards:
            dead |= set(self._on_shard(
                shard, lambda ev: ev.tombstone_state(app_id, channel_id)))
        return frozenset(dead)

    def _chan_dir(self, app_id: int, channel_id: Optional[int]) -> Path:
        """Store-level channel identity: the staging-cache key, and home
        of the MERGED cross-shard snapshot (``<dir>/snapshot/``).  The
        event log itself lives per shard under
        shard_*/<node>/events/...; this dir holds only the derived
        merged columnar file + manifest (rebuildable at any time via
        ``build_snapshot``)."""
        chan = (localfs.DEFAULT_CHANNEL if channel_id is None
                else f"channel_{channel_id}")
        return self._root / "events" / f"app_{app_id}" / chan

    # -- snapshot / delta protocol (shard-namespaced watermarks) -------------

    def build_snapshot(self, app_id: int,
                      channel_id: Optional[int] = None) -> Dict:
        agg = {"events": 0, "segments": 0, "build_s": 0.0,
               "snapshot": f"{self.n_shards} shard(s)"}
        for shard in self._shards:
            res = self._on_shard(
                shard, lambda ev: ev.build_snapshot(app_id, channel_id))
            agg["events"] += res.get("events", 0)
            agg["segments"] += res.get("segments", 0)
            agg["build_s"] = max(agg["build_s"], res.get("build_s", 0.0))
        agg["merged"] = self._build_merged_snapshot(app_id, channel_id)
        return agg

    # -- merged cross-shard snapshot -----------------------------------------
    #
    # The per-shard snapshots make each SHARD's read mmap-cheap, but a
    # merged cold scan still paid N× the fixed read/validate cost plus a
    # full k-way re-code per scan.  Folding the k-way merge result into
    # ONE columnar file at the store root (under the virtual channel dir
    # — the same two-phase manifest protocol as storage.snapshot) makes
    # the cross-shard cold scan literally a single-shard read again:
    # mmap the merged file, validate each shard's covered byte ranges +
    # head fingerprints, parse only per-shard tails.  Any validation
    # failure (compaction, recreated segments, receded tombstones, shard
    # count change, torn file) falls back to the live parallel fan-out
    # merge, which is always correct.

    def _build_merged_snapshot(self, app_id: int,
                               channel_id: Optional[int]) -> bool:
        from predictionio_tpu.storage import snapshot as _snap
        from predictionio_tpu.store.columnar import write_batch

        if not _snap.enabled() or self.n_shards < 2:
            return False
        # tombstones read BEFORE the scan: a delete landing mid-build is
        # then absent from ``tombstones_applied`` and the next scan's
        # new-dead mask drops it — the reverse order could record a
        # tombstone as applied that the batch never masked
        tombs = self.tombstone_state(app_id, channel_id)
        res = self._fanout_snapshot_scan(app_id, channel_id)
        if res is None or res.get("ids") is None:
            return False
        d = self._chan_dir(app_id, channel_id)
        snap_dir = d / _snap.SNAP_DIR
        snap_dir.mkdir(parents=True, exist_ok=True)
        import fcntl
        import uuid

        lockf = open(snap_dir / _snap.LOCK, "a")
        try:
            try:
                fcntl.flock(lockf.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                return False     # another process's merged build in flight
            for stale in snap_dir.glob("*.tmp*"):
                stale.unlink(missing_ok=True)
            name = f"snap-merged-{uuid.uuid4().hex[:8]}.pioc"
            tmp = snap_dir / (name + f".tmp{os.getpid()}")
            write_batch(tmp, res["batch"], res["ids"],
                        meta={"merged": True, "events": res["events"]})
            tmp.rename(snap_dir / name)
            manifest = {
                "version": 1,
                "merged": True,
                "shards": self.n_shards,
                "snapshot": name,
                "covered": res["watermark"],
                "heads": res["heads"],
                "events": res["events"],
                "tombstones_applied": sorted(tombs),
                "built_at": _dt.datetime.now(
                    _dt.timezone.utc).isoformat(),
            }
            _fsync_write(snap_dir / _snap.MANIFEST, json.dumps(
                manifest, indent=1, sort_keys=True))
            for p in snap_dir.glob("snap-*.pioc"):
                if p.name != name:
                    p.unlink(missing_ok=True)
            return True
        finally:
            lockf.close()

    def _merged_snapshot_scan(self, app_id: int,
                              channel_id: Optional[int]) -> Optional[Dict]:
        """Serve the merged cross-shard snapshot if it still describes
        the live store: one mmap read + per-shard covered-range/head
        validation + tail-only parses.  None = no or stale merged snapshot
        (caller falls back to the live fan-out merge)."""
        from predictionio_tpu.storage import snapshot as _snap
        from predictionio_tpu.store.columnar import read_batch

        if not _snap.enabled():
            return None
        d = self._chan_dir(app_id, channel_id)
        m = _snap.load_manifest(d)
        if m is None or not m.get("merged") \
                or m.get("shards") != self.n_shards:
            return None
        split = self._split_marks(m["covered"], m.get("heads", {}))
        if split is None:
            return None
        per_wm, per_heads = split
        tombs = self.tombstone_state(app_id, channel_id)
        applied = set(m.get("tombstones_applied", ()))
        if applied - tombs:
            return None          # tombstones receded: log was rewritten
        snap_dir = d / _snap.SNAP_DIR
        try:
            batch, ids, _meta = read_batch(snap_dir / m["snapshot"])
        except FileNotFoundError:
            return None
        except (ValueError, OSError):
            _snap._quarantine(snap_dir, m["snapshot"])
            return None
        if ids is None:
            return None
        batch, ids = _snap.drop_tombstoned(batch, ids, tombs - applied)
        snap_events = len(batch)
        # per-shard tails past the merged watermark: serial, encoding
        # straight into the merged batch's dictionaries (tails are the
        # hot-append suffix — usually empty or tiny)
        tails: List[Dict] = []
        wm: Dict[str, int] = {}
        heads: Dict[str, dict] = {}
        tail_events = 0
        for k, shard in enumerate(self._shards):
            res = self._on_shard(
                shard,
                lambda ev, k=k: ev.scan_tail_from(
                    app_id, channel_id, per_wm[k], base=batch,
                    heads=per_heads[k]))
            if res is None:
                return None      # a shard's log moved under the manifest
            tail_events += res["events"]
            if res["events"]:
                tails.append(res)
            for name, off in res["watermark"].items():
                wm[f"{k}|{name}"] = off
            for name, h in (res.get("heads") or {}).items():
                heads[f"{k}|{name}"] = h
        if tails:
            batch = EventBatch.concat(
                [batch] + [t["batch"] for t in tails])
            if all(t.get("ids") is not None for t in tails):
                from predictionio_tpu.store.columnar import EventIdColumn
                ids = EventIdColumn.concat(
                    [ids] + [t["ids"] for t in tails])
            else:
                ids = None
        _snap.record_staged(snap_events, "snapshot")
        _snap.record_staged(tail_events, "tail")
        return {"batch": batch, "ids": ids, "events": len(batch),
                "snap_events": snap_events, "tail_events": tail_events,
                "watermark": wm, "heads": heads}

    def _scan_fanout(self, fn) -> Iterator[tuple]:
        """Run ``fn(k, shard)`` for every shard on a bounded thread pool
        (``PIO_SCAN_WORKERS`` wide) and yield ``(k, result)`` IN SHARD
        ORDER as each result becomes consumable — the consumer (the
        k-way merge) stages completed shards while later shards are
        still parsing.  Failover runs inside the worker (``fn`` wraps
        ``_on_shard``), so a shard partitioned mid-fan-out promotes and
        re-reads on its own thread without disturbing its siblings; a
        shard whose failover fails raises, exactly like the serial
        loop.  At ``workers <= 1`` the shards run inline — the
        bit-exactness oracle the parity tests compare against.
        Per-shard wall time lands on
        ``pio_store_scan_shard_duration_seconds{shard}``."""
        workers = _scan_workers(self.n_shards)
        _M_SCAN_WORKERS.set(workers)

        def timed(k, shard):
            t0 = time.perf_counter()
            res = fn(k, shard)
            return res, time.perf_counter() - t0

        if workers <= 1:
            for k, shard in enumerate(self._shards):
                res, dt = timed(k, shard)
                _M_SCAN_SHARD_S.set(dt, shard=str(k))
                yield k, res
            return
        pool = self._pool(workers)
        futs = [pool.submit(timed, k, shard)
                for k, shard in enumerate(self._shards)]
        try:
            for k, fut in enumerate(futs):
                res, dt = fut.result()
                _M_SCAN_SHARD_S.set(dt, shard=str(k))
                yield k, res
        finally:
            # a consumer that bails early (miss → None, or an exception)
            # must not leave stray shard reads running into a store that
            # may be closing
            for f in futs:
                f.cancel()

    def snapshot_scan(self, app_id: int,
                      channel_id: Optional[int] = None) -> Optional[Dict]:
        """Merged snapshot-or-parse read across shards.  Unlike localfs,
        this never returns None for a healthy store: shards without a
        built columnar snapshot fall back to a full parse of their own
        log — the result always carries a shard-namespaced watermark, so
        delta staging and the follow-trainer work on a sharded store with
        or without per-shard snapshot builds.

        Read strategy, fastest first: (1) the merged cross-shard
        snapshot — one mmap read at single-shard cost, validated per
        shard, tails parsed per shard; (2) the parallel fan-out
        pipeline — per-shard reads on the ``PIO_SCAN_WORKERS`` thread
        pool merged through ONE k-way :class:`BatchMerger` pass (each
        column re-coded at most once) instead of the old serial loop
        with pairwise ``EventBatch.concat`` accumulation (O(shards²)
        copying).  On the fan-out path, row order (shard 0 first, then
        shard 1, ...), merged dictionaries, property columns and
        tombstone filtering are bit-exact vs the ``PIO_SCAN_WORKERS=1``
        serial path."""
        t0 = time.perf_counter()
        res = self._merged_snapshot_scan(app_id, channel_id)
        if res is not None:
            wall = time.perf_counter() - t0
            if wall > 0:
                _M_SCAN_RATE.set(res["events"] / wall)
            return res
        return self._fanout_snapshot_scan(app_id, channel_id)

    def _fanout_snapshot_scan(self, app_id: int,
                              channel_id: Optional[int] = None
                              ) -> Optional[Dict]:
        """The live parallel fan-out + k-way merge (strategy 2)."""
        t0 = time.perf_counter()

        def read(k, shard):
            def go(ev):
                res = ev.snapshot_scan(app_id, channel_id)
                if res is None:
                    res = ev.scan_tail_from(app_id, channel_id, {},
                                            base=None, heads=None)
                return res
            return self._on_shard(shard, go)

        # single-shard stores pass the sole part through untouched — the
        # k-way merge would only re-code what is already one batch
        merger = BatchMerger() if self.n_shards > 1 else None
        sole: Optional[Dict] = None
        wm: Dict[str, int] = {}
        heads: Dict[str, dict] = {}
        snap_events = tail_events = parts = 0
        for k, res in self._scan_fanout(read):
            if res is None:
                return None
            for name, off in res["watermark"].items():
                wm[f"{k}|{name}"] = off
            for name, h in (res.get("heads") or {}).items():
                heads[f"{k}|{name}"] = h
            snap_events += res.get("snap_events", 0)
            tail_events += res.get("tail_events", res.get("events", 0))
            if merger is None:
                sole = res
            else:
                merger.add(res["batch"], res.get("ids"))
            parts += 1
        if not parts:
            return None
        if merger is None:
            batch, ids = sole["batch"], sole.get("ids")
        else:
            batch, ids = merger.finish()
        wall = time.perf_counter() - t0
        if wall > 0:
            _M_SCAN_RATE.set(len(batch) / wall)
        return {"batch": batch, "ids": ids, "events": len(batch),
                "snap_events": snap_events, "tail_events": tail_events,
                "watermark": wm, "heads": heads}

    def _split_marks(self, watermark: Dict[str, int],
                     heads: Optional[Dict]) -> Optional[tuple]:
        per_wm: List[Dict[str, int]] = [dict() for _ in self._shards]
        per_heads: List[Dict[str, dict]] = [dict() for _ in self._shards]
        for key, off in (watermark or {}).items():
            k, sep, name = key.partition("|")
            if not sep or not k.isdigit() or int(k) >= self.n_shards:
                return None     # foreign/stale watermark: full restage
            per_wm[int(k)][name] = off
        for key, h in (heads or {}).items():
            k, sep, name = key.partition("|")
            if not sep or not k.isdigit() or int(k) >= self.n_shards:
                return None
            per_heads[int(k)][name] = h
        return per_wm, per_heads

    def scan_tail_from(self, app_id: int, channel_id: Optional[int],
                       watermark: Dict[str, int], base=None,
                       heads: Optional[Dict] = None) -> Optional[Dict]:
        split = self._split_marks(watermark, heads)
        if split is None:
            return None
        per_wm, per_heads = split

        single = self.n_shards == 1

        def read(k, shard):
            # base=None per shard (multi-shard): a worker-thread builder
            # must never encode into the (shared, mutable) base
            # dictionaries; the k-way merge below re-codes each
            # completed part INTO the base dicts serially, in shard
            # order — same final dict state, same codes, no cross-thread
            # mutation.  A single-shard store is inherently serial, so
            # its one builder encodes straight into the base as before.
            return self._on_shard(
                shard,
                lambda ev, k=k: ev.scan_tail_from(
                    app_id, channel_id, per_wm[k],
                    base=base if single else None,
                    heads=per_heads[k] if heads is not None else None))

        merger = BatchMerger(base=base) if not single else None
        sole: Optional[Dict] = None
        new_wm: Dict[str, int] = {}
        new_heads: Dict[str, dict] = {}
        total = parts = 0
        for k, res in self._scan_fanout(read):
            if res is None:
                return None
            total += res["events"]
            for name, off in res["watermark"].items():
                new_wm[f"{k}|{name}"] = off
            for name, h in (res.get("heads") or {}).items():
                new_heads[f"{k}|{name}"] = h
            if merger is None:
                sole = res
            else:
                merger.add(res["batch"], res.get("ids"))
            parts += 1
        if not parts:
            return None
        if merger is None:
            batch, ids = sole["batch"], sole.get("ids")
        else:
            # with base given the merged tail carries the base's
            # dictionary OBJECTS, so the caller's concat([base, tail])
            # takes the shared-dict fast path — the delta-staging
            # contract
            batch, ids = merger.finish()
        return {"batch": batch, "ids": ids, "events": total,
                "watermark": new_wm, "heads": new_heads}

    def scan_events_up_to(self, app_id: int, channel_id: Optional[int],
                          watermark: Dict[str, int],
                          heads: Optional[Dict] = None) -> Optional[Dict]:
        split = self._split_marks(watermark, heads)
        if split is None:
            return None
        per_wm, per_heads = split

        def read(k, shard):
            return self._on_shard(
                shard,
                lambda ev, k=k: ev.scan_events_up_to(
                    app_id, channel_id, per_wm[k],
                    heads=per_heads[k] if heads is not None else None))

        merger = BatchMerger() if self.n_shards > 1 else None
        sole: Optional[Dict] = None
        total = parts = 0
        for _k, res in self._scan_fanout(read):
            if res is None:
                return None
            total += res["events"]
            if merger is None:
                sole = res
            else:
                merger.add(res["batch"])
            parts += 1
        if not parts:
            return None
        batch = sole["batch"] if merger is None else merger.finish()[0]
        return {"batch": batch, "events": total}

    def snapshot_status(self, app_id: int,
                        channel_id: Optional[int] = None) -> Optional[Dict]:
        per = []
        for shard in self._shards:
            try:
                st = self._on_shard(
                    shard, lambda ev: ev.snapshot_status(app_id, channel_id))
            except OSError:
                st = None
            if st is not None:
                per.append(st)
        if not per:
            return None
        events = sum(s.get("events", 0) for s in per)
        tail = sum(s.get("tailEvents", 0) for s in per)
        total = events + tail
        return {
            "events": events,
            "tailEvents": tail,
            "tailBytes": sum(s.get("tailBytes", 0) for s in per),
            "coverage": (events / total) if total else 1.0,
            "builtAt": max((s.get("builtAt") or "" for s in per),
                           default="") or None,
            "snapshot": f"{len(per)}/{self.n_shards} shard(s)",
            "segmentsCovered": sum(s.get("segmentsCovered", 0) for s in per),
            "shards": self.n_shards,
        }

    def find_batches(
        self,
        app_id: int,
        batch_size: int = 1 << 20,
        **filters: Any,
    ) -> Iterator["EventBatch"]:
        from predictionio_tpu.storage import snapshot as _snap

        plain = {"channel_id", "start_time", "until_time", "entity_type",
                 "event_names"}
        if set(filters) <= plain:
            res = self.snapshot_scan(app_id, filters.get("channel_id"))
            if res is not None:
                yield _snap.apply_filters(
                    res["batch"],
                    event_names=filters.get("event_names"),
                    entity_type=filters.get("entity_type"),
                    start_time=filters.get("start_time"),
                    until_time=filters.get("until_time"))
                return
        yield from super().find_batches(app_id, batch_size=batch_size,
                                        **filters)

    # -- observability -------------------------------------------------------

    def topology_status(self) -> Dict:
        """Shard/replica topology for /stats.json and the failover drill."""
        per = []
        for k, shard in enumerate(self._shards):
            topo = shard.topology()
            lag = shard.lag_events()
            _M_REPL_LAG.set(lag, shard=str(k))
            per.append({
                "shard": k,
                "primary": topo["primary"],
                "epoch": topo["epoch"],
                "replicaLagEvents": lag,
                "promotedAt": topo.get("promotedAt"),
                "reason": topo.get("reason"),
            })
        return {"shards": self.n_shards, "replicas": self.replicas,
                "perShard": per}


class ShardedSource:
    """Storage source of type ``sharded`` (PIO_STORAGE_SOURCES_*_TYPE):
    metadata and model blobs stay on the shared prefix (localfs, one
    copy); event data is sharded (``_SHARDS``) and optionally replicated
    (``_REPLICAS=2``)."""

    def __init__(self, spec: Dict[str, str]):
        root = Path(spec.get("path", ".pio_store"))
        shards = int(spec.get("shards", "1") or "1")
        replicas = int(spec.get("replicas", "1") or "1")
        self.apps = localfs.FSApps(root)
        self.access_keys = localfs.FSAccessKeys(root)
        self.channels = localfs.FSChannels(root)
        self.engine_instances = localfs.FSEngineInstances(root)
        self.engine_manifests = localfs.FSEngineManifests(root)
        self.evaluation_instances = localfs.FSEvaluationInstances(root)
        self.models = localfs.FSModels(root)
        self.events = ShardedEvents(root, shards=shards, replicas=replicas)
