"""In-memory storage backend (test/dev analogue of the reference's embedded
backends used by LEventsSpec/PEventsSpec — SURVEY.md §4)."""

from __future__ import annotations

import datetime as _dt
import threading
import uuid
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from predictionio_tpu.events.event import Event
from predictionio_tpu.storage import base
from predictionio_tpu.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
)


class MemApps(base.Apps):
    def __init__(self):
        self._apps: Dict[int, App] = {}
        self._next = 1
        self._lock = threading.Lock()

    def insert(self, app: App) -> Optional[int]:
        with self._lock:
            if any(a.name == app.name for a in self._apps.values()):
                return None
            if app.id in self._apps or app.id <= 0:
                app.id = self._next
            self._next = max(self._next, app.id) + 1
            self._apps[app.id] = app
            return app.id

    def get(self, app_id: int) -> Optional[App]:
        return self._apps.get(app_id)

    def get_by_name(self, name: str) -> Optional[App]:
        return next((a for a in self._apps.values() if a.name == name), None)

    def get_all(self) -> List[App]:
        return list(self._apps.values())

    def update(self, app: App) -> bool:
        if app.id not in self._apps:
            return False
        self._apps[app.id] = app
        return True

    def delete(self, app_id: int) -> bool:
        return self._apps.pop(app_id, None) is not None


class MemAccessKeys(base.AccessKeys):
    def __init__(self):
        self._keys: Dict[str, AccessKey] = {}

    def insert(self, access_key: AccessKey) -> Optional[str]:
        if not access_key.key:
            access_key.key = AccessKey.generate()
        self._keys[access_key.key] = access_key
        return access_key.key

    def get(self, key: str) -> Optional[AccessKey]:
        return self._keys.get(key)

    def get_by_app_id(self, app_id: int) -> List[AccessKey]:
        return [k for k in self._keys.values() if k.app_id == app_id]

    def delete(self, key: str) -> bool:
        return self._keys.pop(key, None) is not None


class MemChannels(base.Channels):
    def __init__(self):
        self._channels: Dict[int, Channel] = {}
        self._next = 1

    def insert(self, channel: Channel) -> Optional[int]:
        if any(c.name == channel.name and c.app_id == channel.app_id for c in self._channels.values()):
            return None
        channel.id = self._next
        self._next += 1
        self._channels[channel.id] = channel
        return channel.id

    def get(self, channel_id: int) -> Optional[Channel]:
        return self._channels.get(channel_id)

    def get_by_app_id(self, app_id: int) -> List[Channel]:
        return [c for c in self._channels.values() if c.app_id == app_id]

    def delete(self, channel_id: int) -> bool:
        return self._channels.pop(channel_id, None) is not None


class MemEngineInstances(base.EngineInstances):
    def __init__(self):
        self._instances: Dict[str, EngineInstance] = {}

    def insert(self, instance: EngineInstance) -> str:
        if not instance.id:
            instance.id = uuid.uuid4().hex
        self._instances[instance.id] = instance
        return instance.id

    def get(self, instance_id: str) -> Optional[EngineInstance]:
        return self._instances.get(instance_id)

    def update(self, instance: EngineInstance) -> bool:
        if instance.id not in self._instances:
            return False
        self._instances[instance.id] = instance
        return True

    def get_all(self) -> List[EngineInstance]:
        return list(self._instances.values())

    def delete(self, instance_id: str) -> bool:
        return self._instances.pop(instance_id, None) is not None


class MemEngineManifests(base.EngineManifests):
    def __init__(self):
        self._manifests: Dict[Tuple[str, str], EngineManifest] = {}

    def insert(self, manifest: EngineManifest) -> None:
        self._manifests[(manifest.id, manifest.version)] = manifest

    def get(self, manifest_id: str, version: str) -> Optional[EngineManifest]:
        return self._manifests.get((manifest_id, version))

    def get_all(self) -> List[EngineManifest]:
        return list(self._manifests.values())

    def delete(self, manifest_id: str, version: str) -> bool:
        return self._manifests.pop((manifest_id, version), None) is not None


class MemEvaluationInstances(base.EvaluationInstances):
    def __init__(self):
        self._instances: Dict[str, EvaluationInstance] = {}

    def insert(self, instance: EvaluationInstance) -> str:
        if not instance.id:
            instance.id = uuid.uuid4().hex
        self._instances[instance.id] = instance
        return instance.id

    def get(self, instance_id: str) -> Optional[EvaluationInstance]:
        return self._instances.get(instance_id)

    def update(self, instance: EvaluationInstance) -> bool:
        if instance.id not in self._instances:
            return False
        self._instances[instance.id] = instance
        return True

    def get_completed(self) -> List[EvaluationInstance]:
        return [i for i in self._instances.values() if i.status == "EVALCOMPLETED"]


class MemModels(base.Models):
    def __init__(self):
        self._blobs: Dict[str, bytes] = {}

    def insert(self, instance_id: str, blob: bytes) -> None:
        self._blobs[instance_id] = blob

    def get(self, instance_id: str) -> Optional[bytes]:
        return self._blobs.get(instance_id)

    def delete(self, instance_id: str) -> bool:
        return self._blobs.pop(instance_id, None) is not None


class MemEvents(base.LEvents, base.PEvents):
    """Thread-safe in-memory event store keyed by (app_id, channel_id).

    Implements the delta-tail protocol (``scan_tail_from`` /
    ``scan_events_up_to`` / ``tombstone_state``) over the bucket's
    insertion order, so ``pio deploy --follow`` and delta staging work on
    a memory-backed store: the watermark is simply the consumed event
    COUNT (``{"mem": n}``) plus a bucket generation fingerprint in
    ``heads`` — deletes/removes/TTL-trims mutate in place, bump the
    generation, and invalidate every outstanding watermark (callers full
    restage, exactly like a compacted segment log)."""

    def __init__(self):
        self._events: Dict[Tuple[int, Optional[int]], Dict[str, Event]] = {}
        self._gens: Dict[Tuple[int, Optional[int]], int] = {}
        self._lock = threading.Lock()

    def _bucket(self, app_id: int, channel_id: Optional[int]) -> Dict[str, Event]:
        key = (app_id, channel_id)
        with self._lock:
            return self._events.setdefault(key, {})

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        self._bucket(app_id, channel_id)
        return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self._lock:
            key = (app_id, channel_id)
            self._gens[key] = self._gens.get(key, 0) + 1
            removed = self._events.pop(key, None) is not None
        if removed:
            base.notify_append(None)   # bucket gone: invalidate everything
        return removed

    def compact(self, app_id: int, channel_id: Optional[int] = None,
                before=None) -> Dict[str, int]:
        """Deletes are in-place here, so compaction is only the TTL trim
        (interface parity with the segment-file backends)."""
        from predictionio_tpu.events.event import parse_time

        bucket = self._bucket(app_id, channel_id)
        with self._lock:
            if before is None:
                return {"kept": len(bucket), "expired": 0, "segments": 0}
            before = parse_time(before)
            doomed = [k for k, e in bucket.items() if e.event_time < before]
            for k in doomed:
                del bucket[k]
            if doomed:
                gkey = (app_id, channel_id)
                self._gens[gkey] = self._gens.get(gkey, 0) + 1
            out = {"kept": len(bucket), "expired": len(doomed), "segments": 0}
        if doomed:
            base.notify_append(None)   # TTL trim: invalidate everything
        return out

    def insert(self, event: Event, app_id: int, channel_id: Optional[int] = None) -> str:
        bucket = self._bucket(app_id, channel_id)
        with self._lock:
            if event.event_id in bucket:
                # in-place overwrite: neither the count watermark nor the
                # bucket length moves, so bump the generation or
                # outstanding delta-tail watermarks would keep validating
                # against a silently changed prefix
                key = (app_id, channel_id)
                self._gens[key] = self._gens.get(key, 0) + 1
            bucket[event.event_id] = event
        base.notify_append([(event.entity_type, event.entity_id)])
        return event.event_id

    def get(self, event_id: str, app_id: int, channel_id: Optional[int] = None) -> Optional[Event]:
        return self._bucket(app_id, channel_id).get(event_id)

    def delete(self, event_id: str, app_id: int, channel_id: Optional[int] = None) -> bool:
        bucket = self._bucket(app_id, channel_id)
        with self._lock:
            ok = bucket.pop(event_id, None) is not None
            if ok:
                # in-place delete reorders nothing but shrinks the prefix
                # every outstanding count-watermark describes: bump the
                # generation so holders restage instead of double-reading
                key = (app_id, channel_id)
                self._gens[key] = self._gens.get(key, 0) + 1
        if ok:
            base.notify_append(None)   # entity unknown: invalidate all
        return ok

    # -- delta-tail protocol (count watermark + generation fingerprint) ------

    def tombstone_state(self, app_id: int,
                        channel_id: Optional[int] = None) -> frozenset:
        """Deletes are in-place (no tombstone sidecar); the generation
        fingerprint in the watermark heads is what invalidates staging
        caches instead, so the tombstone set is always empty."""
        return frozenset()

    def _tail_state(self, app_id: int, channel_id: Optional[int]):
        with self._lock:
            bucket = self._events.get((app_id, channel_id), {})
            return (list(bucket.values()),
                    self._gens.get((app_id, channel_id), 0))

    @staticmethod
    def _columnar(events: List[Event], base=None):
        """Events → (EventBatch WITH prop_columns, EventIdColumn), via the
        same wire-dict builder the snapshot tail parser uses — fold-mode
        consumers (URFoldState.bootstrap → fold_properties) require
        property columns, which EventBatch.from_events does not carry.
        With ``base`` (the scan_tail_from contract) codes are assigned in
        the base batch's dictionaries, mutated in place, so the fold's
        incremental code-indexed state stays valid across deltas."""
        from predictionio_tpu.storage.snapshot import ColumnarBuilder

        b = ColumnarBuilder(base=base)
        for e in events:
            b.add(e.to_json())
        return b.finish()

    @classmethod
    def _tail_result(cls, events: List[Event], gen: int, total: int,
                     base=None):
        batch, ids = cls._columnar(events, base=base)
        return {
            "batch": batch,
            "ids": ids,
            "events": len(events),
            "watermark": {"mem": total},
            "heads": {"mem": {"gen": gen}},
        }

    def scan_tail_from(self, app_id: int, channel_id: Optional[int],
                       watermark: Dict[str, int], base=None,
                       heads: Optional[Dict] = None) -> Optional[Dict]:
        """Events past the count watermark, or None (full restage) when
        the bucket mutated in place (delete/remove/TTL) since the
        watermark was taken."""
        events, gen = self._tail_state(app_id, channel_id)
        start = int(watermark.get("mem", 0))
        if heads is not None:
            want = (heads.get("mem") or {}).get("gen", 0)
            if want != gen:
                return None
        if start > len(events):
            return None          # bucket shrank under the watermark
        return self._tail_result(events[start:], gen, len(events),
                                 base=base)

    def scan_events_up_to(self, app_id: int, channel_id: Optional[int],
                          watermark: Dict[str, int],
                          heads: Optional[Dict] = None) -> Optional[Dict]:
        """The covered prefix a persisted watermark describes (the
        follow-trainer's crash-restart read), or None when the bucket
        mutated since."""
        events, gen = self._tail_state(app_id, channel_id)
        end = int(watermark.get("mem", 0))
        if heads is not None:
            want = (heads.get("mem") or {}).get("gen", 0)
            if want != gen:
                return None
        if end > len(events):
            return None
        batch, _ = self._columnar(events[:end])
        return {"batch": batch, "events": end}

    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        limit: Optional[int] = None,
        reversed_order: bool = False,
    ) -> Iterator[Event]:
        with self._lock:
            events = list(self._events.get((app_id, channel_id), {}).values())
        events.sort(key=lambda e: (e.event_time, e.creation_time), reverse=reversed_order)
        n = 0
        for e in events:
            if base.match_filters(
                e, start_time, until_time, entity_type, entity_id,
                event_names, target_entity_type, target_entity_id,
            ):
                if limit is not None and 0 <= limit <= n:
                    return
                yield e
                n += 1
