"""SQL storage backend on sqlite3 — the JDBC-backend parity implementation.

The reference ships a complete JDBC alternative backend (SURVEY.md §2:
`data/.../storage/jdbc/JDBC*` via scalikejdbc against PostgreSQL/MySQL):
events, all metadata repositories, and model blobs in one relational store.
This module is the same full surface on the stdlib ``sqlite3`` driver — a
real SQL schema with indexed predicate pushdown for event scans (the
reference's JDBCPEvents builds WHERE clauses the same way), not a JSON-doc
dump.  A ``path`` of ``:memory:`` gives an ephemeral store for tests.

Concurrency: one shared connection guarded by a re-entrant lock (sqlite is
in-process; the REST layer above provides request concurrency), WAL mode for
file databases so readers don't block the ingest path.
"""

from __future__ import annotations

import datetime as _dt
import json
import sqlite3
import threading
import uuid
from typing import Dict, Iterator, List, Optional, Sequence

from predictionio_tpu.events.event import DataMap, Event
from predictionio_tpu.storage import base
from predictionio_tpu.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
)

_EPOCH = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)


def _ts(t: _dt.datetime) -> float:
    if t.tzinfo is None:
        t = t.replace(tzinfo=_dt.timezone.utc)
    return (t - _EPOCH).total_seconds()


def _from_ts(s: float) -> _dt.datetime:
    return _EPOCH + _dt.timedelta(seconds=s)


class SQLClient:
    """Shared sqlite3 connection + schema management for one database."""

    def __init__(self, path: str = ":memory:"):
        self.path = path
        self.conn = sqlite3.connect(path, check_same_thread=False)
        self.lock = threading.RLock()
        self._known_tables: set = set()   # positive existence cache (ingest hot path)
        with self.lock:
            if path != ":memory:":
                self.conn.execute("PRAGMA journal_mode=WAL")
            self.conn.execute("PRAGMA foreign_keys=ON")
            self._create_schema()

    def _create_schema(self) -> None:
        c = self.conn
        c.executescript(
            """
            CREATE TABLE IF NOT EXISTS apps (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                name TEXT UNIQUE NOT NULL,
                description TEXT NOT NULL DEFAULT ''
            );
            CREATE TABLE IF NOT EXISTS access_keys (
                key TEXT PRIMARY KEY,
                app_id INTEGER NOT NULL,
                events TEXT NOT NULL DEFAULT '[]'
            );
            CREATE TABLE IF NOT EXISTS channels (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                name TEXT NOT NULL,
                app_id INTEGER NOT NULL,
                UNIQUE(app_id, name)
            );
            CREATE TABLE IF NOT EXISTS engine_instances (
                id TEXT PRIMARY KEY,
                status TEXT NOT NULL,
                start_time REAL NOT NULL,
                doc TEXT NOT NULL
            );
            CREATE TABLE IF NOT EXISTS evaluation_instances (
                id TEXT PRIMARY KEY,
                status TEXT NOT NULL,
                doc TEXT NOT NULL
            );
            CREATE TABLE IF NOT EXISTS models (
                id TEXT PRIMARY KEY,
                blob BLOB NOT NULL
            );
            CREATE TABLE IF NOT EXISTS engine_manifests (
                id TEXT NOT NULL,
                version TEXT NOT NULL,
                doc TEXT NOT NULL,
                PRIMARY KEY (id, version)
            );
            """
        )
        c.commit()

    # -- per-(app, channel) event tables (reference: JDBCUtils.eventTableName)

    @staticmethod
    def event_table(app_id: int, channel_id: Optional[int]) -> str:
        return f"events_{app_id}" + (f"_{channel_id}" if channel_id else "")

    def init_event_table(self, app_id: int, channel_id: Optional[int]) -> None:
        t = self.event_table(app_id, channel_id)
        with self.lock:
            self._known_tables.add(t)
            self.conn.executescript(
                f"""
                CREATE TABLE IF NOT EXISTS {t} (
                    id TEXT PRIMARY KEY,
                    event TEXT NOT NULL,
                    entity_type TEXT NOT NULL,
                    entity_id TEXT NOT NULL,
                    target_entity_type TEXT,
                    target_entity_id TEXT,
                    properties TEXT NOT NULL,
                    event_time REAL NOT NULL,
                    tags TEXT NOT NULL DEFAULT '[]',
                    pr_id TEXT,
                    creation_time REAL NOT NULL
                );
                CREATE INDEX IF NOT EXISTS {t}_time ON {t}(event_time);
                CREATE INDEX IF NOT EXISTS {t}_entity ON {t}(entity_type, entity_id);
                CREATE INDEX IF NOT EXISTS {t}_event ON {t}(event);
                """
            )
            self.conn.commit()

    def has_event_table(self, app_id: int, channel_id: Optional[int]) -> bool:
        t = self.event_table(app_id, channel_id)
        with self.lock:
            if t in self._known_tables:
                return True
            row = self.conn.execute(
                "SELECT 1 FROM sqlite_master WHERE type='table' AND name=?", (t,)
            ).fetchone()
            if row is not None:
                self._known_tables.add(t)
            return row is not None


class SQLApps(base.Apps):
    def __init__(self, client: SQLClient):
        self.c = client

    def insert(self, app: App) -> Optional[int]:
        with self.c.lock:
            try:
                if app.id and app.id > 0:
                    self.c.conn.execute(
                        "INSERT INTO apps (id, name, description) VALUES (?,?,?)",
                        (app.id, app.name, app.description),
                    )
                    new_id = app.id
                else:
                    cur = self.c.conn.execute(
                        "INSERT INTO apps (name, description) VALUES (?,?)",
                        (app.name, app.description),
                    )
                    new_id = int(cur.lastrowid)
                self.c.conn.commit()
                return new_id
            except sqlite3.IntegrityError:
                # roll back the implicit BEGIN or the shared connection stays
                # inside an open read transaction pinning a stale WAL snapshot
                self.c.conn.rollback()
                return None

    def get(self, app_id: int) -> Optional[App]:
        with self.c.lock:
            row = self.c.conn.execute(
                "SELECT id, name, description FROM apps WHERE id=?", (app_id,)
            ).fetchone()
        return App(*row) if row else None

    def get_by_name(self, name: str) -> Optional[App]:
        with self.c.lock:
            row = self.c.conn.execute(
                "SELECT id, name, description FROM apps WHERE name=?", (name,)
            ).fetchone()
        return App(*row) if row else None

    def get_all(self) -> List[App]:
        with self.c.lock:
            rows = self.c.conn.execute(
                "SELECT id, name, description FROM apps ORDER BY id"
            ).fetchall()
        return [App(*r) for r in rows]

    def update(self, app: App) -> bool:
        with self.c.lock:
            cur = self.c.conn.execute(
                "UPDATE apps SET name=?, description=? WHERE id=?",
                (app.name, app.description, app.id),
            )
            self.c.conn.commit()
        return cur.rowcount > 0

    def delete(self, app_id: int) -> bool:
        with self.c.lock:
            cur = self.c.conn.execute("DELETE FROM apps WHERE id=?", (app_id,))
            self.c.conn.commit()
        return cur.rowcount > 0


class SQLAccessKeys(base.AccessKeys):
    def __init__(self, client: SQLClient):
        self.c = client

    def insert(self, access_key: AccessKey) -> Optional[str]:
        key = access_key.key or AccessKey.generate()
        with self.c.lock:
            try:
                self.c.conn.execute(
                    "INSERT INTO access_keys (key, app_id, events) VALUES (?,?,?)",
                    (key, access_key.app_id, json.dumps(list(access_key.events))),
                )
                self.c.conn.commit()
                return key
            except sqlite3.IntegrityError:
                # roll back the implicit BEGIN or the shared connection stays
                # inside an open read transaction pinning a stale WAL snapshot
                self.c.conn.rollback()
                return None

    def get(self, key: str) -> Optional[AccessKey]:
        with self.c.lock:
            row = self.c.conn.execute(
                "SELECT key, app_id, events FROM access_keys WHERE key=?", (key,)
            ).fetchone()
        return AccessKey(row[0], row[1], json.loads(row[2])) if row else None

    def get_by_app_id(self, app_id: int) -> List[AccessKey]:
        with self.c.lock:
            rows = self.c.conn.execute(
                "SELECT key, app_id, events FROM access_keys WHERE app_id=?", (app_id,)
            ).fetchall()
        return [AccessKey(r[0], r[1], json.loads(r[2])) for r in rows]

    def delete(self, key: str) -> bool:
        with self.c.lock:
            cur = self.c.conn.execute("DELETE FROM access_keys WHERE key=?", (key,))
            self.c.conn.commit()
        return cur.rowcount > 0


class SQLChannels(base.Channels):
    def __init__(self, client: SQLClient):
        self.c = client

    def insert(self, channel: Channel) -> Optional[int]:
        with self.c.lock:
            try:
                if channel.id and channel.id > 0:
                    self.c.conn.execute(
                        "INSERT INTO channels (id, name, app_id) VALUES (?,?,?)",
                        (channel.id, channel.name, channel.app_id),
                    )
                    new_id = channel.id
                else:
                    cur = self.c.conn.execute(
                        "INSERT INTO channels (name, app_id) VALUES (?,?)",
                        (channel.name, channel.app_id),
                    )
                    new_id = int(cur.lastrowid)
                self.c.conn.commit()
                return new_id
            except sqlite3.IntegrityError:
                # roll back the implicit BEGIN or the shared connection stays
                # inside an open read transaction pinning a stale WAL snapshot
                self.c.conn.rollback()
                return None

    def get(self, channel_id: int) -> Optional[Channel]:
        with self.c.lock:
            row = self.c.conn.execute(
                "SELECT id, name, app_id FROM channels WHERE id=?", (channel_id,)
            ).fetchone()
        return Channel(*row) if row else None

    def get_by_app_id(self, app_id: int) -> List[Channel]:
        with self.c.lock:
            rows = self.c.conn.execute(
                "SELECT id, name, app_id FROM channels WHERE app_id=? ORDER BY id",
                (app_id,),
            ).fetchall()
        return [Channel(*r) for r in rows]

    def delete(self, channel_id: int) -> bool:
        with self.c.lock:
            cur = self.c.conn.execute("DELETE FROM channels WHERE id=?", (channel_id,))
            self.c.conn.commit()
        return cur.rowcount > 0


def _ei_doc(i: EngineInstance) -> str:
    return json.dumps(
        {
            "end_time": _ts(i.end_time) if i.end_time else None,
            "engine_id": i.engine_id,
            "engine_version": i.engine_version,
            "engine_variant": i.engine_variant,
            "engine_factory": i.engine_factory,
            "env": i.env,
            "spark_conf": i.spark_conf,
            "data_source_params": i.data_source_params,
            "preparator_params": i.preparator_params,
            "algorithms_params": i.algorithms_params,
            "serving_params": i.serving_params,
        }
    )


def _ei_from_row(iid: str, status: str, start: float, doc: str) -> EngineInstance:
    d = json.loads(doc)
    return EngineInstance(
        id=iid,
        status=status,
        start_time=_from_ts(start),
        end_time=_from_ts(d["end_time"]) if d.get("end_time") is not None else None,
        engine_id=d["engine_id"],
        engine_version=d["engine_version"],
        engine_variant=d["engine_variant"],
        engine_factory=d["engine_factory"],
        env=d.get("env", {}),
        spark_conf=d.get("spark_conf", {}),
        data_source_params=d.get("data_source_params", "{}"),
        preparator_params=d.get("preparator_params", "{}"),
        algorithms_params=d.get("algorithms_params", "[]"),
        serving_params=d.get("serving_params", "{}"),
    )


class SQLEngineInstances(base.EngineInstances):
    def __init__(self, client: SQLClient):
        self.c = client

    def insert(self, instance: EngineInstance) -> str:
        if not instance.id:
            instance.id = uuid.uuid4().hex
        with self.c.lock:
            self.c.conn.execute(
                "INSERT OR REPLACE INTO engine_instances (id, status, start_time, doc)"
                " VALUES (?,?,?,?)",
                (instance.id, instance.status, _ts(instance.start_time), _ei_doc(instance)),
            )
            self.c.conn.commit()
        return instance.id

    def get(self, instance_id: str) -> Optional[EngineInstance]:
        with self.c.lock:
            row = self.c.conn.execute(
                "SELECT id, status, start_time, doc FROM engine_instances WHERE id=?",
                (instance_id,),
            ).fetchone()
        return _ei_from_row(*row) if row else None

    def update(self, instance: EngineInstance) -> bool:
        with self.c.lock:
            cur = self.c.conn.execute(
                "UPDATE engine_instances SET status=?, start_time=?, doc=? WHERE id=?",
                (instance.status, _ts(instance.start_time), _ei_doc(instance), instance.id),
            )
            self.c.conn.commit()
        return cur.rowcount > 0

    def get_all(self) -> List[EngineInstance]:
        with self.c.lock:
            rows = self.c.conn.execute(
                "SELECT id, status, start_time, doc FROM engine_instances"
                " ORDER BY start_time"
            ).fetchall()
        return [_ei_from_row(*r) for r in rows]

    def delete(self, instance_id: str) -> bool:
        with self.c.lock:
            cur = self.c.conn.execute(
                "DELETE FROM engine_instances WHERE id=?", (instance_id,)
            )
            self.c.conn.commit()
        return cur.rowcount > 0


class SQLEngineManifests(base.EngineManifests):
    def __init__(self, client: SQLClient):
        self.c = client

    def insert(self, manifest: EngineManifest) -> None:
        doc = json.dumps(
            {
                "name": manifest.name,
                "description": manifest.description,
                "files": manifest.files,
                "engine_factory": manifest.engine_factory,
            }
        )
        with self.c.lock:
            self.c.conn.execute(
                "INSERT OR REPLACE INTO engine_manifests (id, version, doc) VALUES (?,?,?)",
                (manifest.id, manifest.version, doc),
            )
            self.c.conn.commit()

    @staticmethod
    def _from_row(mid: str, version: str, doc: str) -> EngineManifest:
        d = json.loads(doc)
        return EngineManifest(
            id=mid, version=version, name=d.get("name", mid),
            description=d.get("description", ""), files=d.get("files", []),
            engine_factory=d.get("engine_factory", ""),
        )

    def get(self, manifest_id: str, version: str) -> Optional[EngineManifest]:
        with self.c.lock:
            row = self.c.conn.execute(
                "SELECT id, version, doc FROM engine_manifests WHERE id=? AND version=?",
                (manifest_id, version),
            ).fetchone()
        return self._from_row(*row) if row else None

    def get_all(self) -> List[EngineManifest]:
        with self.c.lock:
            rows = self.c.conn.execute(
                "SELECT id, version, doc FROM engine_manifests"
            ).fetchall()
        return [self._from_row(*r) for r in rows]

    def delete(self, manifest_id: str, version: str) -> bool:
        with self.c.lock:
            cur = self.c.conn.execute(
                "DELETE FROM engine_manifests WHERE id=? AND version=?",
                (manifest_id, version),
            )
            self.c.conn.commit()
        return cur.rowcount > 0


def _evi_doc(i: EvaluationInstance) -> str:
    return json.dumps(
        {
            "start_time": _ts(i.start_time),
            "end_time": _ts(i.end_time) if i.end_time else None,
            "evaluation_class": i.evaluation_class,
            "engine_params_generator_class": i.engine_params_generator_class,
            "env": i.env,
            "evaluator_results": i.evaluator_results,
            "evaluator_results_html": i.evaluator_results_html,
            "evaluator_results_json": i.evaluator_results_json,
        }
    )


class SQLEvaluationInstances(base.EvaluationInstances):
    def __init__(self, client: SQLClient):
        self.c = client

    def insert(self, instance: EvaluationInstance) -> str:
        if not instance.id:
            instance.id = uuid.uuid4().hex
        with self.c.lock:
            self.c.conn.execute(
                "INSERT OR REPLACE INTO evaluation_instances (id, status, doc)"
                " VALUES (?,?,?)",
                (instance.id, instance.status, _evi_doc(instance)),
            )
            self.c.conn.commit()
        return instance.id

    def _from_row(self, iid: str, status: str, doc: str) -> EvaluationInstance:
        d = json.loads(doc)
        return EvaluationInstance(
            id=iid,
            status=status,
            start_time=_from_ts(d["start_time"]),
            end_time=_from_ts(d["end_time"]) if d.get("end_time") is not None else None,
            evaluation_class=d["evaluation_class"],
            engine_params_generator_class=d.get("engine_params_generator_class", ""),
            env=d.get("env", {}),
            evaluator_results=d.get("evaluator_results", ""),
            evaluator_results_html=d.get("evaluator_results_html", ""),
            evaluator_results_json=d.get("evaluator_results_json", ""),
        )

    def get(self, instance_id: str) -> Optional[EvaluationInstance]:
        with self.c.lock:
            row = self.c.conn.execute(
                "SELECT id, status, doc FROM evaluation_instances WHERE id=?",
                (instance_id,),
            ).fetchone()
        return self._from_row(*row) if row else None

    def update(self, instance: EvaluationInstance) -> bool:
        with self.c.lock:
            cur = self.c.conn.execute(
                "UPDATE evaluation_instances SET status=?, doc=? WHERE id=?",
                (instance.status, _evi_doc(instance), instance.id),
            )
            self.c.conn.commit()
        return cur.rowcount > 0

    def get_completed(self) -> List[EvaluationInstance]:
        with self.c.lock:
            rows = self.c.conn.execute(
                "SELECT id, status, doc FROM evaluation_instances WHERE status='EVALCOMPLETED'"
            ).fetchall()
        return [self._from_row(*r) for r in rows]


class SQLModels(base.Models):
    def __init__(self, client: SQLClient):
        self.c = client

    def insert(self, instance_id: str, blob: bytes) -> None:
        with self.c.lock:
            self.c.conn.execute(
                "INSERT OR REPLACE INTO models (id, blob) VALUES (?,?)",
                (instance_id, sqlite3.Binary(blob)),
            )
            self.c.conn.commit()

    def get(self, instance_id: str) -> Optional[bytes]:
        with self.c.lock:
            row = self.c.conn.execute(
                "SELECT blob FROM models WHERE id=?", (instance_id,)
            ).fetchone()
        return bytes(row[0]) if row else None

    def delete(self, instance_id: str) -> bool:
        with self.c.lock:
            cur = self.c.conn.execute("DELETE FROM models WHERE id=?", (instance_id,))
            self.c.conn.commit()
        return cur.rowcount > 0


_EVENT_COLS = (
    "id, event, entity_type, entity_id, target_entity_type, target_entity_id,"
    " properties, event_time, tags, pr_id, creation_time"
)


def _event_from_row(r: tuple) -> Event:
    return Event(
        event=r[1],
        entity_type=r[2],
        entity_id=r[3],
        target_entity_type=r[4],
        target_entity_id=r[5],
        properties=DataMap(json.loads(r[6])),
        event_time=_from_ts(r[7]),
        tags=tuple(json.loads(r[8])),
        pr_id=r[9],
        event_id=r[0],
        creation_time=_from_ts(r[10]),
    )


class SQLEvents(base.LEvents, base.PEvents):
    """Event store with SQL predicate pushdown (reference: JDBCLEvents +
    JDBCPEvents; the WHERE construction mirrors JDBCPEvents.find)."""

    def __init__(self, client: SQLClient):
        self.c = client

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        self.c.init_event_table(app_id, channel_id)
        return True

    def compact(self, app_id: int, channel_id: Optional[int] = None,
                before=None) -> dict:
        """Deletes are in-place in SQL, so compaction is the TTL trim plus
        a VACUUM to reclaim pages (interface parity with segment backends)."""
        from predictionio_tpu.events.event import parse_time

        if not self.c.has_event_table(app_id, channel_id):
            return {"kept": 0, "expired": 0, "segments": 0}
        t = self.c.event_table(app_id, channel_id)
        with self.c.lock:
            expired = 0
            if before is not None:
                before = parse_time(before)
                cur = self.c.conn.execute(
                    f"DELETE FROM {t} WHERE event_time < ?", (_ts(before),))
                expired = cur.rowcount
            kept = self.c.conn.execute(f"SELECT COUNT(*) FROM {t}").fetchone()[0]
            self.c.conn.commit()
            # VACUUM under the shared-connection lock: a concurrent writer's
            # open transaction would otherwise make it raise
            self.c.conn.execute("VACUUM")
        return {"kept": kept, "expired": expired, "segments": 0}

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        if not self.c.has_event_table(app_id, channel_id):
            return False
        t = self.c.event_table(app_id, channel_id)
        with self.c.lock:
            self.c.conn.execute(f"DROP TABLE IF EXISTS {t}")
            self.c.conn.commit()
            self.c._known_tables.discard(t)
        return True

    def insert(self, event: Event, app_id: int, channel_id: Optional[int] = None) -> str:
        if not self.c.has_event_table(app_id, channel_id):
            self.c.init_event_table(app_id, channel_id)
        t = self.c.event_table(app_id, channel_id)
        with self.c.lock:
            self.c.conn.execute(
                f"INSERT OR REPLACE INTO {t} ({_EVENT_COLS}) VALUES (?,?,?,?,?,?,?,?,?,?,?)",
                (
                    event.event_id, event.event, event.entity_type, event.entity_id,
                    event.target_entity_type, event.target_entity_id,
                    json.dumps(dict(event.properties)), _ts(event.event_time),
                    json.dumps(list(event.tags)), event.pr_id, _ts(event.creation_time),
                ),
            )
            self.c.conn.commit()
        return event.event_id

    def insert_batch(
        self, events: Sequence[Event], app_id: int, channel_id: Optional[int] = None
    ) -> List[str]:
        if not self.c.has_event_table(app_id, channel_id):
            self.c.init_event_table(app_id, channel_id)
        t = self.c.event_table(app_id, channel_id)
        rows = [
            (
                e.event_id, e.event, e.entity_type, e.entity_id,
                e.target_entity_type, e.target_entity_id,
                json.dumps(dict(e.properties)), _ts(e.event_time),
                json.dumps(list(e.tags)), e.pr_id, _ts(e.creation_time),
            )
            for e in events
        ]
        with self.c.lock:
            self.c.conn.executemany(
                f"INSERT OR REPLACE INTO {t} ({_EVENT_COLS}) VALUES (?,?,?,?,?,?,?,?,?,?,?)",
                rows,
            )
            self.c.conn.commit()
        return [e.event_id for e in events]

    def get(self, event_id: str, app_id: int, channel_id: Optional[int] = None) -> Optional[Event]:
        if not self.c.has_event_table(app_id, channel_id):
            return None
        t = self.c.event_table(app_id, channel_id)
        with self.c.lock:
            row = self.c.conn.execute(
                f"SELECT {_EVENT_COLS} FROM {t} WHERE id=?", (event_id,)
            ).fetchone()
        return _event_from_row(row) if row else None

    def delete(self, event_id: str, app_id: int, channel_id: Optional[int] = None) -> bool:
        if not self.c.has_event_table(app_id, channel_id):
            return False
        t = self.c.event_table(app_id, channel_id)
        with self.c.lock:
            cur = self.c.conn.execute(f"DELETE FROM {t} WHERE id=?", (event_id,))
            self.c.conn.commit()
        return cur.rowcount > 0

    def _where(
        self,
        start_time=None, until_time=None, entity_type=None, entity_id=None,
        event_names=None, target_entity_type=None, target_entity_id=None,
    ):
        clauses, params = [], []
        if start_time is not None:
            clauses.append("event_time >= ?")
            params.append(_ts(start_time))
        if until_time is not None:
            clauses.append("event_time < ?")
            params.append(_ts(until_time))
        if entity_type is not None:
            clauses.append("entity_type = ?")
            params.append(entity_type)
        if entity_id is not None:
            clauses.append("entity_id = ?")
            params.append(entity_id)
        if event_names is not None:
            names = list(event_names)
            clauses.append(f"event IN ({','.join('?' * len(names))})" if names else "0")
            params.extend(names)
        if target_entity_type is not None:
            clauses.append("target_entity_type = ?")
            params.append(target_entity_type)
        if target_entity_id is not None:
            clauses.append("target_entity_id = ?")
            params.append(target_entity_id)
        return (" WHERE " + " AND ".join(clauses) if clauses else ""), params

    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        limit: Optional[int] = None,
        reversed_order: bool = False,
    ) -> Iterator[Event]:
        if not self.c.has_event_table(app_id, channel_id):
            return iter(())
        t = self.c.event_table(app_id, channel_id)
        where, params = self._where(
            start_time, until_time, entity_type, entity_id,
            event_names, target_entity_type, target_entity_id,
        )
        order = " ORDER BY event_time" + (" DESC" if reversed_order else "")
        lim = f" LIMIT {int(limit)}" if limit is not None and limit >= 0 else ""
        sql = f"SELECT {_EVENT_COLS} FROM {t}{where}{order}{lim}"
        with self.c.lock:
            rows = self.c.conn.execute(sql, params).fetchall()
        return (_event_from_row(r) for r in rows)

    def scan(self, app_id: int, channel_id: Optional[int] = None, **filters) -> Iterator[Event]:
        """Unordered streaming scan for training reads — no ORDER BY, rows
        fetched incrementally from a dedicated cursor."""
        if not self.c.has_event_table(app_id, channel_id):
            return iter(())
        t = self.c.event_table(app_id, channel_id)
        where, params = self._where(**filters)
        sql = f"SELECT {_EVENT_COLS} FROM {t}{where}"

        def gen():
            with self.c.lock:
                cur = self.c.conn.execute(sql, params)
            while True:
                with self.c.lock:
                    rows = cur.fetchmany(8192)
                if not rows:
                    return
                for r in rows:
                    yield _event_from_row(r)

        return gen()


class SQLSource:
    """Storage-locator source: one sqlite database providing every repository."""

    def __init__(self, path: str = ":memory:"):
        client = SQLClient(path)
        self.client = client
        self.apps = SQLApps(client)
        self.access_keys = SQLAccessKeys(client)
        self.channels = SQLChannels(client)
        self.engine_instances = SQLEngineInstances(client)
        self.engine_manifests = SQLEngineManifests(client)
        self.evaluation_instances = SQLEvaluationInstances(client)
        self.models = SQLModels(client)
        self.events = SQLEvents(client)
