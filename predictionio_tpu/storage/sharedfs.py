"""Shared-prefix storage backend — the multi-host system-of-record.

The localfs backend assumes ONE writer process per (app, channel): it
appends to a single active segment and keeps whole-file JSON documents for
metadata, both of which corrupt under concurrent writers on different
hosts.  This backend keeps the same ``base.py`` interfaces (and the same
on-disk event format, so the native scanner and the host-sharded scan
logic run unchanged) but is **object-store-shaped**, targeting a shared
prefix every host can reach (NFS/GCS-fuse/…; reference analogue: the
HBase/Elasticsearch cluster every Spark executor talks to, SURVEY.md §2):

- every write is either a CREATE of a uniquely-named immutable object
  (events, models, instances) or an atomic replace of a record the caller
  logically owns (instance status updates);
- event segments are **per-writer**: ``seg-<host>-<pid>-NNNNN.jsonl`` —
  no cross-writer appends, so any number of event servers / import jobs
  on any number of hosts can ingest concurrently; readers simply list
  ``seg-*.jsonl`` (the glob the localfs scan paths already use);
- tombstones are per-writer too (``tombstones-<writer>.txt``), unioned at
  read time;
- metadata records are one JSON object per file; uniqueness (app/channel
  names) is claimed with O_EXCL creates — the "if-absent PUT" every
  object store offers — instead of read-modify-write of a shared doc.
"""

from __future__ import annotations

import datetime as _dt
import json
import os
import socket
import uuid
import zlib
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from predictionio_tpu.storage import base, localfs
from predictionio_tpu.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
)
from predictionio_tpu.storage.localfs import (
    _atomic_write,
    _ei_from_json,
    _ei_to_json,
)


def writer_id() -> str:
    """Stable per-process writer tag for segment/tombstone names."""
    host = "".join(c if c.isalnum() else "_" for c in socket.gethostname())[:24]
    return f"{host}-{os.getpid()}"


def _create_exclusive(path: Path, text: str) -> bool:
    """If-absent PUT: atomically create ``path`` with ``text``; False if it
    already exists (another host claimed it)."""
    path.parent.mkdir(parents=True, exist_ok=True)
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        return False
    with os.fdopen(fd, "w") as f:
        f.write(text)
    return True


def _safe_name(s: str) -> str:
    """Filesystem-safe record name: readable prefix + collision-proof hash."""
    keep = "".join(c if c.isalnum() or c in "-_" else "_" for c in s)[:48]
    return f"{keep}-{zlib.crc32(s.encode()):08x}"


def _claim_id(ids: "_RecordDir", want: int, owner_name: str) -> int:
    """Claim a numeric id via if-absent creates, probing upward past ids
    other owners hold; idempotent for the same owner (crash-retry safe)."""
    claimed = want
    while not ids.put_new(str(claimed), {"name": owner_name}):
        holder = ids.get(str(claimed))
        if holder and holder.get("name") == owner_name:
            break
        claimed += 1
    return claimed


class _RecordDir:
    """A directory of single-JSON-object records (one file per record)."""

    def __init__(self, d: Path):
        self.d = d

    def put(self, name: str, obj: Dict) -> None:
        self.d.mkdir(parents=True, exist_ok=True)
        _atomic_write(self.d / f"{name}.json", json.dumps(obj, sort_keys=True))

    def put_new(self, name: str, obj: Dict) -> bool:
        return _create_exclusive(self.d / f"{name}.json", json.dumps(obj, sort_keys=True))

    def get(self, name: str) -> Optional[Dict]:
        p = self.d / f"{name}.json"
        if not p.exists():
            return None
        try:
            return json.loads(p.read_text())
        except (json.JSONDecodeError, OSError):
            return None

    def all(self) -> List[Dict]:
        if not self.d.exists():
            return []
        out = []
        for p in sorted(self.d.glob("*.json")):
            try:
                out.append(json.loads(p.read_text()))
            except (json.JSONDecodeError, OSError):
                continue  # racing a concurrent replace
        return out

    def delete(self, name: str) -> bool:
        p = self.d / f"{name}.json"
        try:
            p.unlink()
            return True
        except FileNotFoundError:
            return False


class SharedApps(base.Apps):
    def __init__(self, root: Path):
        self._names = _RecordDir(root / "meta" / "apps" / "by_name")
        self._ids = _RecordDir(root / "meta" / "apps" / "by_id")

    def insert(self, app: App) -> Optional[int]:
        name_key = _safe_name(app.name)
        # two-phase but CRASH-SAFE: phase 1 claims the name (id 0 =
        # incomplete) AND records the wanted id, phase 2 claims the id and
        # finalizes.  A retry after a crash mid-insert finds the incomplete
        # record and resumes phase 2 FROM THE RECORDED want, so concurrent
        # repairers (who may not know the original app.id) converge.
        want = app.id if app.id > 0 else (zlib.crc32(app.name.encode()) % (1 << 30)) + 1
        rec = {"id": 0, "want": want, "name": app.name,
               "description": app.description}
        if not self._names.put_new(name_key, rec):
            existing = self._names.get(name_key)
            if existing is None or existing.get("id"):
                return None  # completed insert by someone else: duplicate
            rec = existing  # resume a wedged insert
            want = int(rec.get("want") or want)
        app_id = _claim_id(self._ids, want, app.name)
        rec["id"] = app.id = app_id
        self._names.put(name_key, rec)
        return app_id

    def _from(self, d: Optional[Dict]) -> Optional[App]:
        if d is None or not d.get("id"):
            return None
        return App(d["id"], d["name"], d.get("description", ""))

    def get(self, app_id: int) -> Optional[App]:
        owner = self._ids.get(str(app_id))
        if owner is None:
            return None
        return self.get_by_name(owner["name"])

    def get_by_name(self, name: str) -> Optional[App]:
        return self._from(self._names.get(_safe_name(name)))

    def get_all(self) -> List[App]:
        return [a for a in (self._from(d) for d in self._names.all()) if a]

    def update(self, app: App) -> bool:
        cur = self.get(app.id)
        if cur is None or cur.name != app.name:
            return False  # renames would need a new name claim; not supported
        self._names.put(_safe_name(app.name), {
            "id": app.id, "name": app.name, "description": app.description})
        return True

    def delete(self, app_id: int) -> bool:
        owner = self._ids.get(str(app_id))
        if owner is None:
            return False
        self._names.delete(_safe_name(owner["name"]))
        return self._ids.delete(str(app_id))


class SharedAccessKeys(base.AccessKeys):
    def __init__(self, root: Path):
        self._keys = _RecordDir(root / "meta" / "access_keys")

    def insert(self, access_key: AccessKey) -> Optional[str]:
        if not access_key.key:
            access_key.key = AccessKey.generate()
        ok = self._keys.put_new(_safe_name(access_key.key), {
            "key": access_key.key, "appid": access_key.app_id,
            "events": access_key.events})
        return access_key.key if ok else None

    def _from(self, d: Dict) -> AccessKey:
        return AccessKey(d["key"], d["appid"], d.get("events", []))

    def get(self, key: str) -> Optional[AccessKey]:
        d = self._keys.get(_safe_name(key))
        return self._from(d) if d else None

    def get_by_app_id(self, app_id: int) -> List[AccessKey]:
        return [self._from(d) for d in self._keys.all() if d["appid"] == app_id]

    def delete(self, key: str) -> bool:
        return self._keys.delete(_safe_name(key))


class SharedChannels(base.Channels):
    def __init__(self, root: Path):
        self._root = root

    def _dir(self, app_id: int) -> _RecordDir:
        return _RecordDir(self._root / "meta" / "channels" / f"app_{app_id}")

    def _ids(self, app_id: int) -> _RecordDir:
        return _RecordDir(self._root / "meta" / "channels" / f"app_{app_id}_ids")

    def insert(self, channel: Channel) -> Optional[int]:
        name_key = _safe_name(channel.name)
        rec = {"id": 0, "name": channel.name, "appid": channel.app_id}
        d = self._dir(channel.app_id)
        if not d.put_new(name_key, rec):
            existing = d.get(name_key)
            if existing is None or existing.get("id"):
                return None
            rec = existing  # resume a wedged insert
        want = (zlib.crc32(f"{channel.app_id}/{channel.name}".encode()) % (1 << 30)) + 1
        cid = _claim_id(self._ids(channel.app_id), want, channel.name)
        rec["id"] = channel.id = cid
        d.put(name_key, rec)
        return cid

    def get(self, channel_id: int) -> Optional[Channel]:
        base_dir = self._root / "meta" / "channels"
        if not base_dir.exists():
            return None
        for appdir in base_dir.iterdir():
            if appdir.name.endswith("_ids"):
                continue
            for d in _RecordDir(appdir).all():
                if d.get("id") == channel_id:
                    return Channel(d["id"], d["name"], d["appid"])
        return None

    def get_by_app_id(self, app_id: int) -> List[Channel]:
        return [Channel(d["id"], d["name"], d["appid"])
                for d in self._dir(app_id).all() if d.get("id")]

    def delete(self, channel_id: int) -> bool:
        ch = self.get(channel_id)
        if ch is None:
            return False
        ok = self._dir(ch.app_id).delete(_safe_name(ch.name))
        self._ids(ch.app_id).delete(str(channel_id))  # release the id claim
        return ok


class SharedEngineInstances(base.EngineInstances):
    def __init__(self, root: Path):
        self._recs = _RecordDir(root / "meta" / "engine_instances")

    def insert(self, instance: EngineInstance) -> str:
        if not instance.id:
            instance.id = uuid.uuid4().hex
        self._recs.put(_safe_name(instance.id), _ei_to_json(instance))
        return instance.id

    def get(self, instance_id: str) -> Optional[EngineInstance]:
        d = self._recs.get(_safe_name(instance_id))
        return _ei_from_json(d) if d else None

    def update(self, instance: EngineInstance) -> bool:
        if self._recs.get(_safe_name(instance.id)) is None:
            return False
        self._recs.put(_safe_name(instance.id), _ei_to_json(instance))
        return True

    def get_all(self) -> List[EngineInstance]:
        return [_ei_from_json(d) for d in self._recs.all()]

    def delete(self, instance_id: str) -> bool:
        return self._recs.delete(_safe_name(instance_id))


class SharedEngineManifests(base.EngineManifests):
    def __init__(self, root: Path):
        self._recs = _RecordDir(root / "meta" / "engine_manifests")

    @staticmethod
    def _key(manifest_id: str, version: str) -> str:
        return _safe_name(f"{manifest_id}@@{version}")

    def insert(self, manifest: EngineManifest) -> None:
        self._recs.put(self._key(manifest.id, manifest.version),
                       localfs.FSEngineManifests._to_json(manifest))

    def get(self, manifest_id: str, version: str) -> Optional[EngineManifest]:
        d = self._recs.get(self._key(manifest_id, version))
        return localfs.FSEngineManifests._from_json(d) if d else None

    def get_all(self) -> List[EngineManifest]:
        return [localfs.FSEngineManifests._from_json(d) for d in self._recs.all()]

    def delete(self, manifest_id: str, version: str) -> bool:
        return self._recs.delete(self._key(manifest_id, version))


class SharedEvaluationInstances(base.EvaluationInstances):
    def __init__(self, root: Path):
        self._recs = _RecordDir(root / "meta" / "evaluation_instances")

    def insert(self, instance: EvaluationInstance) -> str:
        if not instance.id:
            instance.id = uuid.uuid4().hex
        self._recs.put(_safe_name(instance.id),
                       localfs.FSEvaluationInstances._to_json(instance))
        return instance.id

    def get(self, instance_id: str) -> Optional[EvaluationInstance]:
        d = self._recs.get(_safe_name(instance_id))
        return localfs.FSEvaluationInstances._from_json(d) if d else None

    def update(self, instance: EvaluationInstance) -> bool:
        if self._recs.get(_safe_name(instance.id)) is None:
            return False
        self._recs.put(_safe_name(instance.id),
                       localfs.FSEvaluationInstances._to_json(instance))
        return True

    def get_all(self) -> List[EvaluationInstance]:
        return [localfs.FSEvaluationInstances._from_json(d) for d in self._recs.all()]

    def get_completed(self) -> List[EvaluationInstance]:
        return [i for i in self.get_all() if i.status == "EVALCOMPLETED"]

    def delete(self, instance_id: str) -> bool:
        return self._recs.delete(_safe_name(instance_id))


class SharedModels(localfs.FSModels):
    """Model blobs are keyed by engine-instance id (uuid → unique object
    names already); the localfs tmp+rename write is the object PUT."""


class SharedFSEvents(localfs.FSEvents):
    """Per-writer segments over the shared prefix.

    Readers (find/scan/native batch/host-sharded scans) are inherited
    unchanged — they glob ``seg-*.jsonl``, and per-writer names sort into a
    stable global order.  The write hooks are the tagged localfs ones:
    segments are ``seg-<writer>-NNNNN.jsonl`` and tombstones
    ``tombstones-<writer>.txt`` (unioned at read time by the inherited
    ``_tombstones``); the tag defaults to ``<host>-<pid>`` instead of
    localfs's untagged single-writer naming.

    Columnar snapshots are shared the same way: ANY host may run
    ``pio snapshot`` (or hit the auto-trigger) and the build lands as
    ``snapshot/snap-<its writer tag>-<id>.pioc`` plus an atomically
    replaced ``manifest.json`` on the shared prefix — every other host's
    ``snapshot_scan`` validates that manifest against the live segment
    set and mmap-loads the same file, so one build serves the whole
    fleet.  Concurrent builders are serialized by the flock where the
    filesystem honors it; where it doesn't, last-writer-wins manifest
    replaces stay self-consistent (the loser's file is garbage-collected
    by the next build)."""

    def __init__(self, root: Path, writer_tag: Optional[str] = None):
        super().__init__(root, writer_tag=writer_tag or writer_id())


class SharedFSSource:
    """Storage source of type ``sharedfs`` (PIO_STORAGE_SOURCES_*_TYPE)."""

    def __init__(self, path: str):
        root = Path(path)
        self.apps = SharedApps(root)
        self.access_keys = SharedAccessKeys(root)
        self.channels = SharedChannels(root)
        self.engine_instances = SharedEngineInstances(root)
        self.engine_manifests = SharedEngineManifests(root)
        self.evaluation_instances = SharedEvaluationInstances(root)
        self.models = SharedModels(root)
        self.events = SharedFSEvents(root)
