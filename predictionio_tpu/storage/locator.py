"""Storage locator (reference: data/.../storage/Storage.scala).

The reference resolves repositories from ``PIO_STORAGE_REPOSITORIES_{METADATA,
EVENTDATA,MODELDATA}_{NAME,SOURCE}`` + ``PIO_STORAGE_SOURCES_<NAME>_{TYPE,...}``
env vars (set by conf/pio-env.sh) and instantiates backend clients by
reflection.  Same contract here: sources of type ``memory`` or ``localfs``;
each repository (metadata / eventdata / modeldata) binds to a source.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Optional

from predictionio_tpu.storage import base, localfs, memory, sql

_REPOSITORIES = ("METADATA", "EVENTDATA", "MODELDATA")


@dataclass
class StorageConfig:
    """Parsed PIO_STORAGE_* configuration."""

    sources: Dict[str, Dict[str, str]]        # name -> {type, path, ...}
    repositories: Dict[str, str]              # METADATA/EVENTDATA/MODELDATA -> source name

    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None) -> "StorageConfig":
        env = dict(env if env is not None else os.environ)
        sources: Dict[str, Dict[str, str]] = {}
        repositories: Dict[str, str] = {}
        for k, v in env.items():
            if k.startswith("PIO_STORAGE_SOURCES_"):
                rest = k[len("PIO_STORAGE_SOURCES_"):]
                name, _, attr = rest.partition("_")
                sources.setdefault(name, {})[attr.lower()] = v
            elif k.startswith("PIO_STORAGE_REPOSITORIES_"):
                rest = k[len("PIO_STORAGE_REPOSITORIES_"):]
                repo, _, attr = rest.partition("_")
                if attr == "SOURCE":
                    repositories[repo] = v
        if not repositories:
            # Default single-node config: everything on localfs under ~/.pio_store
            home = env.get("PIO_FS_BASEDIR", str(Path(env.get("HOME", ".")) / ".pio_store"))
            sources = {"LOCALFS": {"type": "localfs", "path": home}}
            repositories = {r: "LOCALFS" for r in _REPOSITORIES}
        for r in _REPOSITORIES:
            if r not in repositories:
                raise ValueError(f"PIO_STORAGE_REPOSITORIES_{r}_SOURCE is not configured")
            if repositories[r] not in sources:
                raise ValueError(
                    f"repository {r} references undefined source {repositories[r]!r}"
                )
        return cls(sources, repositories)


class _MemorySource:
    def __init__(self):
        self.apps = memory.MemApps()
        self.access_keys = memory.MemAccessKeys()
        self.channels = memory.MemChannels()
        self.engine_instances = memory.MemEngineInstances()
        self.engine_manifests = memory.MemEngineManifests()
        self.evaluation_instances = memory.MemEvaluationInstances()
        self.models = memory.MemModels()
        self.events = memory.MemEvents()


class _LocalFSSource:
    def __init__(self, path: str):
        root = Path(path)
        self.apps = localfs.FSApps(root)
        self.access_keys = localfs.FSAccessKeys(root)
        self.channels = localfs.FSChannels(root)
        self.engine_instances = localfs.FSEngineInstances(root)
        self.engine_manifests = localfs.FSEngineManifests(root)
        self.evaluation_instances = localfs.FSEvaluationInstances(root)
        self.models = localfs.FSModels(root)
        self.events = localfs.FSEvents(root)


def _sharedfs_source(path: str):
    from predictionio_tpu.storage import sharedfs

    return sharedfs.SharedFSSource(path)


def _sharded_source(spec: Dict[str, str]):
    from predictionio_tpu.storage import sharded

    return sharded.ShardedSource(spec)


_SOURCE_TYPES = {
    "memory": _MemorySource,
    "localfs": _LocalFSSource,
    "sql": sql.SQLSource,
    "sharedfs": _sharedfs_source,
    "sharded": _sharded_source,
}


class Storage:
    """Repository accessor bound to a StorageConfig (reference: Storage object)."""

    def __init__(self, config: Optional[StorageConfig] = None):
        self.config = config or StorageConfig.from_env()
        self._clients: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _client(self, repo: str):
        name = self.config.repositories[repo]
        with self._lock:
            if name not in self._clients:
                spec = self.config.sources[name]
                typ = spec.get("type", "localfs")
                if typ not in _SOURCE_TYPES:
                    raise ValueError(
                        f"unknown storage source type {typ!r} (have: {sorted(_SOURCE_TYPES)})"
                    )
                if typ in ("localfs", "sharedfs"):
                    self._clients[name] = _SOURCE_TYPES[typ](spec.get("path", ".pio_store"))
                elif typ == "sharded":
                    # needs the whole spec: path + shards + replicas
                    # (PIO_STORAGE_SOURCES_<NAME>_{SHARDS,REPLICAS})
                    self._clients[name] = _SOURCE_TYPES[typ](spec)
                elif typ == "sql":
                    # reference JDBC URL ≈ our path; default is an ephemeral db
                    self._clients[name] = _SOURCE_TYPES[typ](spec.get("path", ":memory:"))
                else:
                    self._clients[name] = _SOURCE_TYPES[typ]()
            return self._clients[name]

    # Metadata repositories
    @property
    def apps(self) -> base.Apps:
        return self._client("METADATA").apps

    @property
    def access_keys(self) -> base.AccessKeys:
        return self._client("METADATA").access_keys

    @property
    def channels(self) -> base.Channels:
        return self._client("METADATA").channels

    @property
    def engine_instances(self) -> base.EngineInstances:
        return self._client("METADATA").engine_instances

    @property
    def engine_manifests(self) -> base.EngineManifests:
        return self._client("METADATA").engine_manifests

    @property
    def evaluation_instances(self) -> base.EvaluationInstances:
        return self._client("METADATA").evaluation_instances

    # Model repository
    @property
    def models(self) -> base.Models:
        return self._client("MODELDATA").models

    # Event repositories
    @property
    def l_events(self) -> base.LEvents:
        return self._client("EVENTDATA").events

    @property
    def p_events(self) -> base.PEvents:
        return self._client("EVENTDATA").events


_default: Optional[Storage] = None
_default_lock = threading.Lock()


def get_storage(refresh: bool = False) -> Storage:
    global _default
    with _default_lock:
        if _default is None or refresh:
            _default = Storage()
            changed = True
        else:
            changed = False
        result = _default
    if changed:
        base.notify_append(None)   # new default: cached reads are stale
    return result


def set_storage(storage: Optional[Storage]) -> None:
    """Override the process-default storage (used by tests and servers).

    Cached reads keyed by app/entity names (the serve lane's history
    cache) describe the OLD storage once the default moves — flush them
    through the mutation bus."""
    global _default
    with _default_lock:
        _default = storage
    base.notify_append(None)
