from predictionio_tpu.storage.base import (  # noqa: F401
    AccessKey,
    AccessKeys,
    App,
    Apps,
    Channel,
    Channels,
    EngineInstance,
    EngineInstances,
    EngineManifest,
    EngineManifests,
    EvaluationInstance,
    EvaluationInstances,
    LEvents,
    Models,
    PEvents,
)
from predictionio_tpu.storage.locator import (  # noqa: F401
    Storage,
    StorageConfig,
    get_storage,
    set_storage,
)
