"""Local-filesystem storage backend.

System-of-record replacing the reference's HBase/Elasticsearch/JDBC backends
(data/.../storage/{hbase,elasticsearch,jdbc}/ per SURVEY.md §2) with a layout
designed for the TPU ingest path:

- **Events**: append-only JSON-lines segments per (app, channel), rotated at
  a size threshold (``events/app_<id>/<channel>/seg-NNNNN.jsonl``).  Segments
  are immutable once rotated, so bulk training scans are sharded sequential
  reads — the unit the native C++ scanner (``predictionio_tpu/native``) and
  the columnar staging path parallelise over.  Deletes are tombstones in a
  sidecar so the log stays append-only.
- **Metadata** (apps/keys/channels/instances): single JSON documents under
  ``meta/`` written atomically (tmp+rename).
- **Models**: blobs under ``models/<instance_id>.bin``.
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import os
import threading
import uuid
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Sequence

from predictionio_tpu.events.event import Event, canonical_event_json
from predictionio_tpu.obs.metrics import LATENCY_BUCKETS, SIZE_BUCKETS, get_registry
from predictionio_tpu.obs.tracing import trace_span
from predictionio_tpu.storage import base
from predictionio_tpu.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EngineManifest,
    EvaluationInstance,
)

# rotate segments at 64 MiB; PIO_SEGMENT_MAX_BYTES overrides (benches and
# snapshot tests rotate early to exercise multi-segment layouts cheaply)
SEGMENT_MAX_BYTES = int(os.environ.get("PIO_SEGMENT_MAX_BYTES", 64 << 20))
DEFAULT_CHANNEL = "_default"

log = logging.getLogger("pio.storage")

# event-mutation listener bus lives in storage.base (every backend
# notifies it); alias kept local for the call sites below
_notify_append = base.notify_append

# -- write-path instruments (obs tentpole).  All recorded at group-commit
# granularity (one observation per physical write/fsync, not per event),
# so the hot ingest loop pays a few dict updates per THOUSANDS of events.
_REG = get_registry()
_M_APPEND = _REG.histogram(
    "pio_storage_append_duration_seconds",
    "Segment append latency (write+flush, excluding fsync); count = "
    "physical appends", buckets=LATENCY_BUCKETS)
_M_APPEND_BYTES = _REG.counter(
    "pio_storage_append_bytes_total", "Bytes appended to event segments")
_M_EVENTS = _REG.counter(
    "pio_storage_events_appended_total",
    "Event lines appended to the log (exactly the on-disk line count)")
_M_FSYNC = _REG.histogram(
    "pio_storage_fsync_duration_seconds",
    "fsync latency on event segments; count = fsyncs issued",
    buckets=LATENCY_BUCKETS)
_M_GROUP = _REG.histogram(
    "pio_storage_group_commit_batch_size",
    "Request buffers coalesced per group commit (occupancy = sum/count)",
    buckets=SIZE_BUCKETS)
_M_HEALS = _REG.counter(
    "pio_storage_torn_tail_heals_total",
    "Torn segment tails truncated on writer reopen")
_M_ROTATE = _REG.counter(
    "pio_storage_segment_rotations_total", "New segment files opened")
_M_SEGS = _REG.gauge(
    "pio_storage_live_segments",
    "Segments in the writer's channel directory at last open, by channel")


def _fsync_policy() -> str:
    """Ingest durability policy (PIO_FSYNC):

    - ``rotate`` (default): fsync only when a segment rotates or the writer
      closes — a crash can lose the OS-buffered tail of the active segment,
      like the reference's HBase deferred-WAL-flush mode.
    - ``always``: fsync after every append — no acknowledged event is ever
      lost, at a per-request latency cost.
    - ``interval:<ms>``: fsync at most every <ms> milliseconds — bounded
      loss window, group-commit throughput.
    - ``never``: leave it entirely to the OS.
    """
    return os.environ.get("PIO_FSYNC", "rotate").lower()


class _SegmentWriter:
    """Kept-open appender for one (app, channel) log.

    The previous write path re-opened the active segment per insert (open +
    append + close per HTTP request); this holds the handle open, appends
    with one write(), and applies the PIO_FSYNC durability policy.  Callers
    serialize via FSEvents' per-channel commit group; writes use O_APPEND
    semantics so external writers to the same directory stay safe.

    With ``tag`` set (prefork event-server workers, sharedfs multi-host
    ingest) segments are named ``seg-<tag>-NNNNN.jsonl`` and this writer
    only ever appends to its OWN segments — N concurrent writer processes
    never share an active file, so their appends can never interleave
    bytes.  Readers glob ``seg-*.jsonl`` and see the union."""

    def __init__(self, d: Path, tag: Optional[str] = None):
        self._dir = d
        self._tag = tag
        self._f = None
        self._path: Optional[Path] = None
        self._last_sync = 0.0
        self.rotations = 0   # new segment files opened (snapshot auto-trigger)

    def append(self, text: str) -> None:
        import time as _time

        if self._f is not None:
            # a data-delete/re-import from ANY process may have unlinked or
            # replaced the segment under us; writing on would ack events
            # into an orphaned inode that no reader can ever see.  Compare
            # the directory entry's inode with the open handle's — unlike
            # fstat's st_nlink, this also detects the unlink on filesystems
            # (9p, some overlayfs) that keep st_nlink at 1 for open files.
            try:
                if os.stat(self._path).st_ino != os.fstat(self._f.fileno()).st_ino:
                    self._f.close()
                    self._f = None
            except OSError:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None
        if self._f is None or self._f.tell() >= SEGMENT_MAX_BYTES:
            self._open_next()
        t0 = _time.perf_counter()
        self._f.write(text)
        self._f.flush()
        _M_APPEND.observe(_time.perf_counter() - t0)
        _M_APPEND_BYTES.inc(len(text))
        policy = _fsync_policy()
        if policy == "always":
            self._timed_fsync()
        elif policy.startswith("interval:"):
            try:
                every = float(policy.split(":", 1)[1]) / 1e3
            except ValueError:
                every = 0.1
            now = _time.monotonic()
            if now - self._last_sync >= every:
                self._timed_fsync()
                self._last_sync = now

    def _timed_fsync(self) -> None:
        import time as _time

        t0 = _time.perf_counter()
        os.fsync(self._f.fileno())
        _M_FSYNC.observe(_time.perf_counter() - t0)

    @staticmethod
    def _heal_torn_tail(path: Path) -> None:
        """Truncate an unterminated final line before resuming appends.

        A crash (kill -9, power loss) mid-append can leave a partial last
        line; appending after it would fuse two events into one corrupt
        line mid-file.  The torn event was never acknowledged (the fsync
        policy runs after the full write), so dropping it is safe — and
        only THIS writer owns the file (per-writer/single-writer
        contract), so truncating cannot race another appender."""
        with open(path, "rb+") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            if size == 0:
                return
            f.seek(size - 1)
            if f.read(1) == b"\n":
                return
            # scan backwards in chunks for the last newline
            pos = size
            keep = 0
            while pos > 0:
                step = min(64 * 1024, pos)
                f.seek(pos - step)
                chunk = f.read(step)
                nl = chunk.rfind(b"\n")
                if nl >= 0:
                    keep = pos - step + nl + 1
                    break
                pos -= step
            f.truncate(keep)
            _M_HEALS.inc()

    def _open_next(self) -> None:
        self.close()
        self._dir.mkdir(parents=True, exist_ok=True)
        if self._tag is None:
            # only THIS writer's numeric naming — never append into a
            # per-writer segment that may coexist in the same directory
            segs = sorted(p for p in self._dir.glob("seg-*.jsonl")
                          if p.stem.split("-", 1)[1].isdigit())
        else:
            # exact-tag match, not just the glob: the glob alone would let
            # tag 'bulk' claim (and truncate-heal!) live segments of a
            # dash-extended tag like 'bulk-2'
            def _own(p: Path) -> bool:
                n = p.stem.rsplit("-", 1)[1]
                return n.isdigit() and p.stem == f"seg-{self._tag}-{n}"

            segs = sorted(p for p in self._dir.glob(f"seg-{self._tag}-*.jsonl")
                          if _own(p))
        if segs and segs[-1].stat().st_size < SEGMENT_MAX_BYTES:
            path = segs[-1]
            self._heal_torn_tail(path)
        else:
            n = int(segs[-1].stem.rsplit("-", 1)[1]) + 1 if segs else 0
            path = (self._dir / f"seg-{n:05d}.jsonl" if self._tag is None
                    else self._dir / f"seg-{self._tag}-{n:05d}.jsonl")
            _M_ROTATE.inc()
            self.rotations += 1
        self._path = path
        self._f = open(path, "a")
        # this writer's view of its own series; readers union all writers
        _M_SEGS.set(len(segs) + (1 if path not in segs else 0),
                    channel=f"{self._dir.parent.name}/{self._dir.name}")

    def close(self) -> None:
        if self._f is not None:
            try:
                # skip the durability sync ONLY for externally-unlinked
                # handles (nothing to persist); real flush/fsync failures
                # (ENOSPC/EIO) must propagate so ingest NACKs the events
                try:
                    unlinked = os.fstat(self._f.fileno()).st_nlink == 0
                except OSError:
                    unlinked = True
                self._f.flush()
                if _fsync_policy() != "never" and not unlinked:
                    self._timed_fsync()
            finally:
                f, self._f = self._f, None
                f.close()


def _atomic_write(path: Path, text: str) -> None:
    tmp = path.with_suffix(path.suffix + f".tmp{os.getpid()}")
    tmp.write_text(text)
    tmp.replace(path)


class _JsonDoc:
    """A JSON document on disk with atomic replace and an in-process lock."""

    def __init__(self, path: Path, default):
        self.path = path
        self.lock = threading.Lock()
        self.default = default
        self.path.parent.mkdir(parents=True, exist_ok=True)

    def read(self):
        if not self.path.exists():
            return json.loads(json.dumps(self.default))
        return json.loads(self.path.read_text())

    def write(self, obj) -> None:
        _atomic_write(self.path, json.dumps(obj, indent=1, sort_keys=True))


def _dt_to_json(t: Optional[_dt.datetime]) -> Optional[str]:
    return t.isoformat() if t else None


def _dt_from_json(s: Optional[str]) -> Optional[_dt.datetime]:
    return _dt.datetime.fromisoformat(s) if s else None


class FSApps(base.Apps):
    def __init__(self, root: Path):
        self._doc = _JsonDoc(root / "meta" / "apps.json", {"next_id": 1, "apps": []})

    def insert(self, app: App) -> Optional[int]:
        with self._doc.lock:
            d = self._doc.read()
            if any(a["name"] == app.name for a in d["apps"]):
                return None
            if app.id <= 0 or any(a["id"] == app.id for a in d["apps"]):
                app.id = d["next_id"]
            d["next_id"] = max(d["next_id"], app.id) + 1
            d["apps"].append({"id": app.id, "name": app.name, "description": app.description})
            self._doc.write(d)
            return app.id

    def _all(self) -> List[App]:
        return [App(a["id"], a["name"], a.get("description", "")) for a in self._doc.read()["apps"]]

    def get(self, app_id: int) -> Optional[App]:
        return next((a for a in self._all() if a.id == app_id), None)

    def get_by_name(self, name: str) -> Optional[App]:
        return next((a for a in self._all() if a.name == name), None)

    def get_all(self) -> List[App]:
        return self._all()

    def update(self, app: App) -> bool:
        with self._doc.lock:
            d = self._doc.read()
            for a in d["apps"]:
                if a["id"] == app.id:
                    a["name"], a["description"] = app.name, app.description
                    self._doc.write(d)
                    return True
            return False

    def delete(self, app_id: int) -> bool:
        with self._doc.lock:
            d = self._doc.read()
            n = len(d["apps"])
            d["apps"] = [a for a in d["apps"] if a["id"] != app_id]
            self._doc.write(d)
            return len(d["apps"]) < n


class FSAccessKeys(base.AccessKeys):
    def __init__(self, root: Path):
        self._doc = _JsonDoc(root / "meta" / "access_keys.json", {"keys": []})

    def insert(self, access_key: AccessKey) -> Optional[str]:
        with self._doc.lock:
            if not access_key.key:
                access_key.key = AccessKey.generate()
            d = self._doc.read()
            d["keys"].append({"key": access_key.key, "appid": access_key.app_id, "events": access_key.events})
            self._doc.write(d)
            return access_key.key

    def _all(self) -> List[AccessKey]:
        return [AccessKey(k["key"], k["appid"], k.get("events", [])) for k in self._doc.read()["keys"]]

    def get(self, key: str) -> Optional[AccessKey]:
        return next((k for k in self._all() if k.key == key), None)

    def get_by_app_id(self, app_id: int) -> List[AccessKey]:
        return [k for k in self._all() if k.app_id == app_id]

    def delete(self, key: str) -> bool:
        with self._doc.lock:
            d = self._doc.read()
            n = len(d["keys"])
            d["keys"] = [k for k in d["keys"] if k["key"] != key]
            self._doc.write(d)
            return len(d["keys"]) < n


class FSChannels(base.Channels):
    def __init__(self, root: Path):
        self._doc = _JsonDoc(root / "meta" / "channels.json", {"next_id": 1, "channels": []})

    def insert(self, channel: Channel) -> Optional[int]:
        with self._doc.lock:
            d = self._doc.read()
            if any(c["name"] == channel.name and c["appid"] == channel.app_id for c in d["channels"]):
                return None
            channel.id = d["next_id"]
            d["next_id"] += 1
            d["channels"].append({"id": channel.id, "name": channel.name, "appid": channel.app_id})
            self._doc.write(d)
            return channel.id

    def _all(self) -> List[Channel]:
        return [Channel(c["id"], c["name"], c["appid"]) for c in self._doc.read()["channels"]]

    def get(self, channel_id: int) -> Optional[Channel]:
        return next((c for c in self._all() if c.id == channel_id), None)

    def get_by_app_id(self, app_id: int) -> List[Channel]:
        return [c for c in self._all() if c.app_id == app_id]

    def delete(self, channel_id: int) -> bool:
        with self._doc.lock:
            d = self._doc.read()
            n = len(d["channels"])
            d["channels"] = [c for c in d["channels"] if c["id"] != channel_id]
            self._doc.write(d)
            return len(d["channels"]) < n


def _ei_to_json(i: EngineInstance) -> Dict:
    return {
        "id": i.id, "status": i.status,
        "startTime": _dt_to_json(i.start_time), "endTime": _dt_to_json(i.end_time),
        "engineId": i.engine_id, "engineVersion": i.engine_version,
        "engineVariant": i.engine_variant, "engineFactory": i.engine_factory,
        "env": i.env, "sparkConf": i.spark_conf,
        "dataSourceParams": i.data_source_params, "preparatorParams": i.preparator_params,
        "algorithmsParams": i.algorithms_params, "servingParams": i.serving_params,
    }


def _ei_from_json(d: Dict) -> EngineInstance:
    return EngineInstance(
        id=d["id"], status=d["status"],
        start_time=_dt_from_json(d["startTime"]), end_time=_dt_from_json(d.get("endTime")),
        engine_id=d["engineId"], engine_version=d["engineVersion"],
        engine_variant=d["engineVariant"], engine_factory=d["engineFactory"],
        env=d.get("env", {}), spark_conf=d.get("sparkConf", {}),
        data_source_params=d.get("dataSourceParams", "{}"),
        preparator_params=d.get("preparatorParams", "{}"),
        algorithms_params=d.get("algorithmsParams", "[]"),
        serving_params=d.get("servingParams", "{}"),
    )


class FSEngineInstances(base.EngineInstances):
    def __init__(self, root: Path):
        self._doc = _JsonDoc(root / "meta" / "engine_instances.json", {"instances": []})

    def insert(self, instance: EngineInstance) -> str:
        with self._doc.lock:
            if not instance.id:
                instance.id = uuid.uuid4().hex
            d = self._doc.read()
            d["instances"].append(_ei_to_json(instance))
            self._doc.write(d)
            return instance.id

    def get(self, instance_id: str) -> Optional[EngineInstance]:
        return next((_ei_from_json(i) for i in self._doc.read()["instances"] if i["id"] == instance_id), None)

    def update(self, instance: EngineInstance) -> bool:
        with self._doc.lock:
            d = self._doc.read()
            for k, i in enumerate(d["instances"]):
                if i["id"] == instance.id:
                    d["instances"][k] = _ei_to_json(instance)
                    self._doc.write(d)
                    return True
            return False

    def get_all(self) -> List[EngineInstance]:
        return [_ei_from_json(i) for i in self._doc.read()["instances"]]

    def delete(self, instance_id: str) -> bool:
        with self._doc.lock:
            d = self._doc.read()
            n = len(d["instances"])
            d["instances"] = [i for i in d["instances"] if i["id"] != instance_id]
            self._doc.write(d)
            return len(d["instances"]) < n


class FSEngineManifests(base.EngineManifests):
    def __init__(self, root: Path):
        self._doc = _JsonDoc(root / "meta" / "engine_manifests.json", {"manifests": []})

    @staticmethod
    def _to_json(m: EngineManifest) -> Dict:
        return {
            "id": m.id, "version": m.version, "name": m.name,
            "description": m.description, "files": m.files,
            "engineFactory": m.engine_factory,
        }

    @staticmethod
    def _from_json(d: Dict) -> EngineManifest:
        return EngineManifest(
            id=d["id"], version=d["version"], name=d["name"],
            description=d.get("description", ""), files=d.get("files", []),
            engine_factory=d.get("engineFactory", ""),
        )

    def insert(self, manifest: EngineManifest) -> None:
        with self._doc.lock:
            d = self._doc.read()
            d["manifests"] = [
                m for m in d["manifests"]
                if not (m["id"] == manifest.id and m["version"] == manifest.version)
            ]
            d["manifests"].append(self._to_json(manifest))
            self._doc.write(d)

    def get(self, manifest_id: str, version: str) -> Optional[EngineManifest]:
        return next(
            (self._from_json(m) for m in self._doc.read()["manifests"]
             if m["id"] == manifest_id and m["version"] == version),
            None,
        )

    def get_all(self) -> List[EngineManifest]:
        return [self._from_json(m) for m in self._doc.read()["manifests"]]

    def delete(self, manifest_id: str, version: str) -> bool:
        with self._doc.lock:
            d = self._doc.read()
            n = len(d["manifests"])
            d["manifests"] = [
                m for m in d["manifests"]
                if not (m["id"] == manifest_id and m["version"] == version)
            ]
            self._doc.write(d)
            return len(d["manifests"]) < n


class FSEvaluationInstances(base.EvaluationInstances):
    def __init__(self, root: Path):
        self._doc = _JsonDoc(root / "meta" / "evaluation_instances.json", {"instances": []})

    @staticmethod
    def _to_json(i: EvaluationInstance) -> Dict:
        return {
            "id": i.id, "status": i.status,
            "startTime": _dt_to_json(i.start_time), "endTime": _dt_to_json(i.end_time),
            "evaluationClass": i.evaluation_class,
            "engineParamsGeneratorClass": i.engine_params_generator_class,
            "env": i.env, "evaluatorResults": i.evaluator_results,
            "evaluatorResultsHTML": i.evaluator_results_html,
            "evaluatorResultsJSON": i.evaluator_results_json,
        }

    @staticmethod
    def _from_json(d: Dict) -> EvaluationInstance:
        return EvaluationInstance(
            id=d["id"], status=d["status"],
            start_time=_dt_from_json(d["startTime"]), end_time=_dt_from_json(d.get("endTime")),
            evaluation_class=d["evaluationClass"],
            engine_params_generator_class=d.get("engineParamsGeneratorClass", ""),
            env=d.get("env", {}),
            evaluator_results=d.get("evaluatorResults", ""),
            evaluator_results_html=d.get("evaluatorResultsHTML", ""),
            evaluator_results_json=d.get("evaluatorResultsJSON", ""),
        )

    def insert(self, instance: EvaluationInstance) -> str:
        with self._doc.lock:
            if not instance.id:
                instance.id = uuid.uuid4().hex
            d = self._doc.read()
            d["instances"].append(self._to_json(instance))
            self._doc.write(d)
            return instance.id

    def get(self, instance_id: str) -> Optional[EvaluationInstance]:
        return next((self._from_json(i) for i in self._doc.read()["instances"] if i["id"] == instance_id), None)

    def update(self, instance: EvaluationInstance) -> bool:
        with self._doc.lock:
            d = self._doc.read()
            for k, i in enumerate(d["instances"]):
                if i["id"] == instance.id:
                    d["instances"][k] = self._to_json(instance)
                    self._doc.write(d)
                    return True
            return False

    def get_completed(self) -> List[EvaluationInstance]:
        return [self._from_json(i) for i in self._doc.read()["instances"] if i["status"] == "EVALCOMPLETED"]


class FSModels(base.Models):
    """Reference: data/.../storage/localfs/LocalFSModels.scala."""

    def __init__(self, root: Path):
        self._dir = root / "models"
        self._dir.mkdir(parents=True, exist_ok=True)

    def _path(self, instance_id: str) -> Path:
        if not instance_id.replace("-", "").replace("_", "").isalnum():
            raise ValueError(f"invalid model id {instance_id!r}")
        return self._dir / f"{instance_id}.bin"

    def insert(self, instance_id: str, blob: bytes) -> None:
        tmp = self._path(instance_id).with_suffix(f".tmp{os.getpid()}")
        tmp.write_bytes(blob)
        tmp.replace(self._path(instance_id))

    def get(self, instance_id: str) -> Optional[bytes]:
        p = self._path(instance_id)
        return p.read_bytes() if p.exists() else None

    def delete(self, instance_id: str) -> bool:
        p = self._path(instance_id)
        if p.exists():
            p.unlink()
            return True
        return False


class _EntityIndex:
    """Incremental (entityType, entityId) → line-offset index over one
    channel's segments.

    The reference gets per-entity serving reads for free from HBase rowkeys;
    here the log is append-only JSONL, so the index tails each segment from
    the last consumed byte on every lookup (a stat per segment when nothing
    changed) and stores (path, offset, length) per event — memory stays
    O(events) small ints, and lookups read only the matching lines.  Safe
    with concurrent writers in other processes: a torn tail line (no final
    newline yet) is not consumed until complete.
    """

    def __init__(self, directory: Path):
        self._dir = directory
        self._consumed: Dict[str, int] = {}          # segment path -> bytes indexed
        self._inodes: Dict[str, int] = {}            # segment path -> st_ino
        self._postings: Dict[tuple, List[tuple]] = {}  # (etype, eid) -> [(path, off, len)]
        self._lock = threading.Lock()

    def _reset(self) -> None:
        self._consumed.clear()
        self._inodes.clear()
        self._postings.clear()

    def _refresh(self) -> None:
        segs = sorted(self._dir.glob("seg-*.jsonl")) if self._dir.exists() else []
        stats = {}
        for seg in segs:
            try:
                stats[str(seg)] = seg.stat()
            except FileNotFoundError:  # racing a delete
                pass
        # data-delete / re-import from ANY process replaces or truncates
        # segment files; offsets into the old bytes are meaningless, so any
        # inode change, shrink, or vanished segment rebuilds from scratch
        for path, consumed in self._consumed.items():
            st = stats.get(path)
            if (
                st is None
                or st.st_size < consumed
                or self._inodes.get(path) not in (None, st.st_ino)
            ):
                self._reset()
                break
        for seg in segs:
            path = str(seg)
            st = stats.get(path)
            if st is None:
                continue
            consumed = self._consumed.get(path, 0)
            self._inodes[path] = st.st_ino
            if st.st_size <= consumed:
                continue
            with open(seg, "rb") as f:
                f.seek(consumed)
                chunk = f.read(st.st_size - consumed)
            end = chunk.rfind(b"\n")
            if end < 0:
                continue  # only a torn partial line so far
            offset = consumed
            for line in chunk[: end + 1].split(b"\n"):
                ln = len(line) + 1
                if line.strip():
                    try:
                        d = json.loads(line)
                        key = (d.get("entityType"), d.get("entityId"))
                        self._postings.setdefault(key, []).append((path, offset, len(line)))
                    except json.JSONDecodeError:
                        pass  # skip corrupt line; offset still advances
                offset += ln
            self._consumed[path] = consumed + end + 1

    def warm(self) -> None:
        """Consume every segment byte into the postings now.  The index
        otherwise builds on the FIRST per-entity lookup — at a
        million-event log that is seconds of JSON parsing landing inside
        the first serving query's latency (and, during a follow deploy,
        contending with the bootstrap fold).  Deploy warms it off-thread
        instead; later lookups tail only the appended bytes."""
        with self._lock:
            self._refresh()

    def events(self, entity_type: str, entity_id: str, tombstones: set) -> List[Event]:
        for _attempt in range(2):
            with self._lock:
                self._refresh()
                postings = list(self._postings.get((entity_type, entity_id), ()))
            try:
                return self._read_postings(postings, tombstones)
            except (FileNotFoundError, json.JSONDecodeError, ValueError, KeyError):
                # segment replaced between refresh and read: rebuild once
                with self._lock:
                    self._reset()
        return []

    @staticmethod
    def _read_postings(postings: List[tuple], tombstones: set) -> List[Event]:
        out: List[Event] = []
        by_path: Dict[str, List[tuple]] = {}
        for path, off, ln in postings:
            by_path.setdefault(path, []).append((off, ln))
        for path, spans in by_path.items():
            with open(path, "rb") as f:
                for off, ln in spans:
                    f.seek(off)
                    e = Event.from_json(json.loads(f.read(ln)))
                    if e.event_id not in tombstones:
                        out.append(e)
        return out


def _env_writer_tag() -> Optional[str]:
    """Per-process writer tag from PIO_WRITER_TAG (set by the event
    server's prefork spawn), sanitized to filesystem-safe characters.
    '-' is kept: tags like ``w1-<parent pid>`` must stay distinct —
    stripping the separator could collide two different tags."""
    tag = os.environ.get("PIO_WRITER_TAG", "")
    tag = "".join(c for c in tag if c.isalnum() or c in "_-")
    return tag.strip("-") or None


class _CommitGroup:
    """Pending group-commit appends for one (app, channel) log."""

    __slots__ = ("cond", "pending", "active")

    def __init__(self):
        self.cond = threading.Condition()
        self.pending: List[dict] = []
        self.active = False


class FSEvents(base.LEvents, base.PEvents):
    """Append-only segmented JSONL event log.

    Concurrency model: within one process, appends to one (app, channel)
    are GROUP-COMMITTED — concurrent threads enqueue their encoded lines
    and the first thread in becomes the commit leader, writing every
    queued buffer with ONE write() (and at most one fsync per the
    PIO_FSYNC policy) while later arrivals queue for the next commit.
    Across processes, each writer appends only to its own
    ``seg-<tag>-NNNNN.jsonl`` segments (``writer_tag`` / PIO_WRITER_TAG),
    so prefork event-server workers never share a file descriptor; all
    read paths glob ``seg-*.jsonl`` and see the union."""

    def __init__(self, root: Path, writer_tag: Optional[str] = None):
        self._root = Path(root) / "events"
        # RLock: lock-holding paths (delete, compact) re-enter via
        # segment_paths' crashed-compaction recovery branch
        self._lock = threading.RLock()
        self._indexes: Dict[tuple, _EntityIndex] = {}
        self._writers: Dict[tuple, _SegmentWriter] = {}
        self._groups: Dict[tuple, _CommitGroup] = {}
        self._writer_tag = (writer_tag if writer_tag is not None
                            else _env_writer_tag())
        self._rot_seen: Dict[tuple, int] = {}    # snapshot auto-trigger state
        self._snap_inflight: set = set()

    def _entity_index(self, app_id: int, channel_id: Optional[int]) -> _EntityIndex:
        key = (app_id, channel_id)
        with self._lock:
            if key not in self._indexes:
                self._indexes[key] = _EntityIndex(self._chan_dir(app_id, channel_id))
            return self._indexes[key]

    def warm_entity_index(self, app_id: int,
                          channel_id: Optional[int] = None) -> None:
        """Pre-build the per-entity serving index (see
        ``_EntityIndex.warm``) so the FIRST ``find_by_entity`` after a
        deploy doesn't pay the whole log's JSON parse inline — the query
        server calls this off-thread at startup."""
        self._entity_index(app_id, channel_id).warm()

    # -- layout --------------------------------------------------------------

    def _chan_dir(self, app_id: int, channel_id: Optional[int]) -> Path:
        chan = DEFAULT_CHANNEL if channel_id is None else f"channel_{channel_id}"
        return self._root / f"app_{app_id}" / chan

    @staticmethod
    def _list_segments(d: Path) -> List[Path]:
        if not d.exists():
            return []
        return sorted(d.glob("seg-*.jsonl"))

    def segment_paths(self, app_id: int, channel_id: Optional[int] = None) -> List[Path]:
        d = self._chan_dir(app_id, channel_id)
        if (d / self._COMPACT_INTENT).exists():
            # finish/roll back a crashed compaction before anyone reads
            with self._lock:
                self._recover_compact(d)
        return self._list_segments(d)

    def _tombstones(self, d: Path) -> set:
        # union of all tombstone files: "tombstones.txt" (single-writer
        # localfs) and per-writer "tombstones-<writer>.txt" (sharedfs)
        dead: set = set()
        if d.exists():
            for p in d.glob("tombstones*.txt"):
                dead.update(p.read_text().split())
        return dead

    # -- LEvents -------------------------------------------------------------

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        self._chan_dir(app_id, channel_id).mkdir(parents=True, exist_ok=True)
        return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        import shutil

        d = self._chan_dir(app_id, channel_id)
        with self._lock:
            self._indexes.pop((app_id, channel_id), None)  # data-delete invalidates
            w = self._writers.pop((app_id, channel_id), None)
            if w is not None:
                w.close()
        if d.exists():
            shutil.rmtree(d)
            _notify_append(None)   # channel data gone: invalidate everything
            return True
        return False

    def insert(self, event: Event, app_id: int, channel_id: Optional[int] = None) -> str:
        return self.insert_batch([event], app_id, channel_id)[0]

    def _new_writer(self, d: Path) -> _SegmentWriter:
        """Writer factory hook: per-writer segment naming when a writer
        tag is set (prefork workers, sharedfs multi-host ingest)."""
        return _SegmentWriter(d, self._writer_tag)

    def _tombstone_path(self, d: Path) -> Path:
        """Tombstone file hook: per-writer when a tag is set (readers
        union all ``tombstones*.txt``)."""
        if self._writer_tag:
            return d / f"tombstones-{self._writer_tag}.txt"
        return d / "tombstones.txt"

    def insert_batch(
        self, events: Sequence[Event], app_id: int, channel_id: Optional[int] = None
    ) -> List[str]:
        self._append_lines("".join(e.to_json_line() + "\n" for e in events),
                           app_id, channel_id)
        _notify_append([(e.entity_type, e.entity_id) for e in events])
        return [e.event_id for e in events]

    def insert_json_batch(
        self, items: Sequence, app_id: int, channel_id: Optional[int] = None
    ) -> List[dict]:
        """Ingest fast path: wire dicts are canonicalized WITHOUT building
        Event objects (events.canonical_event_json — byte-identical lines,
        ~5× cheaper) and all valid items land in one group-committed
        append.  One clock read serves the whole batch: events with no
        explicit eventTime/creationTime share the batch's commit instant."""
        results: List[dict] = []
        lines: List[str] = []
        ents: List[tuple] = []
        now_iso = _dt.datetime.now(_dt.timezone.utc).isoformat()
        for item in items:
            try:
                d = canonical_event_json(item, now_iso)
                lines.append(json.dumps(d, separators=(",", ":"),
                                        sort_keys=True))
                results.append({"status": 201, "eventId": d["eventId"]})
                ents.append((str(d["entityType"]), str(d["entityId"])))
            except (ValueError, KeyError, TypeError) as e:
                results.append({"status": 400, "message": str(e)})
        if lines:
            self._append_lines("".join(ln + "\n" for ln in lines),
                               app_id, channel_id)
            _notify_append(ents)
        return results

    def _append_lines(self, lines: str, app_id: int,
                      channel_id: Optional[int]) -> None:
        """Group-commit append: enqueue this call's buffer; the first
        thread into an idle group becomes the commit leader and writes
        EVERY queued buffer with one write() (one fsync per policy),
        amortizing the syscall + durability cost across concurrent
        request threads — a storage group commit, same pattern as the
        serving micro-batcher.  Buffers arriving while a commit is in
        flight queue for the next leader — any waiter claims the vacancy
        when woken (leadership is released, never transferred)."""
        with trace_span("group_commit_append"):
            self._append_lines_traced(lines, app_id, channel_id)

    def _append_lines_traced(self, lines: str, app_id: int,
                             channel_id: Optional[int]) -> None:
        key = (app_id, channel_id)
        with self._lock:
            g = self._groups.get(key)
            if g is None:
                g = self._groups[key] = _CommitGroup()
        item: dict = {"lines": lines}
        with g.cond:
            g.pending.append(item)
            while "done" not in item and g.active:
                g.cond.wait()
            if "done" not in item:
                # leadership vacancy: commit everything queued (incl. ours)
                g.active = True
                batch = g.pending[:]
                del g.pending[:]
            else:
                batch = None
        if batch is not None:
            err: Optional[BaseException] = None
            commit_info = None
            try:
                with self._lock:
                    w = self._writers.get(key)
                    if w is None:
                        d = self._chan_dir(*key)
                        if (d / self._COMPACT_INTENT).exists():
                            # finish a crashed compaction BEFORE picking a
                            # segment: appending to a superseded segment
                            # would ack events the roll-forward recovery
                            # then unlinks
                            self._recover_compact(d)
                        w = self._writers[key] = self._new_writer(d)
                    payload = "".join(i["lines"] for i in batch)
                    w.append(payload)
                    _M_GROUP.observe(len(batch))
                    _M_EVENTS.inc(payload.count("\n"))
                    commit_info = self._commit_point(key, w)
                    # snapshot auto-trigger: only worth checking when this
                    # commit opened a new segment (rotations are rare; the
                    # default-0 get keeps a resumed writer's first commit
                    # from paying the manifest/glob check for nothing)
                    if w.rotations != self._rot_seen.get(key, 0):
                        self._rot_seen[key] = w.rotations
                        self._maybe_auto_snapshot(key)
            except BaseException as e:
                # a failed write (ENOSPC/EIO) must NACK every event in
                # the group — none of them is durable
                err = e
            if err is None and commit_info is not None:
                try:
                    # replication barrier OUTSIDE the instance lock: a
                    # slow follower must not block unrelated channels, and
                    # a failed barrier NACKs the whole group exactly like
                    # a failed write (nothing is acked that a promoted
                    # follower would not have)
                    self._post_commit(key, commit_info)
                except BaseException as e:
                    err = e
            with g.cond:
                for i in batch:
                    if err is not None:
                        i["err"] = err
                    i["done"] = True
                g.active = False
                g.cond.notify_all()
        err2 = item.get("err")
        if err2 is not None:
            raise err2

    # -- replication hooks (storage.sharded overrides) -----------------------

    def _commit_point(self, key: tuple, writer: _SegmentWriter):
        """Called by the group-commit leader with the instance lock held,
        right after the physical write: capture what this commit covered.
        Replicated backends return (segment path, end offset); the base
        backend has no barrier and returns None."""
        return None

    def _post_commit(self, key: tuple, info) -> None:
        """Called by the leader AFTER the lock is released when
        ``_commit_point`` returned non-None.  Raising here NACKs every
        event in the group — the semi-sync replication barrier."""

    _COMPACT_INTENT = "compact-intent.json"
    _COMPACT_LOCK = "compact.lock"

    def _recover_compact(self, d: Path, owned: bool = False) -> None:
        """Finish or roll back a CRASHED compaction (two-phase intent file).

        Liveness is decided by an OS flock on ``compact.lock``: a running
        compactor holds it for the whole operation, so recovery that cannot
        acquire it does NOTHING — an in-progress compaction is never
        mistaken for a crashed one (which would delete its output and then
        lose the log at commit).  With the flock held: phase 'prepare'
        rolls back (delete partial hidden output, original log intact);
        phase 'commit' rolls forward (publish remaining hidden segments,
        unlink superseded files, drop the intent)."""
        import fcntl

        intent_path = d / self._COMPACT_INTENT
        if not intent_path.exists():
            return
        lockf = None
        try:
            if not owned:
                lockf = open(d / self._COMPACT_LOCK, "a")
                try:
                    fcntl.flock(lockf.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
                except OSError:
                    return  # a live compactor owns the intent; leave it alone
            if not intent_path.exists():   # recovered while we waited
                return
            try:
                intent = json.loads(intent_path.read_text())
            except (json.JSONDecodeError, OSError):
                intent = {"phase": "prepare", "old": [], "tag": ""}
            tag = intent.get("tag", "")
            if intent.get("phase") == "commit":
                for hidden in d.glob(f".seg-{tag}-*.jsonl.tmp"):
                    try:
                        hidden.rename(d / hidden.name[1:-4])
                    except FileNotFoundError:
                        pass  # racing recoverer on another host won it
                for name in intent.get("old", []):
                    (d / name).unlink(missing_ok=True)
            else:
                for hidden in d.glob(f".seg-{tag}-*.jsonl.tmp"):
                    hidden.unlink(missing_ok=True)
                for pub in d.glob(f"seg-{tag}-*.jsonl"):
                    pub.unlink(missing_ok=True)
            intent_path.unlink(missing_ok=True)
        finally:
            if lockf is not None:
                lockf.close()  # closing releases any held flock

    def compact(self, app_id: int, channel_id: Optional[int] = None,
                before: Optional[_dt.datetime] = None) -> Dict[str, int]:
        """Rewrite the (app, channel) log dropping tombstoned events — and,
        with ``before``, expiring events older than that instant (the
        ActionML ecosystem's SelfCleaningDataSource role: TTL + compaction
        so the append-only log doesn't grow forever).

        OFFLINE maintenance op, like the reference runs data maintenance:
        pause ingest AND in-flight scans for this (app, channel) while it
        runs.  It is crash-safe — a two-phase intent file means a kill at
        any instant either rolls back (original log intact) or rolls
        forward (compacted log) on the next access; survivors stream
        straight from the read to hidden output files (O(1 event) memory).
        Returns {"kept", "expired", "segments"}.
        """
        import fcntl

        from predictionio_tpu.events.event import parse_time

        if before is not None:
            before = parse_time(before)
        d = self._chan_dir(app_id, channel_id)
        with self._lock:
            w = self._writers.pop((app_id, channel_id), None)
            if w is not None:
                w.close()
            d.mkdir(parents=True, exist_ok=True)
            # own the operation for its whole duration: concurrent readers'
            # recovery checks see the flock held and leave our intent alone
            lockf = open(d / self._COMPACT_LOCK, "a")
            try:
                fcntl.flock(lockf.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                lockf.close()
                raise RuntimeError(
                    "another compaction is in progress for this channel")
            try:
                return self._compact_locked(d, (app_id, channel_id), before)
            finally:
                lockf.close()

    def _compact_locked(self, d: Path, key: tuple,
                        before: Optional[_dt.datetime]) -> Dict[str, int]:
        """compact() body; caller holds BOTH the instance lock and the
        cross-process flock."""
        self._recover_compact(d, owned=True)
        old_segs = self._list_segments(d)
        old_tombs = sorted(d.glob("tombstones*.txt"))
        tag = uuid.uuid4().hex[:8]
        intent_path = d / self._COMPACT_INTENT
        old_names = [p.name for p in old_segs] + [p.name for p in old_tombs]
        _atomic_write(intent_path, json.dumps(
            {"phase": "prepare", "tag": tag, "old": old_names}))
        # phase 1: stream survivors into HIDDEN output (readers can't
        # see it; a crash here rolls back)
        kept = expired = n_new = 0
        f = None
        try:
            # iterate the snapshot directly (NOT _iter_raw, whose
            # segment_paths recovery branch would self-deadlock on the
            # intent we just wrote); tombstones applied the same way
            for e in self._iter_segments(old_segs, self._tombstones(d)):
                if before is not None and e.event_time < before:
                    expired += 1
                    continue
                if f is None or f.tell() >= SEGMENT_MAX_BYTES:
                    if f is not None:
                        f.flush()
                        os.fsync(f.fileno())
                        f.close()
                    f = open(d / f".seg-{tag}-{n_new:05d}.jsonl.tmp", "w")
                    n_new += 1
                f.write(e.to_json_line() + "\n")
                kept += 1
        finally:
            if f is not None:
                f.flush()
                os.fsync(f.fileno())
                f.close()
        # phase 2: COMMIT — atomic intent flip, then publish + unlink
        # (a crash after the flip rolls forward via _recover_compact)
        _atomic_write(intent_path, json.dumps(
            {"phase": "commit", "tag": tag, "old": old_names}))
        for hidden in sorted(d.glob(f".seg-{tag}-*.jsonl.tmp")):
            hidden.rename(d / hidden.name[1:-4])
        for p in old_segs + old_tombs:
            p.unlink(missing_ok=True)
        intent_path.unlink(missing_ok=True)
        self._indexes.pop(key, None)
        return {"kept": kept, "expired": expired, "segments": n_new}

    # -- columnar snapshots --------------------------------------------------

    def build_snapshot(self, app_id: int,
                       channel_id: Optional[int] = None) -> Dict:
        """Fold the (app, channel) log into a columnar snapshot (see
        storage.snapshot).  Safe alongside live ingest: segments are
        append-only and only complete lines at build time are covered."""
        from predictionio_tpu.storage import snapshot as _snap

        self.segment_paths(app_id, channel_id)   # recover crashed compaction
        d = self._chan_dir(app_id, channel_id)
        d.mkdir(parents=True, exist_ok=True)
        return _snap.build_snapshot(
            d, self._tombstones(d), self._writer_tag or "local")

    def snapshot_scan(self, app_id: int,
                      channel_id: Optional[int] = None) -> Optional[Dict]:
        """Snapshot-or-tail columnar read: {"batch", "ids", "watermark",
        ...} from the mmap'd snapshot plus a parse of only the uncovered
        JSONL tail, or None (miss — caller scans the log)."""
        from predictionio_tpu.storage import snapshot as _snap

        if not _snap.enabled():
            return None
        with trace_span("snapshot_scan"):
            self.segment_paths(app_id, channel_id)  # recover crashed compaction
            d = self._chan_dir(app_id, channel_id)
            res = _snap.scan_snapshot(d, self._tombstones(d))
        if res is None:
            _snap.record_miss()
        else:
            _snap.record_hit()
        return res

    def scan_tail_from(self, app_id: int, channel_id: Optional[int],
                       watermark: Dict[str, int], base=None,
                       heads: Optional[Dict] = None) -> Optional[Dict]:
        """Delta staging: parse only events past ``watermark`` (a
        per-segment byte map from a previous snapshot_scan; ``heads``
        are its segment fingerprints).  None when the watermark no
        longer matches the log (full restage needed)."""
        from predictionio_tpu.storage import snapshot as _snap

        d = self._chan_dir(app_id, channel_id)
        return _snap.scan_tail(d, watermark, self._tombstones(d), base=base,
                               heads=heads)

    def scan_events_up_to(self, app_id: int, channel_id: Optional[int],
                          watermark: Dict[str, int],
                          heads: Optional[Dict] = None) -> Optional[Dict]:
        """Bounded restart read for the follow-trainer: parse the log UP
        TO ``watermark`` so a restarted follower reconstructs exactly
        the event set its persisted watermark describes, then folds only
        the unapplied suffix.  None = the watermark no longer matches
        the live log (full restage)."""
        from predictionio_tpu.storage import snapshot as _snap

        d = self._chan_dir(app_id, channel_id)
        return _snap.scan_bounded(d, watermark, self._tombstones(d),
                                  heads=heads)

    def snapshot_status(self, app_id: int,
                        channel_id: Optional[int] = None) -> Optional[Dict]:
        from predictionio_tpu.storage import snapshot as _snap

        return _snap.snapshot_status(self._chan_dir(app_id, channel_id))

    def tombstone_state(self, app_id: int,
                        channel_id: Optional[int] = None) -> frozenset:
        """Current tombstone-id set (staging caches key their validity on
        it: any change forces a full restage)."""
        return frozenset(self._tombstones(self._chan_dir(app_id, channel_id)))

    def _maybe_auto_snapshot(self, key: tuple) -> None:
        """Background build once PIO_SNAPSHOT_SEGMENTS uncovered segments
        exist.  Called with self._lock held, on segment rotation only."""
        from predictionio_tpu.storage import snapshot as _snap

        thr = _snap.auto_threshold()
        if thr <= 0 or not _snap.enabled() or key in self._snap_inflight:
            return
        d = self._chan_dir(*key)
        if _snap.uncovered_segments(d) < thr:
            return
        self._snap_inflight.add(key)

        def run():
            try:
                self.build_snapshot(*key)
            except RuntimeError:
                pass     # another process's build already in flight
            except Exception:
                log.warning("auto snapshot build failed for %s", key,
                            exc_info=True)
            finally:
                with self._lock:
                    self._snap_inflight.discard(key)

        threading.Thread(target=run, daemon=True,
                         name="pio-snapshot-build").start()

    def find_batches(
        self,
        app_id: int,
        batch_size: int = 1 << 20,
        **filters: Any,
    ) -> Iterator["EventBatch"]:  # noqa: F821 - forward ref via base
        """Columnar batches served snapshot-first: a valid snapshot plus
        its JSONL tail becomes ONE batch (filters applied columnar), at
        mmap speed; misses stream through the base scan-and-encode path."""
        from predictionio_tpu.storage import snapshot as _snap

        plain = {"channel_id", "start_time", "until_time", "entity_type",
                 "event_names"}
        if set(filters) <= plain:
            res = self.snapshot_scan(app_id, filters.get("channel_id"))
            if res is not None:
                yield _snap.apply_filters(
                    res["batch"],
                    event_names=filters.get("event_names"),
                    entity_type=filters.get("entity_type"),
                    start_time=filters.get("start_time"),
                    until_time=filters.get("until_time"))
                return
        yield from super().find_batches(app_id, batch_size=batch_size,
                                        **filters)

    @staticmethod
    def _iter_segments(segs: Sequence[Path], dead: set,
                       needles: Optional[List[bytes]] = None) -> Iterator[Event]:
        for seg in segs:
            with open(seg, "rb") as f:
                prev = None
                for raw in f:
                    if prev is not None:
                        line = prev.strip()
                        if line and (needles is None
                                     or any(nd in line for nd in needles)):
                            e = Event.from_json(json.loads(line))
                            if e.event_id not in dead:
                                yield e
                    prev = raw
                # an unterminated final line is a torn tail from a writer
                # killed mid-append (never acknowledged — the fsync policy
                # runs after the full write): skip it instead of crashing
                # the scan; the writer truncates it on its next open
                if prev is not None and prev.endswith(b"\n"):
                    line = prev.strip()
                    if line and (needles is None
                                 or any(nd in line for nd in needles)):
                        e = Event.from_json(json.loads(line))
                        if e.event_id not in dead:
                            yield e

    @staticmethod
    def _event_needles(event_names: Optional[Sequence[str]]
                       ) -> Optional[List[bytes]]:
        """Raw-line prefilter for name-filtered scans: a stored line whose
        bytes contain none of these can't have one of the wanted event
        verbs, so the (dominant) json.loads cost is skipped.  Needles use
        json.dumps for the exact escaping both writer paths emit; the
        spaced variant tolerates pretty-printed external lines.  A false
        positive (the needle inside a property VALUE) merely parses — the
        post-parse filter still decides."""
        if event_names is None:
            return None
        needles: List[bytes] = []
        for n in event_names:
            j = json.dumps(n)
            needles.append(f'"event":{j}'.encode())
            needles.append(f'"event": {j}'.encode())
        return needles

    def _iter_raw(self, app_id: int, channel_id: Optional[int],
                  needles: Optional[List[bytes]] = None) -> Iterator[Event]:
        d = self._chan_dir(app_id, channel_id)
        yield from self._iter_segments(
            self.segment_paths(app_id, channel_id), self._tombstones(d),
            needles=needles)

    def get(self, event_id: str, app_id: int, channel_id: Optional[int] = None) -> Optional[Event]:
        return next((e for e in self._iter_raw(app_id, channel_id) if e.event_id == event_id), None)

    def delete(self, event_id: str, app_id: int, channel_id: Optional[int] = None) -> bool:
        d = self._chan_dir(app_id, channel_id)
        with self._lock:
            # Single pass under the lock: confirm the id is live, then tombstone.
            if not any(e.event_id == event_id for e in self._iter_raw(app_id, channel_id)):
                return False
            with open(self._tombstone_path(d), "a") as f:
                f.write(event_id + "\n")
        _notify_append(None)   # entity unknown here: invalidate everything
        return True

    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        limit: Optional[int] = None,
        reversed_order: bool = False,
    ) -> Iterator[Event]:
        if entity_type is not None and entity_id is not None:
            # serving hot path (LEventStore.find_by_entity): read only this
            # entity's lines via the incremental index instead of the log
            candidates = self._entity_index(app_id, channel_id).events(
                entity_type, entity_id, self._tombstones(self._chan_dir(app_id, channel_id))
            )
        else:
            candidates = self._iter_raw(app_id, channel_id)
        matched = (
            e
            for e in candidates
            if base.match_filters(
                e, start_time, until_time, entity_type, entity_id,
                event_names, target_entity_type, target_entity_id,
            )
        )
        ordered = sorted(matched, key=lambda e: (e.event_time, e.creation_time), reverse=reversed_order)
        if limit is not None and limit >= 0:
            ordered = ordered[:limit]
        yield from ordered

    def scan(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
    ) -> Iterator[Event]:
        """Streaming unordered scan over segments — O(segment) memory, unlike
        ``find`` which must sort. This is the bulk-training read path.
        Name-filtered scans prefilter raw lines by substring before
        parsing (see _event_needles)."""
        for e in self._iter_raw(app_id, channel_id,
                                needles=self._event_needles(event_names)):
            if base.match_filters(
                e, start_time, until_time, entity_type, None,
                event_names, target_entity_type, None,
            ):
                yield e
