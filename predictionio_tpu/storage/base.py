"""Storage interfaces (reference: data/src/main/scala/io/prediction/data/storage/).

The reference defines repository interfaces — ``LEvents``, ``PEvents``,
``Models``, ``EngineInstances``, ``EvaluationInstances``, ``Apps``,
``AccessKeys``, ``Channels`` — each implemented by HBase/Elasticsearch/JDBC/
localfs backends and located via ``Storage.scala`` from ``PIO_STORAGE_*`` env
config.  This module defines the same repository surface as Python ABCs.

TPU-first design note: ``PEvents`` in the reference returns Spark RDDs; here
``find_batches`` yields columnar ``EventBatch`` blocks (numpy arrays + string
dictionaries) sized for host→device staging, which is what the JAX training
workflow consumes instead of RDD partitions.
"""

from __future__ import annotations

import abc
import datetime as _dt
import secrets
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence

from predictionio_tpu.events.event import Event, PropertyMap


# ---------------------------------------------------------------------------
# Metadata records (reference: Apps.scala, AccessKeys.scala, Channels.scala,
# EngineInstances.scala, EvaluationInstances.scala)
# ---------------------------------------------------------------------------


@dataclass
class App:
    id: int
    name: str
    description: str = ""


@dataclass
class AccessKey:
    key: str
    app_id: int
    events: List[str] = field(default_factory=list)  # empty = all events allowed

    @staticmethod
    def generate() -> str:
        return secrets.token_urlsafe(32)


@dataclass
class Channel:
    id: int
    name: str
    app_id: int


@dataclass
class EngineInstance:
    id: str
    status: str  # INIT | TRAINING | COMPLETED | FAILED
    start_time: _dt.datetime
    end_time: Optional[_dt.datetime]
    engine_id: str
    engine_version: str
    engine_variant: str
    engine_factory: str
    env: Dict[str, str] = field(default_factory=dict)
    spark_conf: Dict[str, str] = field(default_factory=dict)  # kept for config parity; holds mesh/runtime conf
    data_source_params: str = "{}"
    preparator_params: str = "{}"
    algorithms_params: str = "[]"
    serving_params: str = "{}"


@dataclass
class EngineManifest:
    """Registered engine build (reference: EngineManifest.scala — written by
    `pio build` via RegisterEngine; train/deploy fall back to the registered
    file when the --engine-json path does not exist, keyed by --engine-id/
    --engine-version).  `files` held assembly-jar paths in the reference;
    here it holds the engine.json path."""

    id: str
    version: str
    name: str
    description: str = ""
    files: List[str] = field(default_factory=list)
    engine_factory: str = ""


@dataclass
class EvaluationInstance:
    id: str
    status: str
    start_time: _dt.datetime
    end_time: Optional[_dt.datetime]
    evaluation_class: str
    engine_params_generator_class: str = ""
    env: Dict[str, str] = field(default_factory=dict)
    evaluator_results: str = ""
    evaluator_results_html: str = ""
    evaluator_results_json: str = ""


# ---------------------------------------------------------------------------
# Repository interfaces
# ---------------------------------------------------------------------------


# -- append listeners ---------------------------------------------------------
# In-process subscribers to event-log mutations (the serve lane's user-
# history cache invalidates through this).  A listener is called with a
# list of (entity_type, entity_id) pairs just appended, or None when the
# mutation's entities are unknown / everything may have changed (event
# delete, channel remove, TTL trim).  Listener exceptions never fail a
# write.  Scope is per-process, matching the caches that subscribe.
_APPEND_LISTENERS: List[Any] = []


def add_append_listener(fn) -> None:
    """Subscribe ``fn(entities: Optional[List[tuple]])`` to event-log
    mutations in this process (idempotent per function)."""
    if fn not in _APPEND_LISTENERS:
        _APPEND_LISTENERS.append(fn)


def notify_append(entities: Optional[List[tuple]]) -> None:
    """Called by event backends after a durable mutation; ``entities``
    is the appended (entity_type, entity_id) pairs, or None when
    unknown."""
    for fn in list(_APPEND_LISTENERS):
        try:
            fn(entities)
        except Exception:
            import logging
            logging.getLogger("pio.storage").exception(
                "append listener failed")


class Apps(abc.ABC):
    @abc.abstractmethod
    def insert(self, app: App) -> Optional[int]: ...

    @abc.abstractmethod
    def get(self, app_id: int) -> Optional[App]: ...

    @abc.abstractmethod
    def get_by_name(self, name: str) -> Optional[App]: ...

    @abc.abstractmethod
    def get_all(self) -> List[App]: ...

    @abc.abstractmethod
    def update(self, app: App) -> bool: ...

    @abc.abstractmethod
    def delete(self, app_id: int) -> bool: ...


class AccessKeys(abc.ABC):
    @abc.abstractmethod
    def insert(self, access_key: AccessKey) -> Optional[str]: ...

    @abc.abstractmethod
    def get(self, key: str) -> Optional[AccessKey]: ...

    @abc.abstractmethod
    def get_by_app_id(self, app_id: int) -> List[AccessKey]: ...

    @abc.abstractmethod
    def delete(self, key: str) -> bool: ...


class Channels(abc.ABC):
    @abc.abstractmethod
    def insert(self, channel: Channel) -> Optional[int]: ...

    @abc.abstractmethod
    def get(self, channel_id: int) -> Optional[Channel]: ...

    @abc.abstractmethod
    def get_by_app_id(self, app_id: int) -> List[Channel]: ...

    @abc.abstractmethod
    def delete(self, channel_id: int) -> bool: ...


class EngineInstances(abc.ABC):
    @abc.abstractmethod
    def insert(self, instance: EngineInstance) -> str: ...

    @abc.abstractmethod
    def get(self, instance_id: str) -> Optional[EngineInstance]: ...

    @abc.abstractmethod
    def update(self, instance: EngineInstance) -> bool: ...

    @abc.abstractmethod
    def get_all(self) -> List[EngineInstance]: ...

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[EngineInstance]:
        """Latest COMPLETED instance for an engine triple (reference:
        EngineInstances.getLatestCompleted) — what `pio deploy` binds to."""
        candidates = [
            i
            for i in self.get_all()
            if i.status == "COMPLETED"
            and i.engine_id == engine_id
            and i.engine_version == engine_version
            and i.engine_variant == engine_variant
        ]
        if not candidates:
            return None
        return max(candidates, key=lambda i: i.start_time)

    @abc.abstractmethod
    def delete(self, instance_id: str) -> bool: ...


class EngineManifests(abc.ABC):
    """Engine manifest registry (reference: EngineManifests.scala; keyed by
    (id, version), upserted by `pio build`)."""

    @abc.abstractmethod
    def insert(self, manifest: EngineManifest) -> None: ...

    @abc.abstractmethod
    def get(self, manifest_id: str, version: str) -> Optional[EngineManifest]: ...

    @abc.abstractmethod
    def get_all(self) -> List[EngineManifest]: ...

    def update(self, manifest: EngineManifest) -> None:
        self.insert(manifest)

    @abc.abstractmethod
    def delete(self, manifest_id: str, version: str) -> bool: ...


class EvaluationInstances(abc.ABC):
    @abc.abstractmethod
    def insert(self, instance: EvaluationInstance) -> str: ...

    @abc.abstractmethod
    def get(self, instance_id: str) -> Optional[EvaluationInstance]: ...

    @abc.abstractmethod
    def update(self, instance: EvaluationInstance) -> bool: ...

    @abc.abstractmethod
    def get_completed(self) -> List[EvaluationInstance]: ...


class Models(abc.ABC):
    """Serialized model blobs keyed by engine-instance id (reference: Models.scala)."""

    @abc.abstractmethod
    def insert(self, instance_id: str, blob: bytes) -> None: ...

    @abc.abstractmethod
    def get(self, instance_id: str) -> Optional[bytes]: ...

    @abc.abstractmethod
    def delete(self, instance_id: str) -> bool: ...


# ---------------------------------------------------------------------------
# Event repositories
# ---------------------------------------------------------------------------


class LEvents(abc.ABC):
    """Serving/ingest-time event CRUD (reference: LEvents.scala).

    The reference exposes future-based async ops over HBase; here the ops are
    synchronous (backends are local/embedded) and the REST layer provides
    concurrency.
    """

    @abc.abstractmethod
    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool: ...

    @abc.abstractmethod
    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool: ...

    @abc.abstractmethod
    def insert(self, event: Event, app_id: int, channel_id: Optional[int] = None) -> str: ...

    def insert_batch(
        self, events: Sequence[Event], app_id: int, channel_id: Optional[int] = None
    ) -> List[str]:
        return [self.insert(e, app_id, channel_id) for e in events]

    def insert_json_batch(
        self, items: Sequence, app_id: int, channel_id: Optional[int] = None
    ) -> List[dict]:
        """Batch-insert WIRE-FORMAT dicts with per-item statuses — the
        Event Server's /batch/events.json path.  Returns one
        ``{"status": 201, "eventId": ...}`` or ``{"status": 400,
        "message": ...}`` per input, in order; valid items are inserted in
        ONE backend batch even when some items fail validation.

        Backends with an append-only line format override this to skip the
        Event-object round trip entirely (see localfs — the canonical-dict
        fast path is ~5× cheaper per event).
        """
        results: List[dict] = []
        valid: List[Event] = []
        for item in items:
            try:
                valid.append(Event.from_json(item))
                results.append(None)   # patched with the eventId below
            except (ValueError, KeyError, TypeError) as e:
                results.append({"status": 400, "message": str(e)})
        ids = self.insert_batch(valid, app_id, channel_id) if valid else []
        it = iter(ids)
        for k, r in enumerate(results):
            if r is None:
                results[k] = {"status": 201, "eventId": next(it)}
        return results

    @abc.abstractmethod
    def get(self, event_id: str, app_id: int, channel_id: Optional[int] = None) -> Optional[Event]: ...

    @abc.abstractmethod
    def delete(self, event_id: str, app_id: int, channel_id: Optional[int] = None) -> bool: ...

    @abc.abstractmethod
    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        limit: Optional[int] = None,
        reversed_order: bool = False,
    ) -> Iterator[Event]: ...

    def aggregate_properties(
        self,
        app_id: int,
        entity_type: str,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
    ) -> Dict[str, PropertyMap]:
        from predictionio_tpu.events.event import aggregate_properties

        evs = self.find(
            app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
        )
        return aggregate_properties(evs)


def match_filters(
    e: Event,
    start_time: Optional[_dt.datetime] = None,
    until_time: Optional[_dt.datetime] = None,
    entity_type: Optional[str] = None,
    entity_id: Optional[str] = None,
    event_names: Optional[Sequence[str]] = None,
    target_entity_type: Optional[str] = None,
    target_entity_id: Optional[str] = None,
) -> bool:
    """Shared event-filter predicate used by all backends (reference semantics
    of HBEventsUtil.createScan's column filters)."""
    if start_time is not None and e.event_time < start_time:
        return False
    if until_time is not None and e.event_time >= until_time:
        return False
    if entity_type is not None and e.entity_type != entity_type:
        return False
    if entity_id is not None and e.entity_id != entity_id:
        return False
    if event_names is not None and e.event not in event_names:
        return False
    if target_entity_type is not None and e.target_entity_type != target_entity_type:
        return False
    if target_entity_id is not None and e.target_entity_id != target_entity_id:
        return False
    return True


class StoreCapabilityError(NotImplementedError):
    """An event backend was asked for an optional capability it does not
    provide (e.g. the ``scan_tail_from``/``scan_events_up_to`` delta-tail
    protocol that ``pio deploy --follow`` and delta staging need).  Raised
    with an actionable message naming the backend and the capability, so
    the failure is a one-line diagnosis instead of an AttributeError deep
    in a worker thread."""


def delta_tail_supported(backend) -> bool:
    """True when ``backend`` implements the delta-tail protocol
    (``scan_tail_from`` + ``scan_events_up_to`` + ``tombstone_state``) —
    the capability the follow-trainer's fold mode and the retained-batch
    staging cache require.  localfs/sharedfs/sharded/memory do; a backend
    that can't should leave the methods undefined and callers surface
    :class:`StoreCapabilityError` (or degrade) with a clear message."""
    return all(
        callable(getattr(backend, name, None))
        for name in ("scan_tail_from", "scan_events_up_to",
                     "tombstone_state"))


def require_delta_tail(backend, what: str) -> None:
    """Raise :class:`StoreCapabilityError` with a clear, actionable
    message when ``backend`` lacks the delta-tail protocol."""
    if not delta_tail_supported(backend):
        raise StoreCapabilityError(
            f"{what} requires the event backend to support the delta-tail "
            f"protocol (scan_tail_from/scan_events_up_to/tombstone_state), "
            f"but {type(backend).__module__}.{type(backend).__name__} does "
            "not provide it; use a localfs, sharedfs, sharded, or memory "
            "event store, or implement the protocol on the backend")


class PEvents(abc.ABC):
    """Bulk training-time reads (reference: PEvents.scala returns RDD[Event]).

    TPU-native shape: iterate columnar batches ready for host→device staging.
    """

    @abc.abstractmethod
    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
    ) -> Iterator[Event]: ...

    def scan(self, app_id: int, **filters: Any) -> Iterator[Event]:
        """Unordered streaming bulk scan. Backends whose ``find`` must sort
        (and therefore materialize) override this with a true stream; the
        training path never needs time ordering."""
        return self.find(app_id, **filters)

    # -- columnar snapshot plumbing (optional per backend) -------------------
    # Segment-file backends (localfs/sharedfs) persist columnar snapshots
    # of the event log and serve find_batches from them at mmap speed,
    # parsing only the uncovered JSONL tail.  The default hooks say "no
    # snapshot": find_batches then streams through scan() as always.

    def snapshot_scan(self, app_id: int,
                      channel_id: Optional[int] = None) -> Optional[Dict]:
        """{"batch", "ids", "watermark", ...} from a persisted columnar
        snapshot + tail, or None when the backend has none (the default)."""
        return None

    def snapshot_status(self, app_id: int,
                        channel_id: Optional[int] = None) -> Optional[Dict]:
        """Coverage summary for dashboards, or None without snapshots."""
        return None

    def find_batches(
        self,
        app_id: int,
        batch_size: int = 1 << 20,
        **filters: Any,
    ) -> Iterator["EventBatch"]:
        """Columnar batches for training reads.  Backends with snapshot
        support override this to serve one snapshot+tail batch instead of
        re-encoding every event through this scan loop."""
        from predictionio_tpu.store.columnar import EventBatch

        buf: List[Event] = []
        for e in self.scan(app_id, **filters):
            buf.append(e)
            if len(buf) >= batch_size:
                yield EventBatch.from_events(buf)
                buf = []
        if buf:
            yield EventBatch.from_events(buf)
