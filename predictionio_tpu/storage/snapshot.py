"""Columnar event-store snapshots — mmap-speed training scans.

The JSONL segment log is the system of record; every cold ``pio train``
used to re-parse it (native C++ scan ~0.5–0.6 M ev/s, JSON-parse-bound).
A snapshot folds the segments into ONE binary struct-of-arrays file
(``store.columnar`` container: int32 code columns + string dictionaries +
int64 timestamps + an event-id column) so training reads memory-mapped
columns at page-cache speed and only the *uncovered JSONL tail* — events
appended since the last build — still pays a parse.

Layout, per (app, channel) directory::

    events/app_<id>/<chan>/snapshot/
        manifest.json          what the snapshot covers (atomic replace)
        snap-<writer>-<id>.pioc  the columnar file (tmp + fsync + rename)
        .lock                  flock held for a build's whole duration

The manifest records the covered byte range of every segment (up to the
last complete line at build time — segments are append-only, so the tail
scan resumes exactly there), the applied tombstone set, and an
event-count watermark.  Builds are crash-safe two-phase: a kill at any
instant leaves either the old manifest + old snapshot (tmp ignored) or
the new pair; readers never see a half state.  A torn/corrupt snapshot
file is quarantined on first read and rebuilt by the next trigger.

Multi-writer stores (prefork event servers, sharedfs multi-host) share
one snapshot: any writer tag may build, every reader validates the
manifest against the live segment set, and last-writer-wins manifest
replaces are self-consistent.
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import os
import threading
import time
import uuid
from pathlib import Path
from typing import Dict, List, Optional, Sequence

import numpy as np

from predictionio_tpu.obs.metrics import LATENCY_BUCKETS, get_registry
from predictionio_tpu.store.columnar import (
    EventBatch,
    EventIdColumn,
    IdDict,
    PropColumn,
    read_batch,
    write_batch,
)

log = logging.getLogger("pio.snapshot")

SNAP_DIR = "snapshot"
MANIFEST = "manifest.json"
LOCK = ".lock"

_REG = get_registry()
_M_BUILD_S = _REG.histogram(
    "pio_snapshot_build_duration_seconds",
    "Wall-clock duration of snapshot builds", buckets=LATENCY_BUCKETS)
_M_BUILDS = _REG.counter(
    "pio_snapshot_builds_total", "Snapshot builds by final status")
_M_EVENTS = _REG.gauge(
    "pio_snapshot_events",
    "Events in the last-built snapshot, by channel")
_M_HITS = _REG.counter(
    "pio_snapshot_scan_hits_total",
    "Training scans served from a snapshot (+ tail)")
_M_MISSES = _REG.counter(
    "pio_snapshot_scan_misses_total",
    "Training scans that fell back to a full JSONL parse")
_M_QUAR = _REG.counter(
    "pio_snapshot_quarantined_total",
    "Torn/corrupt snapshot files set aside for rebuild")
_M_STAGED = _REG.counter(
    "pio_stage_events_total",
    "Events staged into columnar batches by source: snapshot = served "
    "from the mmap'd file, tail = parsed from the uncovered JSONL tail, "
    "delta = parsed past a retained batch's watermark on retrain")


def enabled() -> bool:
    """PIO_SNAPSHOT=off disables the snapshot READ path and auto-trigger
    (builds via CLI still work, for pre-warming before re-enabling)."""
    return os.environ.get("PIO_SNAPSHOT", "").lower() not in (
        "off", "0", "false")


def auto_threshold() -> int:
    """PIO_SNAPSHOT_SEGMENTS=N: the event-log writer auto-triggers a
    background build once N segments exist that the current snapshot
    doesn't cover (0 = disabled, the default — builds are `pio snapshot`
    or programmatic otherwise)."""
    try:
        return max(0, int(os.environ.get("PIO_SNAPSHOT_SEGMENTS", "0")))
    except ValueError:
        return 0


def _chan_label(d: Path) -> str:
    return f"{d.parent.name}/{d.name}"


def _segment_head(seg: Path, consumed: int) -> Optional[Dict[str, int]]:
    """Identity fingerprint of a consumed segment prefix: CRC of its first
    min(64, consumed) bytes.  Segment NAMES can recur with fresh content
    (data-delete + re-import restarts writer numbering at seg-00000), and
    a size check alone passes once the new file outgrows the recorded
    offset — byte offsets into such a file are meaningless and parsing
    from them would crash or, worse, silently splice two generations of
    data.  The first line embeds a unique eventId, so 64 bytes suffice."""
    import zlib

    n = min(64, consumed)
    if n <= 0:
        return None
    try:
        with open(seg, "rb") as f:
            return {"n": n, "crc": zlib.crc32(f.read(n))}
    except OSError:
        return None


def _head_matches(seg: Path, head: Optional[Dict[str, int]]) -> bool:
    if not head:
        return True      # nothing was consumed: nothing to mismatch
    cur = _segment_head(seg, int(head["n"]))
    return cur is not None and cur["crc"] == head["crc"]


def _last_newline_boundary(path: Path, size: int) -> int:
    """Byte offset just past the last complete line within ``size`` bytes
    (0 if none) — the snapshot never covers a torn tail, and a writer's
    truncate-heal only ever removes bytes PAST this boundary."""
    if size <= 0:
        return 0
    with open(path, "rb") as f:
        pos = size
        while pos > 0:
            step = min(64 * 1024, pos)
            f.seek(pos - step)
            chunk = f.read(step)
            nl = chunk.rfind(b"\n")
            if nl >= 0:
                return pos - step + nl + 1
            pos -= step
    return 0


class ColumnarBuilder:
    """Streaming wire-dict → struct-of-arrays builder.

    The Python analogue of the native scanner's output (same columns,
    same property-column kinds) plus an event-id column.  With ``base``
    set, codes are assigned IN the base batch's dictionaries (mutating
    them in place) so the result concatenates with the base via the
    shared-dict fast path — no re-coding, no dictionary rescans.
    """

    def __init__(self, base: Optional[EventBatch] = None):
        if base is not None:
            self.event_dict = base.event_dict
            self.entity_type_dict = base.entity_type_dict
            self.entity_dict = base.entity_dict
            self.target_dict = base.target_dict
        else:
            self.event_dict = IdDict()
            self.entity_type_dict = IdDict()
            self.entity_dict = IdDict()
            self.target_dict = IdDict()
        self._base_props = (base.prop_columns or {}) if base is not None else {}
        self._ev: List[int] = []
        self._et: List[int] = []
        self._ei: List[int] = []
        self._ti: List[int] = []
        self._ts: List[int] = []
        self._rt: List[float] = []
        self._ids: List[str] = []
        self._props: Dict[str, dict] = {}

    def __len__(self) -> int:
        return len(self._ev)

    def add(self, d: dict) -> None:
        """Append one stored wire-format event dict (a parsed log line)."""
        from predictionio_tpu.events.event import parse_time  # no-cycle: lazy

        row = len(self._ev)
        self._ev.append(self.event_dict.add(d["event"]))
        self._et.append(self.entity_type_dict.add(d["entityType"]))
        self._ei.append(self.entity_dict.add(str(d["entityId"])))
        tei = d.get("targetEntityId")
        self._ti.append(self.target_dict.add(str(tei))
                        if tei is not None else -1)
        self._ts.append(int(parse_time(d.get("eventTime")).timestamp() * 1e6))
        props = d.get("properties") or {}
        r = props.get("rating")
        # bool counts as numeric here, mirroring EventBatch.from_events
        self._rt.append(float(r) if isinstance(r, (int, float)) else np.nan)
        self._ids.append(d.get("eventId") or "")
        for key, val in props.items():
            self._add_prop(key, row, val)

    def _add_prop(self, key: str, row: int, val) -> None:
        p = self._props.get(key)
        if p is None:
            base_col = self._base_props.get(key)
            p = self._props[key] = {
                "rows": [], "kind": [], "num": [], "strs": [],
                "dict": base_col.dict if base_col is not None else IdDict(),
            }
        # kinds mirror PropColumn.value_at: 0 num, 1 bool, 2 str,
        # 3 str-list, 4 null, 5 nested (raw JSON span)
        if isinstance(val, bool):
            kind, num, strs = 1, float(val), ()
        elif isinstance(val, (int, float)):
            kind, num, strs = 0, float(val), ()
        elif isinstance(val, str):
            kind, num, strs = 2, 0.0, (val,)
        elif val is None:
            kind, num, strs = 4, 0.0, ()
        elif isinstance(val, list) and all(isinstance(x, str) for x in val):
            kind, num, strs = 3, 0.0, tuple(val)
        else:
            kind, num, strs = 5, 0.0, (json.dumps(val),)
        p["rows"].append(row)
        p["kind"].append(kind)
        p["num"].append(num)
        p["strs"].append(strs)

    def finish(self) -> tuple:
        """→ (EventBatch with prop_columns, EventIdColumn)."""
        n = len(self._ev)
        props: Dict[str, PropColumn] = {}
        for key, p in self._props.items():
            offs = np.zeros(len(p["rows"]) + 1, np.int64)
            np.cumsum([len(s) for s in p["strs"]], out=offs[1:])
            flat = [s for strs in p["strs"] for s in strs]
            props[key] = PropColumn(
                rows=np.asarray(p["rows"], np.int64),
                kind=np.asarray(p["kind"], np.int8),
                num=np.asarray(p["num"], np.float64),
                str_offs=offs,
                codes=p["dict"].encode(flat) if flat else np.empty(0, np.int32),
                dict=p["dict"],
            )
        batch = EventBatch(
            np.asarray(self._ev, np.int32), np.asarray(self._et, np.int32),
            np.asarray(self._ei, np.int32), np.asarray(self._ti, np.int32),
            np.asarray(self._ts, np.int64),
            np.asarray(self._rt, np.float32) if n else np.empty(0, np.float32),
            self.event_dict, self.entity_type_dict, self.entity_dict,
            self.target_dict, prop_columns=props,
        )
        return batch, EventIdColumn.from_ids(self._ids)


def _parse_range(seg: Path, start: int, end: int, dead: set,
                 builder: ColumnarBuilder, delay: float = 0.0) -> int:
    """Parse complete lines in ``seg[start:end)`` into ``builder``,
    skipping tombstoned ids.  Returns the number of events added."""
    added = 0
    with open(seg, "rb") as f:
        f.seek(start)
        data = f.read(end - start)
    for line in data.split(b"\n"):
        if not line.strip():
            continue
        if delay:
            time.sleep(delay)   # test hook: widen the kill-mid-build window
        d = json.loads(line)
        if d.get("eventId") in dead:
            continue
        builder.add(d)
        added += 1
    return added


def load_manifest(d: Path) -> Optional[dict]:
    p = d / SNAP_DIR / MANIFEST
    try:
        m = json.loads(p.read_text())
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(m, dict) or "snapshot" not in m or "covered" not in m:
        return None
    return m


def _fsync_write(path: Path, text: str) -> None:
    """tmp + fsync + atomic rename — the manifest's durability contract."""
    tmp = path.with_suffix(path.suffix + f".tmp{os.getpid()}")
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    tmp.replace(path)


def build_snapshot(d: Path, tombstones: set, writer: str) -> dict:
    """Fold every complete line of every segment into a fresh snapshot.

    Two-phase: columns stream into ``snap-*.pioc.tmp<pid>`` (invisible to
    readers), fsync, atomic rename, THEN the manifest is atomically
    replaced — a SIGKILL at any instant leaves a fully readable store.
    Exactly-once across processes/hosts via a non-blocking flock; losing
    the race raises RuntimeError("snapshot build already in progress").

    Returns {"events", "segments", "build_s", "snapshot"}.
    """
    import fcntl

    snap_dir = d / SNAP_DIR
    snap_dir.mkdir(parents=True, exist_ok=True)
    lockf = open(snap_dir / LOCK, "a")
    try:
        try:
            fcntl.flock(lockf.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            raise RuntimeError(
                "snapshot build already in progress for this channel")
        t0 = time.perf_counter()
        try:
            delay = float(os.environ.get("PIO_SNAPSHOT_TEST_DELAY_S") or 0.0)
        except ValueError:
            delay = 0.0
        for stale in snap_dir.glob("*.tmp*"):
            stale.unlink(missing_ok=True)
        covered: Dict[str, int] = {}
        heads: Dict[str, Dict[str, int]] = {}
        builder = ColumnarBuilder()
        n = 0
        try:
            for seg in sorted(d.glob("seg-*.jsonl")):
                try:
                    size = seg.stat().st_size
                except FileNotFoundError:
                    continue     # racing a data-delete
                end = _last_newline_boundary(seg, size)
                covered[seg.name] = end
                head = _segment_head(seg, end)
                if head is not None:
                    heads[seg.name] = head
                if end > 0:
                    n += _parse_range(seg, 0, end, tombstones, builder, delay)
            batch, ids = builder.finish()
            name = f"snap-{writer}-{uuid.uuid4().hex[:8]}.pioc"
            tmp = snap_dir / (name + f".tmp{os.getpid()}")
            write_batch(tmp, batch, ids, meta={
                "writer": writer, "events": n})
            tmp.rename(snap_dir / name)
            manifest = {
                "version": 1,
                "snapshot": name,
                "covered": covered,
                "heads": heads,
                "events": n,                     # event-count watermark
                "tombstones_applied": sorted(tombstones),
                "built_at": _dt.datetime.now(
                    _dt.timezone.utc).isoformat(),
                "build_s": round(time.perf_counter() - t0, 6),
                "writer": writer,
            }
            _fsync_write(snap_dir / MANIFEST, json.dumps(
                manifest, indent=1, sort_keys=True))
        except Exception:
            _M_BUILDS.inc(1, status="failed")
            raise
        # superseded snapshot files: unlink AFTER the manifest flip so a
        # reader holding the old manifest raced at worst into a miss
        for p in snap_dir.glob("snap-*.pioc"):
            if p.name != name:
                p.unlink(missing_ok=True)
        build_s = time.perf_counter() - t0
        _M_BUILD_S.observe(build_s)
        _M_BUILDS.inc(1, status="ok")
        _M_EVENTS.set(n, channel=_chan_label(d))
        log.info("snapshot built: %s %d events / %d segments in %.3fs",
                 _chan_label(d), n, len(covered), build_s)
        return {"events": n, "segments": len(covered),
                "build_s": build_s, "snapshot": name}
    finally:
        lockf.close()   # closing releases the flock


def _quarantine(snap_dir: Path, name: str) -> None:
    """Set a torn/corrupt snapshot aside (kept for forensics) and drop the
    manifest so the next trigger rebuilds instead of re-tripping."""
    try:
        (snap_dir / name).rename(snap_dir / (name + ".quarantine"))
    except OSError:
        pass
    (snap_dir / MANIFEST).unlink(missing_ok=True)
    _M_QUAR.inc()
    log.warning("quarantined torn snapshot %s", snap_dir / name)


def scan_tail(d: Path, watermark: Dict[str, int], tombstones: set,
              base: Optional[EventBatch],
              heads: Optional[Dict[str, dict]] = None) -> Optional[dict]:
    """Parse only the log bytes past ``watermark`` (per-segment covered
    byte offsets; unlisted segments are wholly new).

    Returns {"batch", "ids", "events", "watermark", "heads"} — the tail
    batch shares ``base``'s dictionaries when given — or None when the
    watermark no longer describes the live log: a segment vanished or
    shrank (compaction/data-delete), its head fingerprint changed (a
    recreated file reusing the name), or the bytes at the offset don't
    parse (any stale-offset case the cheaper checks miss).  Callers
    treat None as a full restage."""
    segs = sorted(d.glob("seg-*.jsonl")) if d.exists() else []
    names = {s.name for s in segs}
    for name in watermark:
        if name not in names:
            return None
    builder = ColumnarBuilder(base=base)
    new_mark = dict(watermark)
    new_heads: Dict[str, Dict[str, int]] = {}
    n = 0
    for seg in segs:
        start = watermark.get(seg.name, 0)
        try:
            size = seg.stat().st_size
        except FileNotFoundError:
            return None
        if size < start:
            return None          # shrank under the watermark: invalid
        if heads is not None and not _head_matches(seg, heads.get(seg.name)):
            return None          # same name, different content generation
        if size == start:
            # nothing appended: the verified head still describes exactly
            # `start` consumed bytes — skip the boundary scan and the
            # fingerprint re-read (a cross-shard scan pays this loop once
            # per shard, so the idle-segment case must stay cheap)
            new_mark[seg.name] = start
            head = (heads.get(seg.name) if heads is not None
                    else _segment_head(seg, start))
            if head is not None:
                new_heads[seg.name] = head
            continue
        end = _last_newline_boundary(seg, size)
        new_mark[seg.name] = max(end, start)
        head = _segment_head(seg, new_mark[seg.name])
        if head is not None:
            new_heads[seg.name] = head
        if end > start:
            try:
                n += _parse_range(seg, start, end, tombstones, builder)
            except (json.JSONDecodeError, UnicodeDecodeError, KeyError,
                    TypeError, ValueError):
                return None      # stale offset mid-line / foreign bytes
    batch, ids = builder.finish()
    return {"batch": batch, "ids": ids, "events": n,
            "watermark": new_mark, "heads": new_heads}


def scan_bounded(d: Path, watermark: Dict[str, int],
                 tombstones: set,
                 heads: Optional[Dict[str, dict]] = None) -> Optional[dict]:
    """Parse the log UP TO ``watermark`` (per-segment byte offsets) —
    the follow-trainer's crash-restart read: reconstruct exactly the
    event set a persisted watermark describes, so the restart re-folds
    only the unapplied suffix instead of double-folding or re-training
    blind.  Returns {"batch", "events"} or None when the watermark no
    longer matches the live log (segment gone/shrank/recreated — caller
    falls back to a full restage)."""
    builder = ColumnarBuilder()
    n = 0
    for name in sorted(watermark):
        seg = d / name
        end = int(watermark[name])
        try:
            size = seg.stat().st_size
        except OSError:
            return None          # covered segment vanished
        if size < end:
            return None          # shrank under the watermark
        if heads is not None and not _head_matches(seg, heads.get(name)):
            return None          # recreated file reusing the name
        if end > 0:
            try:
                n += _parse_range(seg, 0, end, tombstones, builder)
            except (json.JSONDecodeError, UnicodeDecodeError, KeyError,
                    TypeError, ValueError):
                return None      # stale offset / foreign bytes
    batch, _ids = builder.finish()
    return {"batch": batch, "events": n}


def drop_tombstoned(batch: EventBatch, ids: EventIdColumn,
                    new_dead: set) -> tuple:
    """Mask rows whose event id was tombstoned AFTER a snapshot was
    built → (batch, ids).  Shared by the per-channel snapshot read and
    the sharded store's merged cross-shard snapshot."""
    if not new_dead:
        return batch, ids
    mask = np.ones(len(batch), bool)
    for eid in new_dead:
        r = ids.index_of(eid)
        if r >= 0:
            mask[r] = False
    if not mask.all():
        batch = batch.subset(mask)
        ids = ids.subset(mask)
    return batch, ids


def scan_snapshot(d: Path, tombstones: set) -> Optional[dict]:
    """The snapshot-or-tail read: mmap the covered columns, parse only the
    uncovered tail, splice via the shared-dict concat fast path.

    Returns None (a miss — caller falls back to a full JSONL scan) when
    there is no valid snapshot for the CURRENT log state: no manifest, a
    covered segment vanished/shrank (compaction, data-delete), tombstones
    receded, or the file is torn (then also quarantined).  Events
    tombstoned AFTER the build are dropped via the snapshot's id column,
    so a pre-delete snapshot can never resurface them.

    Hit result: {"batch", "ids", "snap_events", "tail_events",
    "watermark", "manifest"}.
    """
    m = load_manifest(d)
    if m is None:
        return None
    snap_dir = d / SNAP_DIR
    covered: Dict[str, int] = m["covered"]
    heads: Dict[str, dict] = m.get("heads", {})
    for name, end in covered.items():
        p = d / name
        try:
            if p.stat().st_size < end:
                return None      # covered bytes no longer exist
        except OSError:
            return None          # segment gone (compaction/data-delete)
        if not _head_matches(p, heads.get(name)):
            return None          # recreated file reusing a covered name
    applied = set(m.get("tombstones_applied", ()))
    if applied - tombstones:
        return None              # tombstones receded: log was rewritten
    try:
        batch, ids, _meta = read_batch(snap_dir / m["snapshot"])
    except FileNotFoundError:
        return None              # raced a concurrent rebuild's cleanup
    except (ValueError, OSError):
        _quarantine(snap_dir, m["snapshot"])
        return None
    if ids is None:
        return None
    batch, ids = drop_tombstoned(batch, ids, tombstones - applied)
    snap_events = len(batch)
    tail = scan_tail(d, covered, tombstones, base=batch, heads=heads)
    if tail is None:
        return None
    if tail["events"]:
        batch = EventBatch.concat([batch, tail["batch"]])
        ids = EventIdColumn.concat([ids, tail["ids"]])
    _M_STAGED.inc(snap_events, mode="snapshot")
    if tail["events"]:
        _M_STAGED.inc(tail["events"], mode="tail")
    return {"batch": batch, "ids": ids, "snap_events": snap_events,
            "tail_events": tail["events"], "watermark": tail["watermark"],
            "heads": tail["heads"], "manifest": m}


def uncovered_segments(d: Path) -> int:
    """Segments the current snapshot doesn't list — the auto-trigger's
    staleness measure."""
    m = load_manifest(d)
    covered = set(m["covered"]) if m else set()
    if not d.exists():
        return 0
    return sum(1 for s in d.glob("seg-*.jsonl") if s.name not in covered)


# status is wired into scrape-frequency endpoints (/stats.json, the
# dashboard page) while the tail-event count needs a read of every
# uncovered byte — memoize per channel on the (segment name, size,
# covered offset) signature so a growing-but-unpolled log is read once
# per change, not once per scrape
_status_lock = threading.Lock()
_status_cache: Dict[str, dict] = {}


def snapshot_status(d: Path) -> Optional[dict]:
    """Coverage summary for dashboards//stats.json, or None when the
    channel has no snapshot.  ``tailEvents`` counts complete lines past
    the covered offsets (tombstones not subtracted — this is a coverage
    view, not a scan)."""
    m = load_manifest(d)
    if m is None:
        return None
    covered: Dict[str, int] = m["covered"]
    segs = sorted(d.glob("seg-*.jsonl")) if d.exists() else []
    sizes = []
    for seg in segs:
        try:
            sizes.append((seg, seg.stat().st_size))
        except OSError:
            continue
    sig = (m.get("snapshot"),) + tuple(
        (seg.name, size, covered.get(seg.name, 0)) for seg, size in sizes)
    with _status_lock:
        hit = _status_cache.get(str(d))
        if hit is not None and hit["sig"] == sig:
            tail_events, tail_bytes = hit["tail_events"], hit["tail_bytes"]
            sizes = []           # nothing to recount
        else:
            tail_events = tail_bytes = 0
    for seg, size in sizes:
        start = covered.get(seg.name, 0)
        end = _last_newline_boundary(seg, size)
        if end > start:
            tail_bytes += end - start
            with open(seg, "rb") as f:
                f.seek(start)
                tail_events += f.read(end - start).count(b"\n")
    if sizes or hit is None:
        with _status_lock:
            if len(_status_cache) > 256:
                _status_cache.clear()
            _status_cache[str(d)] = {"sig": sig, "tail_events": tail_events,
                                     "tail_bytes": tail_bytes}
    snap_events = int(m.get("events", 0))
    total = snap_events + tail_events
    return {
        "events": snap_events,
        "tailEvents": tail_events,
        "tailBytes": tail_bytes,
        "coverage": (snap_events / total) if total else 1.0,
        "builtAt": m.get("built_at"),
        "buildSeconds": m.get("build_s"),
        "snapshot": m.get("snapshot"),
        "writer": m.get("writer"),
        "segmentsCovered": len(covered),
    }


def apply_filters(batch: EventBatch,
                  event_names: Optional[Sequence[str]] = None,
                  entity_type: Optional[str] = None,
                  start_time: Optional[_dt.datetime] = None,
                  until_time: Optional[_dt.datetime] = None) -> EventBatch:
    """Columnar equivalent of the scan filters (same semantics as
    storage.base.match_filters for these four), shared by every
    snapshot-backed read path."""
    mask = np.ones(len(batch), bool)
    if event_names is not None:
        codes = [batch.event_dict.id(n) for n in event_names]
        codes = [c for c in codes if c is not None]
        mask &= np.isin(batch.event_codes, np.asarray(codes, np.int32))
    if entity_type is not None:
        c = batch.entity_type_dict.id(entity_type)
        mask &= np.asarray(batch.entity_type_codes) == (
            c if c is not None else -2)
    if start_time is not None:
        mask &= np.asarray(batch.times_us) >= int(
            start_time.timestamp() * 1e6)
    if until_time is not None:
        mask &= np.asarray(batch.times_us) < int(
            until_time.timestamp() * 1e6)
    return batch.subset(mask) if not mask.all() else batch


def record_hit() -> None:
    _M_HITS.inc()


def record_miss() -> None:
    _M_MISSES.inc()


def record_delta(n: int) -> None:
    _M_STAGED.inc(n, mode="delta")


def record_staged(n: int, mode: str) -> None:
    """Staged-event accounting for backends that serve columnar batches
    without routing through scan_snapshot (the sharded store's merged
    cross-shard snapshot)."""
    if n:
        _M_STAGED.inc(n, mode=mode)


def staged_counts() -> Dict[str, float]:
    """Current staged-event counter values by mode (snapshot/tail/delta) —
    the exactness hook for delta-retrain assertions and train spans."""
    return {mode: _M_STAGED.value(mode=mode)
            for mode in ("snapshot", "tail", "delta")}


def publish_status_gauges(status: dict, channel: str) -> None:
    """Mirror a status dict onto pio_snapshot_* gauges (dashboard scrapes)."""
    _M_EVENTS.set(status["events"], channel=channel)
    _REG.gauge(
        "pio_snapshot_tail_events",
        "Events in the uncovered JSONL tail, by channel",
    ).set(status["tailEvents"], channel=channel)
    _REG.gauge(
        "pio_snapshot_coverage_ratio",
        "Events in snapshot / total events, by channel",
    ).set(status["coverage"], channel=channel)
    if status.get("builtAt"):
        try:
            ts = _dt.datetime.fromisoformat(status["builtAt"]).timestamp()
        except ValueError:
            ts = 0.0
        _REG.gauge(
            "pio_snapshot_last_build_timestamp_seconds",
            "Unix time of the last snapshot build, by channel",
        ).set(ts, channel=channel)
    if status.get("buildSeconds") is not None:
        _REG.gauge(
            "pio_snapshot_last_build_seconds",
            "Duration of the last snapshot build, by channel",
        ).set(float(status["buildSeconds"]), channel=channel)
