"""CLI→workflow glue (reference: core/.../workflow/CreateWorkflow.scala +
WorkflowUtils engine-variant parsing).

Resolves the engine factory named in engine.json (dotted import path or a
built-in template shortname from models.ENGINE_FACTORIES), binds the variant's
params blocks to typed EngineParams, and dispatches to CoreWorkflow.
"""

from __future__ import annotations

import importlib
import json
import logging
import sys
from pathlib import Path
from typing import Any, Dict, Optional, Tuple, Type

from predictionio_tpu.controller.engine import Engine, EngineFactory, EngineParams
from predictionio_tpu.models import ENGINE_FACTORIES
from predictionio_tpu.workflow import core_workflow

log = logging.getLogger("pio.workflow")


def resolve_engine_factory(name: str) -> Type[EngineFactory]:
    """Import the EngineFactory class for a dotted path or template shortname."""
    dotted = ENGINE_FACTORIES.get(name, name)
    module_name, _, cls_name = dotted.rpartition(".")
    if not module_name:
        raise ValueError(
            f"engineFactory {name!r} is not a dotted path or known template "
            f"({sorted(ENGINE_FACTORIES)})"
        )
    # engine.json lives next to user code; make its directory importable the
    # way the reference adds the engine assembly jar to the classpath.
    module = importlib.import_module(module_name)
    factory = getattr(module, cls_name)
    if not (isinstance(factory, type) and issubclass(factory, EngineFactory)):
        raise TypeError(f"{dotted} is not an EngineFactory subclass")
    return factory


def load_engine_variant(engine_json: str, variant_id: str = "default") -> Dict[str, Any]:
    """Load engine.json; supports both a single variant document and the
    reference's ``engineFactory`` + per-variant files."""
    path = Path(engine_json)
    if not path.exists():
        raise FileNotFoundError(f"engine variant file {engine_json!r} not found")
    doc = json.loads(path.read_text())
    if "engineFactory" not in doc:
        raise ValueError(f"{engine_json}: missing required key 'engineFactory'")
    # engine.json lives next to user code; make its directory importable the
    # way the reference adds the engine assembly jar to the classpath, so
    # engineFactory can name a module local to the engine directory.
    parent = str(path.resolve().parent)
    if parent not in sys.path:
        sys.path.insert(0, parent)
    return doc


def resolve_variant_path(args) -> str:
    """Resolve the engine.json path for a workflow command: the --engine-json
    path if it exists, else the file registered by `pio build` for
    (--engine-id, --engine-version) (reference: RunWorkflow resolving the
    engine via its EngineManifest)."""
    if Path(args.engine_json).exists():
        return args.engine_json
    engine_id = getattr(args, "engine_id", None)
    if engine_id:
        from predictionio_tpu.storage import get_storage

        manifest = get_storage().engine_manifests.get(
            engine_id, getattr(args, "engine_version", "1")
        )
        if manifest and manifest.files and Path(manifest.files[0]).exists():
            log.info("resolved engine %s via manifest: %s", engine_id, manifest.files[0])
            return manifest.files[0]
    return args.engine_json  # let load_engine_variant raise FileNotFoundError


def engine_from_variant(
    variant: Dict[str, Any]
) -> Tuple[Type[EngineFactory], Engine, EngineParams]:
    factory = resolve_engine_factory(variant["engineFactory"])
    engine = factory.apply()
    engine_params = engine.engine_params_from_variant(variant)
    return factory, engine, engine_params


def resolve_engine_id(
    cli_engine_id: Optional[str], variant: Dict[str, Any], factory: Type[EngineFactory]
) -> str:
    """Single precedence rule for the engine id, shared by build/train/deploy:
    explicit --engine-id > engine.json "id" > factory class name."""
    return cli_engine_id or variant.get("id") or factory.engine_id()


def _describe(obj) -> str:
    """One-line structural summary of a training-data object for the
    stop-after-read/prepare debug output."""
    import dataclasses as _dc

    import numpy as _np

    bits = [type(obj).__name__]
    if _dc.is_dataclass(obj) and not isinstance(obj, type):
        for f in _dc.fields(obj):
            v = getattr(obj, f.name)
            if isinstance(v, _np.ndarray):
                bits.append(f"{f.name}[{v.shape} {v.dtype}]")
            elif isinstance(v, dict):
                bits.append(f"{f.name}{{{len(v)}}}")
            elif hasattr(v, "__len__"):
                bits.append(f"{f.name}({len(v)})")
    elif hasattr(obj, "__len__"):
        bits.append(f"len={len(obj)}")
    return " ".join(bits)


def run_train_from_args(args) -> int:
    """`pio train` entry (reference: Console.train → RunWorkflow →
    CreateWorkflow.main)."""
    try:
        # no-op single-process; on a multi-host fleet (PIO_COORDINATOR_ADDRESS
        # et al.) this joins the global runtime before any mesh is built
        from predictionio_tpu.parallel.distributed import init_distributed

        init_distributed()
        variant = load_engine_variant(resolve_variant_path(args), args.variant)
        factory, engine, engine_params = engine_from_variant(variant)
        engine_id = resolve_engine_id(args.engine_id, variant, factory)
        stop_read = getattr(args, "stop_after_read", False)
        stop_prepare = getattr(args, "stop_after_prepare", False)
        if stop_read or stop_prepare:
            # reference WorkflowParams stopAfterRead/stopAfterPrepare:
            # sanity-check the data pipeline without training/persisting
            data_source, preparator, _algos, _serving = engine.make_components(
                engine_params)
            td = data_source.read_training()
            print(f"read_training -> {_describe(td)}")
            if stop_prepare:
                pd = preparator.prepare(td)
                print(f"prepare -> {_describe(pd)}")
            print("Stopped before training (debug flag).")
            return 0
        if getattr(args, "follow", False):
            return _run_follow(args, variant, engine, engine_params,
                               engine_id)
        instance = core_workflow.run_train(
            engine,
            engine_params,
            engine_id=engine_id,
            engine_version=args.engine_version,
            engine_variant=args.variant,
            engine_factory=variant["engineFactory"],
        )
    except Exception as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"Training completed. Engine instance id: {instance.id}")
    return 0


def _run_follow(args, variant, engine, engine_params, engine_id: str) -> int:
    """`pio train --follow` — the resident follow-trainer daemon: train
    (or resume from the persisted watermark), then tail the event store
    and publish an incrementally-folded COMPLETED engine instance per
    batch of new events.  Deployments started with ``--auto-reload``
    hot-swap to each generation within their poll interval."""
    from predictionio_tpu.streaming.follow import FollowTrainer

    trainer = FollowTrainer(
        engine, engine_params, engine_id=engine_id,
        engine_version=args.engine_version, engine_variant=args.variant,
        engine_factory=variant["engineFactory"],
        interval=getattr(args, "follow_interval", 0.0) or None,
        persist=True)
    print(f"Follow-trainer for {engine_id} resident "
          f"(mode={trainer.mode}, interval={trainer.interval:g}s); "
          "Ctrl-C stops.")
    try:
        trainer.run_forever()
    except KeyboardInterrupt:
        pass
    return 0


def run_build_from_args(args) -> int:
    """`pio build` entry (reference: Console.build → sbt assembly +
    RegisterEngine writing an EngineManifest).  There is no jar to compile
    here; "build" = validate the engine variant end to end (factory import,
    engine construction, params binding) and register the manifest so train/
    deploy can resolve the engine by (id, version)."""
    from predictionio_tpu.storage import EngineManifest, get_storage

    try:
        variant = load_engine_variant(args.engine_json, getattr(args, "variant", "default"))
        factory, engine, engine_params = engine_from_variant(variant)
        engine_id = resolve_engine_id(getattr(args, "engine_id", None), variant, factory)
        version = getattr(args, "engine_version", "1")
        manifest = EngineManifest(
            id=engine_id,
            version=version,
            name=variant.get("id", engine_id),
            description=variant.get("description", ""),
            files=[str(Path(args.engine_json).resolve())],
            engine_factory=variant["engineFactory"],
        )
        get_storage().engine_manifests.insert(manifest)
    except Exception as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    n_algos = len(engine_params.algorithm_params_list)
    print(
        f"Build successful. Registered engine {engine_id} {version} "
        f"(factory {variant['engineFactory']}, {n_algos} algorithm(s))."
    )
    return 0


def _load_dotted(path: str, what: str):
    module_name, _, attr = path.rpartition(".")
    if not module_name:
        raise ValueError(f"{what} {path!r} must be a dotted path")
    return getattr(importlib.import_module(module_name), attr)


def run_eval_from_args(args) -> int:
    """`pio eval` entry — evaluation_class is a dotted path to an Evaluation
    subclass or instance; an optional EngineParamsGenerator dotted path
    supplies the candidate grid (reference: Console.eval taking
    <Evaluation> [<EngineParamsGenerator>] → EvaluationWorkflow)."""
    from predictionio_tpu.controller.evaluation import Evaluation, EngineParamsGenerator

    try:
        obj = _load_dotted(args.evaluation_class, "evaluation class")
        evaluation = obj() if isinstance(obj, type) else obj
        if not isinstance(evaluation, Evaluation):
            raise TypeError(f"{args.evaluation_class} is not an Evaluation")
        gen_path = getattr(args, "params_generator", None)
        if gen_path:
            gobj = _load_dotted(gen_path, "engine params generator")
            gen = gobj() if isinstance(gobj, type) else gobj
            if not isinstance(gen, EngineParamsGenerator):
                raise TypeError(f"{gen_path} is not an EngineParamsGenerator")
            evaluation.engine_params_list = list(gen.engine_params_list)
        result = core_workflow.run_eval(evaluation, evaluation_class=args.evaluation_class)
    except Exception as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"Evaluation completed: {result.metric_header} best={result.best_score:.6f}")
    # per-candidate table incl. side metrics (reference MetricEvaluator
    # prints the full candidate/metric matrix, not only the winner)
    headers = [result.metric_header] + list(result.other_metric_headers)
    for i, (_ep, score, others) in enumerate(result.engine_params_scores):
        marker = "*" if i == result.best_index else " "
        cells = "  ".join(f"{h}={v:.6f}" for h, v in zip(headers, [score] + list(others)))
        print(f"  {marker} candidate {i}: {cells}")
    print("Best engine params:")
    print(json.dumps(result.best_engine_params.to_json(), indent=2))
    return 0
