"""Query server — `pio deploy`.

Reference: core/.../workflow/CreateServer.scala — ``MasterActor`` resolves the
latest COMPLETED EngineInstance, loads models, and spawns the spray
``ServerActor`` serving:

  POST /queries.json   query → predict → serve → JSON prediction
  GET  /               engine-instance info
  GET  /reload         hot-swap to the newest COMPLETED instance
  GET  /stop           shut down (reference web UI's stop)
  GET  /metrics        Prometheus text (cross-worker aggregate)
  GET  /stats.json     per-(route, status) request windows

The feedback loop (reference: ServerActor writing prediction events back to
the event store with ``prId`` when feedback is enabled) is implemented via
``--feedback``: every answered query logs a ``predict`` event.
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import os
import sys
import threading
import time
import uuid
from typing import Any, Callable, Dict, Optional

from predictionio_tpu.api import prefork
from predictionio_tpu.api.http_util import JsonHandler, start_server
from predictionio_tpu.obs import cluster as obs_cluster
from predictionio_tpu.obs import lineage as obs_lineage
from predictionio_tpu.obs import metrics as obs_metrics
from predictionio_tpu.obs import slo as obs_slo
from predictionio_tpu.obs import tracing as obs_tracing
from predictionio_tpu.obs import tsdb as obs_tsdb
from predictionio_tpu.obs.exposition import StatsCollector, metrics_payload
from predictionio_tpu.obs.metrics import SIZE_BUCKETS
from predictionio_tpu.serve import response_cache as _response_cache
from predictionio_tpu.storage.locator import Storage, get_storage
from predictionio_tpu.workflow import core_workflow
from predictionio_tpu.workflow.create_workflow import (
    engine_from_variant,
    load_engine_variant,
    resolve_engine_id,
)

log = logging.getLogger("pio.queryserver")

_M_SERVE_BATCH = obs_metrics.get_registry().histogram(
    "pio_serve_batch_size",
    "Queries coalesced per micro-batch device dispatch",
    buckets=SIZE_BUCKETS)
_M_GENERATION = obs_metrics.get_registry().gauge(
    "pio_model_generation",
    "Monotonic generation counter of the live model: bumped by every "
    "hot-swap (follow fold, auto-reload, manual /reload) — serving "
    "caches key on the model object this counts")


def _to_jsonable(obj: Any) -> Any:
    if hasattr(obj, "to_json"):
        return obj.to_json()
    if isinstance(obj, (dict, list, str, int, float, bool)) or obj is None:
        return obj
    if hasattr(obj, "__dict__"):
        return obj.__dict__
    return str(obj)


# How long a queued query waits for its result before giving up.  A fresh
# shape bucket on TPU can compile for minutes, so this is generous; only a
# genuinely dead leader should trip it.  Module-level so tests can shrink
# it to exercise the timeout/handoff races directly.
_WAIT_TIMEOUT_S = 600.0


class _MicroBatcher:
    """Group-commit micro-batching for concurrent queries — across
    requests, threads, and (since the event-loop front end) connections.

    The first thread into an idle batcher becomes the leader and
    immediately executes whatever is queued (usually just itself);
    queries arriving WHILE a batch executes coalesce into the next batch,
    which the same leader drains before releasing leadership.  No timer,
    no added latency for a lone query — batch size adapts to load, like
    a storage group commit.

    Why: each predict is one device dispatch + one readback.  Scoring B
    queued queries as one [B, …] program amortizes the dispatch (and,
    behind a tunneled accelerator, the ~70 ms readback round trip) across
    the batch — the single-chip answer to concurrent serving load, where
    the reference scaled by adding spray nodes.  The http_util event
    loop executes handlers on a small pool, so queries that are
    concurrently in flight across DIFFERENT client connections (and
    different pipelined requests on one connection) meet here and leave
    as one ``serve_batch_predict`` pass — the host numpy tail is
    amortized over the whole in-flight set the same way the device
    dispatch is.

    ``PIO_SERVE_BATCH_WINDOW_MS`` (default 0) optionally makes the
    leader dwell that long before executing its first batch, trading a
    bounded p50 hit for bigger batches when callers prefer throughput;
    0 keeps the pure group-commit behavior (nothing waits on a timer).
    """

    def __init__(self, run_batch: Callable, run_one: Callable,
                 max_batch: Optional[int] = None,
                 window_s: Optional[float] = None):
        from predictionio_tpu.controller.engine import DEFAULT_SERVE_BATCH

        if max_batch is None:
            max_batch = DEFAULT_SERVE_BATCH
        if window_s is None:
            try:
                window_s = float(
                    os.environ.get("PIO_SERVE_BATCH_WINDOW_MS", "0")) / 1e3
            except ValueError:
                window_s = 0.0
        self._run = run_batch
        self._run_one = run_one
        self._max = max_batch
        self._window = max(0.0, window_s)
        self._lock = threading.Lock()
        self._queue: list = []
        self._leader_active = False

    def predict(self, query: Any) -> Any:
        item = {"q": query, "ev": threading.Event()}
        with self._lock:
            self._queue.append(item)
            lead = not self._leader_active
            if lead:
                self._leader_active = True
        while True:
            if lead:
                self._lead_until_served(item)
                lead = False  # leading guarantees our item was served
            if "r" in item or "e" in item:
                break
            # re-arm, then re-check BOTH wake sources under ONE lock hold.
            # Result writers assign r/e before set(), so a set() racing
            # our clear() is caught by the r/e re-check.  Leadership
            # nudges set() WITHOUT writing a result — a clear() could
            # swallow one — so we also probe the vacancy itself: if no
            # leader is active we claim the lead, making a swallowed
            # nudge harmless.  The r/e check MUST share the claim's lock
            # hold: results are written before leadership is released
            # (itself under the lock), so either we see our result here,
            # or the leader hasn't released yet and we won't win the
            # vacancy — never both, so a served waiter can't become a
            # leader that withholds its own finished result.
            item["ev"].clear()
            with self._lock:
                if "r" in item or "e" in item:
                    break
                lead = not self._leader_active
                if lead:
                    self._leader_active = True
            if lead:
                continue
            if not item["ev"].wait(timeout=_WAIT_TIMEOUT_S):
                with self._lock:
                    if item in self._queue:
                        self._queue.remove(item)
                    served = "r" in item or "e" in item
                    # if we were about to inherit leadership, pass the
                    # wake on so the remaining waiters aren't stranded
                    nxt = (self._queue[0]
                           if not served and not self._leader_active
                           and self._queue else None)
                if nxt is not None:
                    nxt["ev"].set()
                if not served:
                    raise TimeoutError(
                        "micro-batch not served within %.0f s (leader died?)"
                        % _WAIT_TIMEOUT_S)
                continue
            # woken: loop re-checks the result and the leadership vacancy
        if "e" in item:
            raise item["e"]
        return item["r"]

    def _lead_until_served(self, own: dict) -> None:
        """Run batches until ``own`` is served, then RELEASE leadership and
        nudge the head waiter to re-claim it under the lock.  Draining
        until the queue empties would starve the leader's own client under
        sustained load — leadership rotates instead, so every request is
        served after at most a few batches.  Leadership is never
        *transferred* to a specific thread: the nudged waiter may already
        have timed out and departed, and a transfer would then leave
        ``_leader_active`` stuck True forever (every later query waits
        600 s and fails).  Releasing means any thread — the nudged waiter
        or a fresh arrival — can claim the vacancy."""
        if self._window:
            # opt-in dwell: let concurrently-arriving queries (other
            # connections' handler threads) join this leader's first batch
            time.sleep(self._window)
        while True:
            with self._lock:
                batch = self._queue[: self._max]
                del self._queue[: self._max]
                if not batch:
                    self._leader_active = False
                    return
            _M_SERVE_BATCH.observe(len(batch))
            try:
                try:
                    results = self._run([i["q"] for i in batch])
                    # strict: a predictor returning the wrong count must
                    # fall into the serial fallback, not leave an unserved
                    # item (whose thread would spin claiming/releasing
                    # leadership)
                    for i, r in zip(batch, results, strict=True):
                        i["r"] = r
                except Exception:
                    # one poisoned query must not 500 its batchmates:
                    # re-run the batch serially so only the offender errors
                    for i in batch:
                        try:
                            i["r"] = self._run_one(i["q"])
                        except Exception as e:
                            i["e"] = e
            except BaseException as exc:
                # SystemExit/KeyboardInterrupt escape the Exception
                # clauses above; leadership and the batch's waiters must
                # not leak with them (a stuck _leader_active wedges every
                # future query)
                err = RuntimeError(f"batch leader aborted: {exc!r}")
                for i in batch:
                    if "r" not in i and "e" not in i:
                        i["e"] = err
                with self._lock:
                    self._leader_active = False
                    nxt = self._queue[0] if self._queue else None
                if nxt is not None:
                    nxt["ev"].set()
                for i in batch:
                    i["ev"].set()
                raise
            served_self = own in batch
            if served_self:
                with self._lock:
                    self._leader_active = False
                    nxt = self._queue[0] if self._queue else None
                if nxt is not None:
                    nxt["ev"].set()  # wake to re-claim the released lead
            for i in batch:
                i["ev"].set()
            if served_self:
                return


class QueryServerState:
    """Holds the deployed engine + models; supports hot reload
    (reference: MasterActor hot-swapping engine instances)."""

    def __init__(
        self,
        engine,
        engine_params,
        query_class,
        engine_id: str,
        engine_version: str,
        engine_variant: str,
        storage: Optional[Storage] = None,
        feedback: bool = False,
        feedback_app_name: str = "",
        plugins=None,
        auto_reload: float = 0.0,
        plane_dir: Optional[str] = None,
    ):
        from predictionio_tpu.api.plugins import PluginRegistry

        self.plugins = PluginRegistry()
        self.engine = engine
        self.engine_params = engine_params
        self.query_class = query_class
        self.engine_id = engine_id
        self.engine_version = engine_version
        self.engine_variant = engine_variant
        self.storage = storage or get_storage()
        self.feedback = feedback
        self.feedback_app_name = feedback_app_name
        self._lock = threading.Lock()
        self.instance = None
        self.predictor: Optional[Callable] = None
        self.batcher = None
        self.query_count = 0
        self.started = _dt.datetime.now(_dt.timezone.utc)
        # model-generation bookkeeping: every hot-swap (reload, auto-
        # reload, embedded follower) installs a NEW model object and
        # bumps this counter — the serving caches (rule masks, inverted
        # CSR, pop order, value masks) all live on the model object, so
        # the swap IS their invalidation
        self.generation = 0
        self.swapped_at: Optional[_dt.datetime] = None
        self.follower = None          # embedded FollowTrainer, if any
        self.follow_info: Optional[Dict] = None
        self._build_seq = 0           # install-order tickets (see _install)
        self._installed_seq = 0
        # (lineage id, generation) of the newest install whose
        # first_serve stage this worker still owes — grabbed by the
        # first predict() that runs on the new generation
        self._lineage_pending: Optional[tuple] = None
        # shared-memory model plane (streaming.plane): when a plane dir
        # is wired, this worker WATCHES the plane manifest and installs
        # each published generation as read-only mmap views — model
        # emit/fold/warm CPU and N× resident copies leave the serving
        # workers; publishing happens in the dedicated publisher process
        # (or through plane_reload / the embedded follower's
        # plane_publish in single-worker topologies)
        self.plane = None
        self.plane_watcher = None
        self.plane_generation = 0
        # plane replication endpoint hosted by THIS process (a
        # PlaneReplicator when deploy --plane-publish, a PlaneSubscriber
        # when --plane-from); freshness() surfaces its role + lag
        self.replication = None
        self._tune_gil_switch()
        self.reload()
        if plane_dir:
            from predictionio_tpu.streaming.plane import (
                ModelPlane, PlaneWatcher,
            )

            self.plane = ModelPlane(plane_dir)
            self.plane_watcher = PlaneWatcher(self.plane,
                                              self._install_plane)
            self.plane_watcher.start()
        # serving reads user history from the live store per query; the
        # per-entity index otherwise builds on the FIRST query — at a
        # million-event log that is seconds of JSON parsing inline in a
        # query (and contending with a follow bootstrap).  Build it on a
        # background thread now instead.
        self._warm_entity_index_async()
        # plugins start only once the state is fully initialized (they get
        # a live QueryServerState with engine/storage/predictor populated)
        for p in plugins or []:
            self.plugins.register(p)
            p.start(self)
        # auto hot-swap (reference: MasterActor watching for retrained
        # instances): poll EngineInstances; when a newer COMPLETED
        # instance appears, reload without dropping the port.  Opt-in via
        # `pio deploy --auto-reload SECS`.
        self._auto_stop = threading.Event()
        if auto_reload > 0:
            t = threading.Thread(
                target=self._auto_reload_loop, args=(float(auto_reload),),
                daemon=True, name="pio-auto-reload")
            t.start()

    @staticmethod
    def _tune_gil_switch() -> None:
        """Shorten the interpreter's GIL switch interval (default 5 ms)
        inside query-server processes: a background fold/emit tick is
        Python-heavy at small shapes and can hold the GIL a full switch
        interval at a time, adding multi-ms stalls to colliding queries'
        p95.  1 ms caps that stall at ~1 ms per handoff for negligible
        switching overhead.  PIO_GIL_SWITCH_S overrides; <= 0 leaves the
        interpreter default."""
        import sys as _sys

        try:
            s = float(os.environ.get("PIO_GIL_SWITCH_S", "0.001"))
            if s > 0:
                _sys.setswitchinterval(s)
        except (ValueError, OSError):
            pass

    def _warm_entity_index_async(self) -> None:
        """Off-thread pre-build of the event store's per-entity serving
        index (localfs/sharded; other backends simply lack the hook).
        Failure is benign — the lazy build on first lookup remains."""
        app_name = getattr(
            getattr(self.engine_params, "data_source_params", None),
            "app_name", None)
        warm = getattr(self.storage.l_events, "warm_entity_index", None)
        if not app_name or warm is None:
            return

        def run() -> None:
            try:
                app = self.storage.apps.get_by_name(app_name)
                if app is not None:
                    warm(app.id)
            except Exception:
                log.exception("entity-index warm failed (the lazy build "
                              "on first query remains)")

        threading.Thread(target=run, daemon=True,
                         name="pio-entity-index-warm").start()

    # -- model-plane integration ---------------------------------------------

    def _install_plane(self, models, info: Optional[Dict] = None) -> bool:
        """PlaneWatcher install hook: the mapped generation goes through
        the ONE build-ticket install path like every other swap."""
        info = dict(info or {})
        gen = int(info.get("planeGeneration") or 0)
        installed = self._install(models, follow_info=info)
        if gen:
            self.plane_generation = gen
        return installed

    def plane_reload(self):
        """Plane-mode /reload: load the latest persisted instance ONCE,
        publish it as a new plane generation, and install it locally —
        every prefork sibling's watcher converges on the same generation
        within one poll interval, so a single /reload reaches the WHOLE
        group (the old behavior reached only the routed worker).
        Returns ``(plane_generation, instance_id)``."""
        instance, models = core_workflow.load_latest_models(
            self.engine_id, self.engine_version, self.engine_variant,
            self.storage)
        gen = self.plane.publish(models, {
            "mode": "reload", "engineInstanceId": instance.id})
        self.plane_watcher.check_now()
        # the mapped install carries no instance row; record it here so
        # freshness reports it and the auto-reload poller sees this
        # instance as live (it would otherwise republish every tick)
        self.instance = instance
        return gen, instance.id

    def plane_publish_initial(self) -> None:
        """Prefork parent, at deploy: seed the plane with the loaded
        instance so every worker converges onto ONE mapped copy from the
        start (each worker's private startup load is transient — it
        drops as soon as the mapped generation installs).  No-op when a
        generation already exists (restart onto a live plane)."""
        if self.plane is None or self.plane.current() is not None:
            return
        self.plane_reload()

    def plane_publish(self, models, info: Optional[Dict] = None) -> None:
        """Embedded-follower publish hook for single-worker plane
        topologies (``--workers 1`` with PIO_MODEL_PLANE=on, and the
        in-process parity/test servers): emit to the arena, then install
        the MAPPED generation locally — the process serves the same
        shared bytes a sibling would."""
        from predictionio_tpu.streaming.plane import PlaneUnsupported

        try:
            self.plane.publish(models, info)
        except PlaneUnsupported as e:
            log.warning("model plane cannot carry this bundle (%s); "
                        "installing in-process", e)
            self.swap_models(models, info)
            return
        self.plane_watcher.check_now()

    def disable_plane(self) -> None:
        """Degrade to the private-model path (non-UR bundle at deploy)."""
        if self.plane_watcher is not None:
            self.plane_watcher.stop()
        self.plane = None
        self.plane_watcher = None

    def _auto_reload_loop(self, interval: float) -> None:
        while not self._auto_stop.wait(interval):
            try:
                latest = self.storage.engine_instances.get_latest_completed(
                    self.engine_id, self.engine_version, self.engine_variant)
            except Exception:
                log.exception("auto-reload: instance lookup failed")
                continue
            current = self.instance
            if latest is not None and (
                    current is None or latest.id != current.id):
                if self.plane is not None:
                    # plane mode: ONE publish converges the whole group
                    # (children are spawned without --auto-reload)
                    try:
                        gen, iid = self.plane_reload()
                        log.info("auto-reload: published instance %s as "
                                 "plane generation %d", iid, gen)
                    except Exception:
                        log.exception("auto-reload: plane publish failed; "
                                      "keeping current generation")
                    continue
                try:
                    if self.reload() is not None:
                        log.info("auto-reload: hot-swapped to instance %s",
                                 latest.id)
                    else:
                        log.info("auto-reload: instance %s dropped as "
                                 "stale (a newer generation installed "
                                 "first)", latest.id)
                except Exception:
                    # the newer instance's models may still be mid-write;
                    # keep serving the current model and retry next tick
                    log.exception("auto-reload: reload failed; keeping "
                                  "current instance")

    def stop_auto_reload(self) -> None:
        """Stop every background updater (auto-reload poller + embedded
        follower) — wired into server shutdown."""
        self._auto_stop.set()
        if self.follower is not None:
            self.follower.stop(timeout=2.0)
        if self.replication is not None:
            try:
                self.replication.stop(timeout=1.0)
            except Exception:
                log.exception("plane replication stop failed")
            self.replication = None
            # publisher-side cluster observability dies with replication
            obs_lineage.set_cluster_provider(None)
            obs_cluster.set_federation(None)
        if self.plane_watcher is not None:
            self.plane_watcher.stop()

    def reload(self) -> Optional[str]:
        """Load + install the latest persisted instance.  Returns its id,
        or None when the bundle was dropped as stale (a build that
        started later — e.g. the embedded follower's — installed first;
        the server is serving that newer generation, not this one)."""
        instance, models = core_workflow.load_latest_models(
            self.engine_id, self.engine_version, self.engine_variant,
            self.storage)
        if self._install(models, instance=instance):
            return instance.id
        return None

    def swap_models(self, models, info: Optional[Dict] = None) -> None:
        """Embedded-follower hot-swap: install already-built models
        without a persistence round trip.  The swap is atomic under the
        serving lock; in-flight queries finish on the old generation."""
        self._install(models, follow_info=info)

    def _install(self, models, instance=None,
                 follow_info: Optional[Dict] = None) -> bool:
        """The ONE model-installation path (reload, auto-reload, follower
        swap): build + warm the serving bundle OUTSIDE the lock — a warm
        can stage tens of MB to device — then swap the predictor,
        batcher and generation in one lock hold.  Concurrent builders
        (auto-reload poller + embedded follower) are ordered by a build
        ticket taken at build START: a bundle whose build began before a
        later build already installed is dropped, so a slow stale build
        can never swap in over a newer generation.  Returns False when
        the bundle was dropped as stale, True when it went live."""
        import jax

        w_inst, t_inst = time.time(), time.perf_counter()
        with self._lock:
            self._build_seq += 1
            ticket = self._build_seq

        # Micro-batch concurrent queries when every algorithm supports
        # serving-safe batch prediction.  PIO_SERVE_BATCH: on | off |
        # auto (default).  Auto engages only on an accelerator
        # backend: there a batch amortizes the per-dispatch/readback
        # overhead that dominates concurrent serving (~70 ms/readback
        # behind the axon tunnel), while on CPU the scoring math is so
        # cheap that the batcher's coordination measurably LOSES
        # (2.4k → 0.4k q/s at 32 clients — see PERF.md round 4).
        conf = os.environ.get("PIO_SERVE_BATCH", "auto").lower()
        enable = conf in ("1", "on", "true")
        if not enable and conf == "auto":
            # probe the backend ONLY for auto — "off" must never touch
            # the accelerator (init can hang for minutes on a dead
            # tunnel), and a broken backend must not kill deploy
            try:
                enable = jax.default_backend() not in ("cpu",)
            except RuntimeError:
                enable = False
        predictor, bp = self.engine.serving_bundle(self.engine_params, models)
        batcher = (
            _MicroBatcher(bp, predictor,
                          max_batch=getattr(bp, "max_batch", None))
            if enable and bp is not None else None)
        with self._lock:
            if ticket <= self._installed_seq:
                return False   # a build that started later already installed
            self._installed_seq = ticket
            # response cache: re-arm on the new generation BEFORE the
            # predictor goes live, sweeping exactly the entries its swap
            # provenance cannot prove unchanged (serve.response_cache);
            # the cache must never be able to break an install
            w_cache, t_cache = time.time(), time.perf_counter()
            cache_attrs = None
            try:
                cache = _response_cache.get_cache()
                cache.on_swap(models)
                cache_attrs = {
                    "start": w_cache,
                    "duration_s": time.perf_counter() - t_cache,
                    # workers without provenance flush everything — that
                    # IS the interesting outcome on a lineage waterfall
                    "outcome": ("full_flush"
                                if cache.last_swap_reason == "no_provenance"
                                else cache.last_swap_reason or "noop"),
                    "dropped": int(cache.last_swap_invalidated),
                    "entries": len(cache),
                }
            except Exception:
                log.exception("response-cache swap sweep failed — "
                              "disarming the cache")
                try:
                    _response_cache.get_cache().disarm()
                except Exception:
                    pass
            self.predictor = predictor
            self.batcher = batcher
            if instance is not None:
                self.instance = instance
            self.generation += 1
            self.swapped_at = _dt.datetime.now(_dt.timezone.utc)
            if follow_info is not None:
                self.follow_info = dict(follow_info)
            lid = (follow_info or {}).get("lineageId")
            gen = int((follow_info or {}).get("planeGeneration")
                      or self.generation)
            if lid:
                # first_serve is owed by whichever predict() runs next on
                # this generation; newer installs overwrite the debt (the
                # superseded generation never served from this worker)
                self._lineage_pending = (lid, gen)
        _M_GENERATION.set(self.generation)
        if lid:
            lin = obs_lineage.get_lineage()
            if lin.enabled:
                lin.note_generation(lid, gen)
                if cache_attrs is not None:
                    lin.stage(lid, "cache_invalidation",
                              parent="install", **cache_attrs)
                lin.stage(lid, "install", start=w_inst,
                          duration_s=time.perf_counter() - t_inst,
                          generation=gen, flush=True)
        return True

    def freshness(self) -> Dict:
        """The /stats.json ``freshness`` key: how current the live model
        is and who keeps it that way."""
        doc: Dict[str, Any] = {
            "generation": self.generation,
            "swappedAt": (self.swapped_at.isoformat()
                          if self.swapped_at else None),
            "engineInstanceId": self.instance.id if self.instance else None,
        }
        if self.plane is not None:
            # the generation every prefork sibling converges on — equal
            # across workers means the group serves ONE mapped model
            doc["planeGeneration"] = self.plane_generation
            if self.plane.last_publish_stats:
                # this process published: surface the delta-arena write
                # profile (logical model bytes vs bytes actually written
                # — the per-generation write amplification, also on the
                # dashboard as pio_model_plane_publish_bytes_total)
                doc["planePublish"] = dict(self.plane.last_publish_stats)
        if self.replication is not None:
            # multi-node topology: which side of the replication channel
            # this node is on, and how far behind it runs — the
            # cluster-convergence analogue of planeGeneration
            try:
                doc["replication"] = self.replication.status()
            except Exception:
                pass
        if self.follower is not None:
            doc["follower"] = self.follower.status()
        elif self.follow_info is not None:
            doc["follower"] = dict(self.follow_info)
        # top-level mirror of the fold-state footprint (also a gauge:
        # pio_follow_state_bytes) so dashboards and the freshness bench
        # read one stable key regardless of follower topology
        fr = doc.get("follower")
        if isinstance(fr, dict):
            doc["stateBytes"] = fr.get("stateBytes")
            doc["stateMode"] = fr.get("stateMode")
        return doc

    def parse_query(self, body: Dict) -> Any:
        if self.query_class is not None and hasattr(self.query_class, "from_json"):
            return self.query_class.from_json(body)
        return body

    def predict(self, body: Dict) -> Any:
        query = self.parse_query(body)
        w_q, t_q = time.time(), time.perf_counter()
        with self._lock:
            predictor = self.predictor
            batcher = self.batcher
            pending, self._lineage_pending = self._lineage_pending, None
        prediction = batcher.predict(query) if batcher else predictor(query)
        if pending is not None:
            # the freshness waterfall's last hop: this worker ANSWERED a
            # query from the new generation (not merely installed it)
            lin = obs_lineage.get_lineage()
            if lin.enabled:
                lin.stage(pending[0], "first_serve", start=w_q,
                          duration_s=time.perf_counter() - t_q,
                          generation=pending[1], flush=True)
        prediction = self.plugins.apply(query, prediction)
        self.query_count += 1
        if self.feedback and self.feedback_app_name:
            self._log_feedback(body, prediction)
        return prediction

    def _log_feedback(self, query_body: Dict, prediction: Any) -> None:
        """Write the served prediction back as a `predict` event (prId links
        follow-up reward events to this prediction, as in the reference)."""
        from predictionio_tpu.events.event import DataMap, Event

        app = self.storage.apps.get_by_name(self.feedback_app_name)
        if app is None:
            return
        self.storage.l_events.insert(
            Event(
                event="predict",
                entity_type="pio_pr",
                entity_id=uuid.uuid4().hex,
                properties=DataMap(
                    {"query": query_body, "prediction": _to_jsonable(prediction)}
                ),
                pr_id=uuid.uuid4().hex,
            ),
            app.id,
        )

    def info(self) -> Dict:
        return {
            "status": "alive",
            # pid identifies WHICH prefork worker answered — the readiness
            # probe for `deploy --workers N` (poll fresh connections until
            # N distinct pids have been seen), same contract as the event
            # server's GET /
            "pid": os.getpid(),
            "workerTag": obs_metrics.worker_tag(),
            "engineId": self.engine_id,
            "engineVersion": self.engine_version,
            "variant": self.engine_variant,
            "engineInstanceId": self.instance.id if self.instance else None,
            "trainedAt": self.instance.start_time.isoformat() if self.instance else None,
            "queryCount": self.query_count,
            "startedAt": self.started.isoformat(),
            "modelGeneration": self.generation,
            # None = plane off; else this worker's installed plane
            # generation (the readiness/convergence probe for the group)
            "planeGeneration": (self.plane_generation
                                if self.plane is not None else None),
            # freshness is STATE, not a metric: it must stay readable
            # under PIO_METRICS=off, where /stats.json answers 503
            "freshness": self.freshness(),
        }


def _render_info_html(state: QueryServerState) -> str:
    """Deploy web UI (reference: CreateServer's engine-instance info page)."""
    import html as _html

    info = state.info()
    rows = "".join(
        f"<tr><th>{_html.escape(str(k))}</th><td>{_html.escape(str(v))}</td></tr>"
        for k, v in info.items()
    )
    plugins = ", ".join(p.name for p in state.plugins.all()) or "(none)"
    return f"""<!DOCTYPE html>
<html><head><title>PredictionIO-TPU engine server</title>
<style>body{{font-family:sans-serif;margin:2em}}table{{border-collapse:collapse}}
th,td{{border:1px solid #ccc;padding:4px 10px;text-align:left}}</style></head>
<body><h1>Engine server: {_html.escape(state.engine_id)}</h1>
<table>{rows}</table>
<p>plugins: {_html.escape(plugins)}</p>
<p>POST /queries.json &middot; GET /reload &middot; GET /stop &middot;
GET /metrics &middot; GET /stats.json</p>
</body></html>"""


def make_handler(state: QueryServerState):
    class QueryHandler(JsonHandler):
        # per-(route, status) windows for /stats.json, fed by the
        # http_util middleware; None under PIO_METRICS=off (the
        # middleware skips recording and /stats.json answers 503)
        stats_collector = (StatsCollector()
                           if obs_metrics.get_registry().enabled else None)

        def do_GET(self):
            path, _query = self.route
            if path == "/":
                accept = self.headers.get("Accept", "")
                if "text/html" in accept:
                    self.send_html(_render_info_html(state))
                else:
                    self.send_json(state.info())
            elif path == "/metrics":
                self._send_raw(200, metrics_payload(),
                               ctype="text/plain; version=0.0.4; "
                                     "charset=utf-8")
            elif obs_tracing.handle_trace_request(self, path):
                pass   # /traces.json + /traces/{rid}.json (flight recorder)
            elif obs_lineage.handle_lineage_request(self, path):
                pass   # /lineage.json + /lineage/{gen|ln-id}.json
            elif obs_tsdb.handle_history_request(self, path):
                pass   # /metrics/history.json (local time-series ring)
            elif obs_cluster.handle_cluster_request(self, path):
                pass   # /cluster/{metrics,history}.json (publisher only)
            elif obs_slo.handle_healthz_request(self, path):
                pass   # /healthz (SLO burn-rate verdicts, always 200)
            elif path == "/stats.json":
                if self.stats_collector is None:
                    self.send_error_json(
                        503, "stats disabled (PIO_METRICS=off)")
                    return
                doc = self.stats_collector.to_json()
                doc["engineId"] = state.engine_id
                doc["queryCount"] = state.query_count
                doc["startedAt"] = state.started.isoformat()
                doc["freshness"] = state.freshness()
                self.send_json(doc)
            elif path == "/reload":
                from predictionio_tpu.streaming.plane import (
                    PlaneUnsupported,
                )

                try:
                    if state.plane is not None:
                        try:
                            # plane mode: ONE reload on ANY worker
                            # publishes a plane generation the whole
                            # prefork group converges on (watchers
                            # install within a poll interval)
                            gen, iid = state.plane_reload()
                            self.send_json({"reloaded": True,
                                            "generation": gen,
                                            "engineInstanceId": iid})
                            return
                        except PlaneUnsupported:
                            pass   # non-UR bundle: private reload below
                    iid = state.reload()
                    live = state.instance.id if state.instance else None
                    self.send_json({"reloaded": iid is not None,
                                    "engineInstanceId": iid or live})
                except Exception as e:
                    self.send_error_json(500, f"reload failed: {e}")
            elif path == "/stop":
                self.send_json({"stopping": True})

                def _stop(server):
                    state.stop_auto_reload()
                    server.shutdown()
                    # close the listening socket too: shutdown() alone
                    # keeps accepting connections that nothing serves
                    # (clients would hang instead of being refused)
                    server.server_close()

                threading.Thread(target=_stop, args=(self.server,),
                                 daemon=True).start()
            else:
                self.send_error_json(404, "not found")

        def do_POST(self):
            path, _query = self.route
            if path != "/queries.json":
                self.send_error_json(404, "not found")
                return
            try:
                body = self.read_json()
            except json.JSONDecodeError as e:
                self.send_error_json(400, f"invalid JSON: {e}")
                return
            if not isinstance(body, dict):
                self.send_error_json(400, "query must be a JSON object")
                return
            try:
                prediction = state.predict(body)
            except (KeyError, ValueError, TypeError) as e:
                self.send_error_json(400, f"bad query: {e}")
                return
            except Exception as e:  # engine failure
                log.exception("prediction failed")
                self.send_error_json(500, f"prediction failed: {e}")
                return
            self.send_json(_to_jsonable(prediction))

    return QueryHandler


def deploy(
    engine_json: str = "engine.json",
    variant: str = "default",
    engine_id: Optional[str] = None,
    engine_version: str = "1",
    host: str = "0.0.0.0",
    port: int = 8000,
    feedback: bool = False,
    storage: Optional[Storage] = None,
    background: bool = False,
    plugins=None,
    auto_reload: float = 0.0,
    workers: int = 1,
    reuse_port: bool = False,
    follow: float = 0.0,
    plane_publish: Optional[str] = None,
    plane_from: Optional[str] = None,
):
    """Programmatic deploy; returns the HTTPServer (background=True) or blocks.

    ``plane_publish=\"[HOST:]PORT\"`` additionally serves this node's
    model plane to replication subscribers; ``plane_from=\"HOST:PORT\"``
    makes this node a replication SUBSCRIBER: no local folding (it
    conflicts with ``follow``), the plane dir (node-local, via
    PIO_MODEL_PLANE_DIR) is fed by the remote publisher and the normal
    watcher/compose/install path serves it.  See docs/operations.md
    "Multi-node plane replication".

    ``workers > 1`` preforks N−1 extra OS processes all serving the SAME
    port via SO_REUSEPORT (the kernel load-balances accepts): CPython's
    GIL caps one process at roughly single-core query throughput, so
    CPU-backend deployments scale across cores this way — the analogue of
    the reference running several spray nodes behind a balancer.  Only
    meaningful on CPU backends: a TPU chip is single-process-exclusive,
    so workers>1 on a TPU backend raises.  Workers resolve storage from
    the PIO_STORAGE_* environment (a programmatic ``storage`` object
    cannot cross the process boundary).

    A manual GET /reload reaches only the ONE worker the kernel routes
    it to — pair --workers with --auto-reload so every worker converges
    on a retrained instance within the polling interval.  `pio undeploy`
    handles the multi-listener teardown (it stops until the port stops
    answering).
    """
    # cheap preconditions FIRST: raising after QueryServerState exists
    # would leak its auto-reload poller and started plugins
    if plane_from and follow > 0:
        raise ValueError(
            "deploy --plane-from replaces local folding with replicated "
            "generations; drop --follow (the publisher node folds)")
    if plane_from and plane_publish:
        raise ValueError(
            "deploy cannot be a replication subscriber and publisher at "
            "once (relaying is not supported)")
    if (plane_from or plane_publish) \
            and not os.environ.get("PIO_CLUSTER_NODE"):
        # multi-node deployment: every lineage stage this node records
        # is SOURCE-stamped with a node name (obs.lineage reads the env
        # lazily) so cross-node stitching attributes per-node lanes
        # without guessing; set BEFORE the serving state exists so the
        # install/first_serve stages carry it, and prefork children
        # inherit it via os.environ.  Operators/CI set it explicitly for
        # stable names across restarts.
        import socket as _socket

        role = "sub" if plane_from else "pub"
        os.environ["PIO_CLUSTER_NODE"] = \
            f"{_socket.gethostname()}-{role}-{os.getpid()}"
    if workers > 1:
        import jax

        if jax.default_backend() not in ("cpu",):
            raise ValueError(
                "deploy --workers requires a CPU backend: an accelerator "
                "chip is single-process-exclusive (scale TPU serving with "
                "micro-batching or more chips, not prefork workers)")
        if storage is not None:
            raise ValueError(
                "deploy --workers resolves storage from PIO_STORAGE_* env "
                "in each worker; a programmatic storage object cannot "
                "cross the process boundary")
    # Orphan-watch only in children WE spawned (marked via env by the
    # prefork spawn below) — a programmatic caller passing reuse_port=True
    # behind their own balancer must not get a server that self-terminates
    # when its launcher exits.
    if workers == 1:
        prefork.maybe_watch_parent(log)   # prefork child: die when orphaned
        # prefork child spawned with a PIO_METRICS_DIR/PIO_METRICS_TAG:
        # publish snapshots so any sibling's /metrics scrape sees us
        # (no-op — pure in-memory metrics — for a true single worker)
        obs_metrics.start_worker_flusher()
        obs_metrics.mark_worker_up()
    doc = load_engine_variant(engine_json, variant)
    factory, engine, engine_params = engine_from_variant(doc)
    eid = resolve_engine_id(engine_id, doc, factory)
    query_class = getattr(factory, "query_class", None)
    feedback_app = ""
    if feedback:
        ds_params = getattr(engine_params.data_source_params, "app_name", "")
        feedback_app = ds_params
    # shared-memory model plane: with a prefork group (or PIO_MODEL_PLANE
    # =on), each model generation is emitted ONCE into an mmap-able arena
    # and every worker maps it read-only — resident model bytes N× → ~1×,
    # one fold per delta, /reload converges the whole group
    from predictionio_tpu.streaming import plane as plane_mod

    metrics_dir: Optional[str] = None
    if workers > 1:
        # the group metrics dir + the parent's worker tag exist BEFORE
        # the serving state: the plane seeds its per-worker generation/
        # rss gauges during state construction, and a later tag change
        # would strand those series under a stale pid-based label
        import tempfile

        metrics_dir = tempfile.mkdtemp(prefix="pio-metrics-")
        obs_metrics.start_worker_flusher(metrics_dir, f"w0-{os.getpid()}")
    plane_dir: Optional[str] = None
    if plane_mod.plane_wanted(workers) or plane_from or plane_publish:
        # replication implies the plane: a subscriber node IS a plane
        # consumer, a publishing node must host the dir it serves
        plane_dir = plane_mod.resolve_plane_dir(
            storage or get_storage(), eid, variant)
        if plane_dir is None:
            if plane_from or plane_publish:
                raise ValueError(
                    "plane replication needs a model-plane directory: "
                    "set PIO_MODEL_PLANE_DIR to a node-LOCAL path (or "
                    "use a localfs METADATA store); see "
                    "docs/operations.md \"Multi-node plane replication\"")
            log.warning(
                "model plane requested but no plane dir is resolvable "
                "(set PIO_MODEL_PLANE_DIR or use a localfs METADATA "
                "store; for multi-node serving see docs/operations.md "
                "\"Multi-node plane replication\"); workers serve "
                "private model copies")
    state = QueryServerState(
        engine, engine_params, query_class, eid, engine_version, variant,
        storage=storage, feedback=feedback, feedback_app_name=feedback_app,
        plugins=plugins, auto_reload=auto_reload, plane_dir=plane_dir,
    )
    if state.plane is not None and plane_from is not None:
        # subscriber node: the plane dir belongs to the remote publisher
        # (via the subscriber daemon below) — seeding it locally would
        # be the exact split-brain the replication marker guards against.
        # Until the first replicated flip lands, workers serve the
        # privately loaded startup model.
        pass
    elif state.plane is not None and not prefork.is_prefork_child():
        # seed the plane with the loaded instance so the group converges
        # onto one mapped copy from the start; a bundle the plane cannot
        # carry (non-UR) degrades the WHOLE deploy to private models —
        # decided here, before workers/publisher are spawned
        try:
            state.plane_publish_initial()
        except plane_mod.PlaneUnsupported as e:
            log.warning("model plane disabled for this engine (%s); "
                        "workers serve private model copies", e)
            state.disable_plane()
            plane_dir = None
        except Exception:
            # e.g. a read-only shared store: a plane that cannot be
            # written is useless — degrade to private models (the
            # pre-plane behavior) instead of failing the deploy
            log.exception("model plane seed publish failed; disabling "
                          "the plane — workers serve private model "
                          "copies")
            state.disable_plane()
            plane_dir = None
    if follow > 0 and plane_dir is not None and workers > 1:
        # prefork plane group: NO worker folds — a dedicated publisher
        # process (spawned below, next to the workers) hosts the one
        # follower and emits each generation into the arena
        pass
    elif follow > 0:
        # embedded follow-trainer: tail the event store every SECS and
        # hot-swap the in-process model (no persistence round trip).
        # Reached only outside prefork plane groups: a lone worker (with
        # or without the plane) hosts the one follower itself; plane-off
        # prefork workers each host their own (the legacy N-fold path —
        # PIO_MODEL_PLANE=off is the parity oracle).
        from predictionio_tpu.streaming.fold import FoldUnsupported
        from predictionio_tpu.streaming.follow import FollowTrainer

        try:
            state.follower = FollowTrainer(
                engine, engine_params, eid, engine_version, variant,
                storage=state.storage, interval=follow,
                # single-worker plane topology: the embedded follower IS
                # the publisher — emit to the arena, serve the mapped copy
                on_publish=(state.plane_publish if state.plane is not None
                            else state.swap_models),
                persist=False)
        except FoldUnsupported as e:
            # e.g. a data source with no app_name: nothing to tail —
            # serve without a follower rather than raising here, which
            # would leak the already-started auto-reload poller/plugins
            log.warning("--follow unsupported for this engine (%s); "
                        "deploying without a follower", e)
        else:
            state.follower.start()
    if plane_publish is not None and state.plane is not None:
        # publisher side of multi-node replication: stream every new
        # generation file + manifest flip to connected subscribers.  The
        # dir watcher covers publishes from the dedicated publisher
        # child; an embedded follower also pokes it directly.
        from predictionio_tpu.streaming.replicate import PlaneReplicator

        repl = PlaneReplicator(state.plane, bind=plane_publish)
        repl.start()
        state.replication = repl
        if state.follower is not None:
            state.follower.add_publish_listener(repl.poke)
    elif plane_from is not None and state.plane is not None:
        # subscriber side: land replicated containers into the local
        # plane dir; the PlaneWatcher started by QueryServerState (and
        # by every prefork sibling) installs them exactly as if a local
        # publisher had flipped the manifest
        from predictionio_tpu.streaming.replicate import PlaneSubscriber

        sub = PlaneSubscriber(state.plane.dir, plane_from)
        state.replication = sub
        # started below once the HTTP port is bound: every sync frame
        # then announces this node's endpoint, so the publisher's
        # federation can scrape /metrics and pull /lineage here
    child_procs: list = []
    # flight recorder: prefork children resolve the group's traces dir
    # from PIO_METRICS_DIR; single workers persist next to the storage
    # spans dir so the dashboard can merge them
    obs_tracing.arm(storage=state.storage)
    # lineage records persist next to the traces (children resolve the
    # group dir from PIO_METRICS_DIR); the history sampler gives every
    # serving process its /metrics/history.json ring + SLO gauges
    obs_lineage.arm(storage=state.storage)
    if obs_metrics.get_registry().enabled:
        obs_tsdb.start_sampler()
    httpd = start_server(make_handler(state), host, port,
                         background=background,
                         reuse_port=workers > 1 or reuse_port)
    bound_port = httpd.server_address[1]
    if plane_from is not None and state.replication is not None:
        state.replication.http_port = bound_port
        state.replication.start()
    elif plane_publish is not None and state.replication is not None:
        # cluster observability fabric (publisher only): lineage reads
        # answer with the stitched cross-node outcome, the federation
        # thread scrapes every subscriber's metrics/lineage, and the
        # cluster-scope SLO rows ride /healthz like any local SLO
        repl = state.replication
        obs_lineage.set_cluster_provider(repl.cluster_view)
        if obs_metrics.get_registry().enabled:
            fed = obs_cluster.ClusterFederation(repl.peers)
            fed.start()
            obs_cluster.set_federation(fed)
            obs_slo.arm_cluster_slos()
    if workers > 1:
        obs_tracing.arm(directory=os.path.join(metrics_dir, "traces"),
                        tag=f"w0-{os.getpid()}")
        obs_lineage.arm(directory=os.path.join(metrics_dir, "lineage"),
                        tag=f"w0-{os.getpid()}")
        # plane mode: children are pure consumers — no per-worker
        # follower (ONE fold per delta, in the publisher process below)
        # and no per-worker auto-reload poller (the parent's poller
        # publishes through the plane, converging everyone)
        plane_child_env = (
            {"PIO_MODEL_PLANE": "on", "PIO_MODEL_PLANE_DIR": plane_dir}
            if plane_dir is not None else {})
        child_procs = prefork.spawn_workers(
            workers - 1,
            lambda w: (
                [sys.executable, "-m", "predictionio_tpu.cli.main",
                 "deploy", "--engine-json", str(engine_json),
                 "--variant", variant,
                 "--engine-version", engine_version,
                 "--ip", host, "--port", str(bound_port), "--reuse-port"]
                + (["--engine-id", engine_id] if engine_id else [])
                + (["--feedback"] if feedback else [])
                + (["--auto-reload", str(auto_reload)]
                   if auto_reload and plane_dir is None else [])
                + (["--follow", str(follow)]
                   if follow and plane_dir is None else [])
            ),
            build_env=lambda w: {
                "PIO_METRICS_TAG": f"w{w + 1}-{os.getpid()}",
                "PIO_METRICS_DIR": metrics_dir,
                **plane_child_env},
            log=log,
        )
        if plane_dir is not None and follow > 0:
            # the ONE fold/emit/warm process per node: hosts the only
            # follower, publishes each generation into the arena, serves
            # no queries — fold CPU leaves the serving workers entirely.
            # Its metrics flush into the group dir, so any worker's
            # /metrics scrape shows the (single) fold counters.
            child_procs += prefork.spawn_workers(
                1,
                lambda w: (
                    [sys.executable, "-m", "predictionio_tpu.cli.main",
                     "deploy", "--engine-json", str(engine_json),
                     "--variant", variant,
                     "--engine-version", engine_version,
                     "--follow", str(follow), "--plane-publisher"]
                    + (["--engine-id", engine_id] if engine_id else [])
                ),
                build_env=lambda w: {
                    "PIO_METRICS_TAG": f"pub-{os.getpid()}",
                    "PIO_METRICS_DIR": metrics_dir,
                    "PIO_MODEL_PLANE_DIR": plane_dir},
                log=log,
            )
    log.info("Query server for %s listening on %s:%d", eid, host, bound_port)
    httpd.pio_state = state  # handle for tests/tools
    httpd.pio_workers = child_procs
    # the auto-reload poller (and any prefork workers) must die with the
    # server, however it is shut down (shutdown()/server_close(), /stop,
    # or pio undeploy)
    prefork.wire_shutdown(httpd, child_procs, before=state.stop_auto_reload)
    if metrics_dir is not None:
        prefork.wire_metrics_cleanup(httpd, metrics_dir)
    if background:
        return httpd
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
    return 0


def run_plane_publisher(
    engine_json: str,
    variant: str = "default",
    engine_id: Optional[str] = None,
    engine_version: str = "1",
    follow: float = 2.0,
) -> int:
    """The model plane's dedicated fold/emit process: hosts the ONE
    follow-trainer for a prefork group and publishes every generation
    into the arena (``on_publish`` = :meth:`ModelPlane.publish`) instead
    of serving queries.  Spawned by ``deploy --workers N --follow`` in
    plane mode (internal ``--plane-publisher`` flag); dies with the
    parent like any prefork child."""
    from predictionio_tpu.streaming.fold import FoldUnsupported
    from predictionio_tpu.streaming.follow import FollowTrainer
    from predictionio_tpu.streaming.plane import ModelPlane

    plane_dir = os.environ.get("PIO_MODEL_PLANE_DIR")
    if not plane_dir:
        print("Error: --plane-publisher requires PIO_MODEL_PLANE_DIR",
              file=sys.stderr)
        return 1
    prefork.maybe_watch_parent(log)
    obs_metrics.start_worker_flusher()
    obs_metrics.mark_worker_up()
    # the publisher OPENS every lineage record (fold + publish stages);
    # PIO_METRICS_DIR is in its spawn env, so arm() lands the records in
    # the group dir the serving workers merge from
    obs_lineage.arm()
    doc = load_engine_variant(engine_json, variant)
    factory, engine, engine_params = engine_from_variant(doc)
    eid = resolve_engine_id(engine_id, doc, factory)
    plane = ModelPlane(plane_dir)
    try:
        trainer = FollowTrainer(
            engine, engine_params, eid, engine_version, variant,
            interval=follow, on_publish=plane.publish, persist=False)
    except FoldUnsupported as e:
        # nothing to tail (no app_name): the workers keep their private
        # startup models; exiting loudly beats a zombie publisher
        print(f"Error: plane publisher cannot follow this engine: {e}",
              file=sys.stderr)
        return 1
    log.info("model-plane publisher for %s: folding every %.2fs into %s",
             eid, trainer.interval, plane_dir)
    try:
        trainer.run_forever()
    except KeyboardInterrupt:
        pass
    return 0


def run_server_from_args(args) -> int:
    from predictionio_tpu.workflow.create_workflow import resolve_variant_path

    if getattr(args, "plane_publisher", False):
        try:
            return run_plane_publisher(
                engine_json=resolve_variant_path(args),
                variant=args.variant,
                engine_id=args.engine_id,
                engine_version=args.engine_version,
                follow=getattr(args, "follow", 0.0) or 2.0,
            )
        except Exception as e:
            print(f"Error: {e}", file=sys.stderr)
            return 1
    try:
        result = deploy(
            engine_json=resolve_variant_path(args),
            variant=args.variant,
            engine_id=args.engine_id,
            engine_version=args.engine_version,
            host=args.ip,
            port=args.port,
            feedback=args.feedback,
            auto_reload=getattr(args, "auto_reload", 0.0) or 0.0,
            workers=getattr(args, "workers", 1) or 1,
            reuse_port=getattr(args, "reuse_port", False),
            follow=getattr(args, "follow", 0.0) or 0.0,
            plane_publish=getattr(args, "plane_publish", None),
            plane_from=getattr(args, "plane_from", None),
        )
    except Exception as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    return 0 if result == 0 else 0
