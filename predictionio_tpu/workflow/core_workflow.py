"""Training/evaluation orchestration.

Reference: core/.../workflow/CoreWorkflow.scala — ``runTrain`` records an
EngineInstance (INIT→TRAINING→COMPLETED/FAILED), runs Engine.train, persists
models; ``runEval`` runs the Evaluation and records an EvaluationInstance.
The spark-submit process boundary of the reference collapses to an in-process
call on the TPU VM (SURVEY.md §3 'pio train' stack).
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import traceback
from typing import Any, List, Optional

from predictionio_tpu.controller.engine import Engine, EngineParams, serialize_engine_params
from predictionio_tpu.controller.evaluation import Evaluation, MetricEvaluatorResult
from predictionio_tpu.core.base import doer_name
from predictionio_tpu.obs import spans as _spans
from predictionio_tpu.obs.metrics import get_registry
from predictionio_tpu.storage.base import EngineInstance, EvaluationInstance
from predictionio_tpu.storage.locator import Storage, get_storage
from predictionio_tpu.workflow import persistence

log = logging.getLogger("pio.workflow")

_REG = get_registry()
_M_TRAINS = _REG.counter(
    "pio_train_runs_total", "Training runs by final status")
_M_TRAIN_S = _REG.histogram(
    "pio_train_duration_seconds", "Wall-clock duration of training runs")
_M_EVALS = _REG.counter(
    "pio_eval_runs_total", "Evaluation runs by final status")
_M_TRAIN_STAGED = _REG.counter(
    "pio_train_staged_events_total",
    "Events staged during training runs, by source: snapshot = mmap'd "
    "columns, tail = JSONL past snapshot coverage, delta = JSONL past a "
    "retained batch's watermark (delta-aware retrain)")


def _staging_delta(before):
    """Per-mode staged-event counts accrued since ``before`` (a
    store.event_store.staging_counts snapshot)."""
    from predictionio_tpu.store.event_store import staging_counts

    after = staging_counts()
    return {mode: after[mode] - before.get(mode, 0.0) for mode in after}


def _now() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


def run_train(
    engine: Engine,
    engine_params: EngineParams,
    engine_id: str,
    engine_version: str = "1",
    engine_variant: str = "default",
    engine_factory: str = "",
    storage: Optional[Storage] = None,
    retries: Optional[int] = None,
) -> EngineInstance:
    """Train and persist: returns the COMPLETED EngineInstance (or raises,
    leaving a FAILED instance recorded).

    ``retries`` (default: PIO_TRAIN_RETRIES env, 0) re-runs Engine.train
    after a failure — the elastic-recovery analogue of Spark task retry in
    the reference.  Algorithms that checkpoint (e.g. ALS with
    checkpointEvery) resume from their newest snapshot instead of redoing
    completed sweeps.
    """
    import os

    storage = storage or get_storage()
    if retries is None:
        retries = int(os.environ.get("PIO_TRAIN_RETRIES", "0"))
    params_json = serialize_engine_params(engine_params)
    instance = EngineInstance(
        id="",
        status="INIT",
        start_time=_now(),
        end_time=None,
        engine_id=engine_id,
        engine_version=engine_version,
        engine_variant=engine_variant,
        engine_factory=engine_factory or engine_id,
        data_source_params=params_json["data_source_params"],
        preparator_params=params_json["preparator_params"],
        algorithms_params=params_json["algorithms_params"],
        serving_params=params_json["serving_params"],
    )
    instance_id = storage.engine_instances.insert(instance)
    instance.status = "TRAINING"
    storage.engine_instances.update(instance)
    attempt = 0
    # span journal persisted next to the engine instance: every timed()
    # inside engine.train nests under this run's root span, and
    # `pio dashboard` renders the breakdown per completed train
    journal = _spans.SpanJournal(_spans.journal_path(storage, instance_id))
    t_run = _dt.datetime.now(_dt.timezone.utc).timestamp()
    with journal.activate():
        with journal.span("train", engine_id=engine_id,
                          instance_id=instance_id):
            while True:
                try:
                    log.info("training engine %s (instance %s, attempt %d)",
                             engine_id, instance_id, attempt + 1)
                    from predictionio_tpu.store.event_store import staging_counts

                    stage_before = staging_counts()
                    with journal.span("engine_train", attempt=attempt + 1):
                        models = engine.train(engine_params)
                    # delta-aware retrain accounting: how many events this
                    # run staged from where (mmap'd snapshot vs parsed
                    # tail vs past-watermark delta) — recorded as a span
                    # attribute per run and a cross-run counter.  An
                    # all-zero read means the engine staged through a
                    # non-snapshot path (memory/sql/native full scan).
                    staged = _staging_delta(stage_before)
                    with journal.span("staging_summary", **{
                            f"staged_{k}": int(v) for k, v in staged.items()}):
                        pass
                    for mode, v in staged.items():
                        if v:
                            _M_TRAIN_STAGED.inc(v, mode=mode)
                    with journal.span("save_models"):
                        persistence.save_models(storage, instance_id, models)
                    instance.status = "COMPLETED"
                    instance.end_time = _now()
                    storage.engine_instances.update(instance)
                    log.info("training done: instance %s COMPLETED",
                             instance_id)
                    _M_TRAINS.inc(1, status="COMPLETED")
                    _M_TRAIN_S.observe(
                        _dt.datetime.now(_dt.timezone.utc).timestamp() - t_run)
                    return instance
                except Exception:
                    attempt += 1
                    if attempt <= retries:
                        log.warning(
                            "training attempt %d failed, retrying (%d left):\n%s",
                            attempt, retries - attempt + 1,
                            traceback.format_exc())
                        continue
                    instance.status = "FAILED"
                    instance.end_time = _now()
                    storage.engine_instances.update(instance)
                    log.error("training FAILED: %s", traceback.format_exc())
                    _M_TRAINS.inc(1, status="FAILED")
                    raise


def load_latest_models(
    engine_id: str,
    engine_version: str = "1",
    engine_variant: str = "default",
    storage: Optional[Storage] = None,
) -> tuple:
    """(instance, models) for the latest COMPLETED engine instance —
    the deploy-time lookup (reference: CreateServer resolving EngineInstance)."""
    storage = storage or get_storage()
    instance = storage.engine_instances.get_latest_completed(
        engine_id, engine_version, engine_variant
    )
    if instance is None:
        raise LookupError(
            f"no COMPLETED engine instance for {engine_id} v{engine_version} ({engine_variant}); "
            "run `pio train` first"
        )
    models = persistence.load_models(storage, instance.id)
    return instance, models


def _eval_results_html(result: MetricEvaluatorResult) -> str:
    """Candidate table for the dashboard (reference: EvaluationInstances'
    evaluatorResultsHTML rendered by the dashboard module)."""
    import html as _html

    rows = "".join(
        "<tr{hl}><td>{i}</td><td>{score:.6f}</td><td>{others}</td>"
        "<td><pre>{params}</pre></td></tr>".format(
            hl=' style="background:#e8f4e8"' if i == result.best_index else "",
            i=i + 1,
            score=score,
            others=_html.escape(", ".join(f"{o:.4f}" for o in others)),
            params=_html.escape(json.dumps(ep.to_json(), indent=1)[:2000]),
        )
        for i, (ep, score, others) in enumerate(result.engine_params_scores)
    )
    return (
        f"<h3>{_html.escape(result.metric_header)}</h3>"
        f"<table><tr><th>#</th><th>{_html.escape(result.metric_header)}</th>"
        f"<th>{_html.escape(', '.join(result.other_metric_headers))}</th>"
        f"<th>engine params</th></tr>{rows}</table>"
    )


def run_eval(
    evaluation: Evaluation,
    evaluation_class: str = "",
    storage: Optional[Storage] = None,
) -> MetricEvaluatorResult:
    """Run an Evaluation, record the EvaluationInstance, return the result."""
    storage = storage or get_storage()
    instance = EvaluationInstance(
        id="",
        status="EVALRUNNING",
        start_time=_now(),
        end_time=None,
        evaluation_class=evaluation_class or doer_name(evaluation),
    )
    instance_id = storage.evaluation_instances.insert(instance)
    journal = _spans.SpanJournal(_spans.journal_path(storage, instance_id))
    try:
        with journal.activate(), journal.span(
                "eval", instance_id=instance_id,
                evaluation_class=instance.evaluation_class):
            result = evaluation.run()
        instance.status = "EVALCOMPLETED"
        instance.end_time = _now()
        instance.evaluator_results = (
            f"{result.metric_header}: best={result.best_score:.6f} "
            f"(candidate {result.best_index + 1}/{len(result.engine_params_scores)})"
        )
        instance.evaluator_results_json = json.dumps(result.to_json())
        instance.evaluator_results_html = _eval_results_html(result)
        storage.evaluation_instances.update(instance)
        # counted only after the instance is durably COMPLETED: a
        # serialization/persistence failure above lands in the except
        # block, and one run must never count under both statuses
        _M_EVALS.inc(1, status="EVALCOMPLETED")
        return result
    except Exception:
        _M_EVALS.inc(1, status="EVALFAILED")
        instance.status = "EVALFAILED"
        instance.end_time = _now()
        storage.evaluation_instances.update(instance)
        raise
