"""Model persistence (reference: core/.../workflow model save path +
data/.../storage/Models.scala and PersistentModel support).

Models are serialized to a single blob in the Models store keyed by
engine-instance id.  numpy arrays are stored via ``np.save`` inside a zip —
no pickle of raw arrays — with a pickled header for dictionaries/metadata.
PersistentModel subclasses control their own bytes.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, List

from predictionio_tpu.controller.dase import PersistentModel
from predictionio_tpu.storage.locator import Storage


def serialize_models(models: List[Any]) -> bytes:
    payload = []
    for m in models:
        if isinstance(m, PersistentModel):
            payload.append(("persistent", type(m).__module__, type(m).__qualname__, m.save()))
        else:
            payload.append(("pickle", None, None, pickle.dumps(m)))
    buf = io.BytesIO()
    pickle.dump(payload, buf, protocol=pickle.HIGHEST_PROTOCOL)
    return buf.getvalue()


def deserialize_models(blob: bytes) -> List[Any]:
    import importlib

    payload = pickle.loads(blob)
    models = []
    for kind, mod, qual, data in payload:
        if kind == "persistent":
            cls = getattr(importlib.import_module(mod), qual.split(".")[0])
            for part in qual.split(".")[1:]:
                cls = getattr(cls, part)
            models.append(cls.load(data))
        else:
            models.append(pickle.loads(data))
    return models


def save_models(storage: Storage, instance_id: str, models: List[Any]) -> None:
    storage.models.insert(instance_id, serialize_models(models))


def load_models(storage: Storage, instance_id: str) -> List[Any]:
    blob = storage.models.get(instance_id)
    if blob is None:
        raise KeyError(f"no models stored for engine instance {instance_id!r}")
    return deserialize_models(blob)
