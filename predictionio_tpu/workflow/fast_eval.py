"""FastEval — memoized evaluation across engine-params candidates.

Reference: FastEvalEngine (core/.../workflow/; SURVEY.md §3 'pio eval' note):
when evaluating a grid of EngineParams, candidates that share a DASE prefix
(same dataSourceParams → same folds; + same preparatorParams → same prepared
data; + same algorithmParams → same trained models) reuse the earlier stage's
result instead of recomputing it.  Worth reproducing because hyperparameter
grids usually vary only the algorithm block.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Sequence, Tuple

from predictionio_tpu.controller.engine import Engine, EngineParams, _unpack_fold


def _key(params) -> str:
    return json.dumps(params.to_json(), sort_keys=True)


class FastEvalEngine:
    """Wraps an Engine with stage-level memoization for eval runs.

    Usage: ``MetricEvaluator(...).evaluate(engine, candidates,
    eval_runner=FastEvalEngine(engine).eval)``
    or pass to ``Evaluation.run(eval_runner=...)``.
    """

    def __init__(self, engine: Engine):
        self.engine = engine
        self._folds: Dict[str, List[Tuple[Any, Any, list]]] = {}
        self._prepared: Dict[str, List[Any]] = {}
        self._models: Dict[str, List[List[Any]]] = {}
        self.stats = {"folds": 0, "prepared": 0, "models": 0,
                      "folds_hit": 0, "prepared_hit": 0, "models_hit": 0}

    def _get_folds(self, engine_params: EngineParams):
        key = _key(engine_params.data_source_params)
        if key not in self._folds:
            data_source = self.engine.data_source_class(engine_params.data_source_params)
            self._folds[key] = [_unpack_fold(f) for f in data_source.read_eval()]
            self.stats["folds"] += 1
        else:
            self.stats["folds_hit"] += 1
        return key, self._folds[key]

    def _get_prepared(self, engine_params: EngineParams):
        folds_key, folds = self._get_folds(engine_params)
        key = folds_key + "|" + _key(engine_params.preparator_params)
        if key not in self._prepared:
            preparator = self.engine.preparator_class(engine_params.preparator_params)
            self._prepared[key] = [preparator.prepare(td) for td, _, _ in folds]
            self.stats["prepared"] += 1
        else:
            self.stats["prepared_hit"] += 1
        return key, folds, self._prepared[key]

    def _get_models(self, engine_params: EngineParams):
        prep_key, folds, prepared = self._get_prepared(engine_params)
        algo_key = json.dumps(
            [[name, p.to_json()] for name, p in engine_params.algorithm_params_list],
            sort_keys=True,
        )
        key = prep_key + "|" + algo_key
        if key not in self._models:
            per_fold = []
            for pd in prepared:
                algorithms = self._algorithms(engine_params)
                per_fold.append([algo.train(pd) for algo in algorithms])
            self._models[key] = per_fold
            self.stats["models"] += 1
        else:
            self.stats["models_hit"] += 1
        return folds, self._models[key]

    def _algorithms(self, engine_params: EngineParams):
        _, _, algorithms, _ = self.engine.make_components(engine_params)
        return algorithms

    def eval(self, engine: Engine, engine_params: EngineParams):
        """Signature-compatible with MetricEvaluator's eval_runner."""
        folds, per_fold_models = self._get_models(engine_params)
        algorithms = self._algorithms(engine_params)
        serving = self.engine.serving_class(engine_params.serving_params)
        results = []
        for (td, info, qa_pairs), models in zip(folds, per_fold_models):
            queries = [q for q, _ in qa_pairs]
            per_algo = [
                algo.batch_predict(model, queries)
                for algo, model in zip(algorithms, models)
            ]
            qpa = []
            for i, (q, a) in enumerate(qa_pairs):
                preds = [per_algo[j][i] for j in range(len(algorithms))]
                qpa.append((q, serving.serve(q, preds), a))
            results.append((info, qpa))
        return results
