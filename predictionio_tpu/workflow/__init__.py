from predictionio_tpu.workflow.core_workflow import (  # noqa: F401
    run_eval,
    run_train,
)
from predictionio_tpu.workflow.create_workflow import (  # noqa: F401
    load_engine_variant,
    resolve_engine_factory,
)
