"""Mid-training checkpoint/resume.

The reference has NO mid-training checkpointing (SURVEY.md §5: "no
mid-training checkpointing; 'checkpointing' = completed-model persistence
per engine instance") — Spark task retry restarts the whole job.  On TPU,
long CCO/ALS trainings are one process, so the framework provides what the
reference delegates to Spark: periodic factor/parameter snapshots plus a
retry loop in the train workflow that resumes from the newest snapshot
(workflow/core_workflow.run_train).

Storage is atomic ``.npz`` per step — training state here is always a flat
dict of host arrays (factors, weights) small enough that synchronous writes
cost nothing next to a sweep.  (orbax-checkpoint is the drop-in upgrade
path if/when sharded multi-host state needs async per-host writes.)
Layout::

    <dir>/step_<n>.npz
    <dir>/MANIFEST.json     {"steps": [...]}
"""

from __future__ import annotations

import json
import os
import shutil
from pathlib import Path
from typing import Any, List, Optional, Tuple

import numpy as np


class CheckpointStore:
    """Step-indexed pytree snapshots under one directory (one training run).

    Values must be a flat dict of numpy/jax arrays plus JSON-able scalars —
    the shape every algorithm's training state reduces to here.
    """

    def __init__(self, directory: str | os.PathLike, keep: int = 2):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.keep = keep

    # -- manifest ----------------------------------------------------------

    def _manifest_path(self) -> Path:
        return self.dir / "MANIFEST.json"

    def steps(self) -> List[int]:
        p = self._manifest_path()
        if not p.exists():
            return []
        return sorted(json.loads(p.read_text()).get("steps", []))

    def _write_manifest(self, steps: List[int]) -> None:
        tmp = self._manifest_path().with_suffix(f".tmp{os.getpid()}")
        tmp.write_text(json.dumps({"steps": sorted(steps)}))
        tmp.replace(self._manifest_path())

    # -- save / restore ----------------------------------------------------

    def save(self, step: int, state: dict) -> None:
        """Snapshot ``state`` (dict of arrays + scalars) as ``step``."""
        arrays = {}
        scalars = {}
        for k, v in state.items():
            if isinstance(v, (int, float, str, bool)) or v is None:
                scalars[k] = v
            else:
                arrays[k] = np.asarray(v)
        path = self.dir / f"step_{step}.npz"
        tmp = path.with_suffix(f".tmp{os.getpid()}.npz")
        with open(tmp, "wb") as f:
            np.savez(f, __scalars__=json.dumps(scalars), **arrays)
        tmp.replace(path)
        steps = [s for s in self.steps() if s != step] + [step]
        # prune oldest beyond keep
        for old in sorted(steps)[:-self.keep] if self.keep > 0 else []:
            self._delete(old)
            steps.remove(old)
        self._write_manifest(steps)

    def restore(self, step: int) -> dict:
        path = self.dir / f"step_{step}.npz"
        with np.load(path, allow_pickle=False) as z:
            state = {k: z[k] for k in z.files if k != "__scalars__"}
            state.update(json.loads(str(z["__scalars__"])))
        return state

    def latest(self) -> Optional[Tuple[int, dict]]:
        # Walk newest→oldest, skipping manifest entries whose step file is
        # gone (a concurrent run's prune/clear can race the manifest):
        # resume falls back to an older snapshot or a fresh run, never crashes.
        for step in reversed(self.steps()):
            try:
                return step, self.restore(step)
            except FileNotFoundError:
                continue
        return None

    def _delete(self, step: int) -> None:
        p = self.dir / f"step_{step}.npz"
        if p.exists():
            p.unlink()

    def clear(self, remove_dir: bool = False) -> None:
        shutil.rmtree(self.dir, ignore_errors=True)
        if not remove_dir:
            self.dir.mkdir(parents=True, exist_ok=True)


def prune_stale_runs(base_dir: str | os.PathLike, ttl_seconds: Optional[float] = None) -> int:
    """Remove per-run checkpoint subdirectories untouched for ``ttl_seconds``
    (default PIO_CHECKPOINT_TTL_SECONDS, else 7 days).

    Run-keyed dirs (checkpoints keyed by data+hyperparam fingerprint) are only
    reused by a resume of the *same* run; a crashed run whose data changes
    before the retry would otherwise leak its snapshots forever.  Returns the
    number of directories removed.
    """
    if ttl_seconds is None:
        ttl_seconds = float(os.environ.get("PIO_CHECKPOINT_TTL_SECONDS", 7 * 86400))
    base = Path(base_dir)
    if not base.exists():
        return 0
    import time

    now = time.time()
    removed = 0
    for d in base.iterdir():
        if not d.is_dir():
            continue
        try:
            newest = max(
                (f.stat().st_mtime for f in d.iterdir()), default=d.stat().st_mtime
            )
        except OSError:
            continue
        if now - newest > ttl_seconds:
            shutil.rmtree(d, ignore_errors=True)
            removed += 1
    return removed


# ---------------------------------------------------------------------------
# fault injection (test/ops tool; reference has none — SURVEY.md §5)
# ---------------------------------------------------------------------------


class InjectedFault(RuntimeError):
    pass


# hit counters keyed by the exact PIO_FAULT_INJECT config string, so a new
# config (different site OR different :n) always starts counting from zero
_fault_hits: dict = {}


def maybe_inject(site: str) -> None:
    """Raise InjectedFault once if PIO_FAULT_INJECT names this site.

    Format: ``PIO_FAULT_INJECT=site[:n]`` — fail the n-th hit (default 1st)
    of ``site``, then disarm.  Lets tests and operators rehearse the
    retry/resume path deterministically.
    """
    conf = os.environ.get("PIO_FAULT_INJECT", "")
    if not conf:
        return
    name, _, nth = conf.partition(":")
    if name != site:
        return
    count = _fault_hits.get(conf, 0) + 1
    _fault_hits[conf] = count
    if count >= (int(nth) if nth else 1):
        os.environ.pop("PIO_FAULT_INJECT", None)
        _fault_hits.pop(conf, None)
        raise InjectedFault(f"injected fault at {site!r} (hit {count})")
