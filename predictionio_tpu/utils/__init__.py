from predictionio_tpu.utils.config import load_pio_env  # noqa: F401
from predictionio_tpu.utils.tracing import named_scope, profile_to, timed  # noqa: F401
