from predictionio_tpu.utils.config import (  # noqa: F401
    apply_platform_override,
    load_pio_env,
)
from predictionio_tpu.utils.tracing import named_scope, profile_to, timed  # noqa: F401
from predictionio_tpu.utils.checkpoint import (  # noqa: F401
    CheckpointStore,
    InjectedFault,
    maybe_inject,
)
