"""pio-env.sh loader (reference: conf/pio-env.sh sourced by bin/pio —
SURVEY.md §5 'Config/flag system': env / engine.json / CLI triple).

The reference's launcher sources a shell file exporting PIO_* variables.
``load_pio_env`` parses the same file format (export lines, simple
assignments, comments, ${VAR} interpolation) without spawning a shell and
merges it into the process env so ``StorageConfig.from_env`` sees it.
"""

from __future__ import annotations

import os
import re
from pathlib import Path
from typing import Dict, Optional

_ASSIGN = re.compile(r"^(?:export\s+)?([A-Za-z_][A-Za-z0-9_]*)=(.*)$")
_REF = re.compile(r"\$\{?([A-Za-z_][A-Za-z0-9_]*)\}?")


def load_pio_env(
    path: Optional[str] = None,
    apply: bool = True,
    base: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    """Parse a pio-env.sh-style file; returns the variables it defines.

    Search order when path is None: $PIO_ENV_FILE, ./conf/pio-env.sh,
    ~/.pio/pio-env.sh.  Missing file → empty dict (defaults apply).
    """
    candidates = (
        [path]
        if path
        else [
            os.environ.get("PIO_ENV_FILE"),
            "conf/pio-env.sh",
            str(Path.home() / ".pio" / "pio-env.sh"),
        ]
    )
    found = next((c for c in candidates if c and Path(c).exists()), None)
    if found is None:
        return {}
    env: Dict[str, str] = dict(base if base is not None else os.environ)
    out: Dict[str, str] = {}
    for raw in Path(found).read_text().splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        m = _ASSIGN.match(line)
        if not m:
            continue
        name, value = m.group(1), m.group(2).strip()
        single_quoted = len(value) >= 2 and value[0] == value[-1] == "'"
        if value and value[0] == value[-1] and value[0] in "\"'" and len(value) >= 2:
            value = value[1:-1]
        if not single_quoted:
            # shell `source` semantics: no ${VAR} expansion inside 'single quotes'
            value = _REF.sub(lambda mm: env.get(mm.group(1), ""), value)
        env[name] = value
        out[name] = value
    if apply:
        os.environ.update(out)
    return out


def apply_platform_override() -> None:
    """PIO_JAX_PLATFORM=cpu|tpu pins the JAX backend before first use.

    Env-var JAX_PLATFORMS alone can be overridden by host site config, so
    entry points (pio CLI, bench.py) apply it programmatically via
    jax.config; must run before any jax backend initialization.
    """
    plat = os.environ.get("PIO_JAX_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


def enable_compilation_cache() -> None:
    """Persistent XLA compilation cache shared across pio processes.

    Every `pio train` / `pio deploy` is a fresh process; without this the
    big CCO/ALS programs recompile each run (~76 s of a 108 s end-to-end
    UR train at a 100k-item catalog measured on TPU v5e — 70% of the
    wall clock).  The on-disk cache makes every run after the first skip
    straight to execution, like the reference's long-lived warmed JVM.
    PIO_JAX_CACHE overrides the location; PIO_JAX_CACHE=off disables.
    """
    loc = os.environ.get("PIO_JAX_CACHE", "")
    if loc.lower() == "off":
        return
    if not loc:
        loc = os.path.join(
            os.path.expanduser("~"), ".cache", "predictionio_tpu", "xla")
    try:
        os.makedirs(loc, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", loc)
        # cache everything that took meaningful compile time; tiny programs
        # stay in-memory only (PIO_JAX_CACHE_MIN_S tunes the cutoff)
        min_s = float(os.environ.get("PIO_JAX_CACHE_MIN_S", "1.0"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", min_s)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as e:  # cache is an optimization, never a hard failure
        import logging

        logging.getLogger("pio.config").warning(
            "persistent XLA cache unavailable at %s: %s", loc, e)
