"""Tracing/profiling helpers.

The reference has no custom tracer (SURVEY.md §5) — it leans on the Spark UI.
The TPU-native equivalents: ``jax.named_scope`` for XLA-visible annotation,
``jax.profiler`` traces viewable in xprof/tensorboard, and a lightweight
wall-clock timer that feeds the workflow logs — and, when a span journal
is active (``obs.spans``: ``pio train``/``pio eval`` activate one per
run), every ``timed()`` block also lands in the journal as a structured
span with parent/child links.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Iterator, Optional

log = logging.getLogger("pio.trace")


@contextlib.contextmanager
def named_scope(name: str) -> Iterator[None]:
    """XLA-visible scope (shows up in xprof timelines and HLO names)."""
    import jax

    with jax.named_scope(name):
        yield


@contextlib.contextmanager
def profile_to(log_dir: str, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a jax.profiler trace into log_dir (view with xprof/tensorboard).

    ``host_tracer_level`` (0 = host tracing off, 1 = critical events,
    2 = info, 3 = verbose) is honored via ``jax.profiler.ProfileOptions``
    where the installed jax exposes it (≥ 0.5); older jax (e.g. the 0.4.x
    line) offers no per-trace option hook on ``start_trace`` at all — its
    signature is ``(log_dir, create_perfetto_link, create_perfetto_trace)``
    — so there the level is logged-and-skipped rather than silently
    dropped."""
    import jax

    options = None
    if host_tracer_level != 2 and hasattr(jax.profiler, "ProfileOptions"):
        options = jax.profiler.ProfileOptions()
        options.host_tracer_level = host_tracer_level
    if options is not None:
        jax.profiler.start_trace(log_dir, profiler_options=options)
    else:
        if host_tracer_level != 2:
            log.warning(
                "host_tracer_level=%d requested but this jax (%s) has no "
                "ProfileOptions; tracing at the default level",
                host_tracer_level, jax.__version__)
        jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def timed(name: str, sink: Optional[dict] = None) -> Iterator[None]:
    """Wall-clock span logged at INFO; optionally recorded into sink.

    ``sink[name]`` accumulates seconds across calls and
    ``sink[name + ".count"]`` the number of calls, so a sink consumer can
    tell one 10 s span from a thousand 10 ms ones.  When a span journal
    is active (obs.spans: train/eval runs), the block is also recorded
    there as a structured span (with parent/child nesting); otherwise,
    when a request trace is live (obs.tracing flight recorder), it lands
    in that trace's waterfall instead."""
    from predictionio_tpu.obs import spans as _spans
    from predictionio_tpu.obs import tracing as _tracing

    sink_obj = _spans.current_journal() or _tracing.current_trace()
    ctx = sink_obj.span(name) if sink_obj is not None else contextlib.nullcontext()
    t0 = time.perf_counter()
    try:
        with ctx:
            yield
    finally:
        dt = time.perf_counter() - t0
        log.info("%s took %.3fs", name, dt)
        if sink is not None:
            sink[name] = sink.get(name, 0.0) + dt
            count_key = name + ".count"
            sink[count_key] = sink.get(count_key, 0) + 1
