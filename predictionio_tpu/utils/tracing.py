"""Tracing/profiling helpers.

The reference has no custom tracer (SURVEY.md §5) — it leans on the Spark UI.
The TPU-native equivalents: ``jax.named_scope`` for XLA-visible annotation,
``jax.profiler`` traces viewable in xprof/tensorboard, and a lightweight
wall-clock timer that feeds the workflow logs.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Iterator, Optional

log = logging.getLogger("pio.trace")


@contextlib.contextmanager
def named_scope(name: str) -> Iterator[None]:
    """XLA-visible scope (shows up in xprof timelines and HLO names)."""
    import jax

    with jax.named_scope(name):
        yield


@contextlib.contextmanager
def profile_to(log_dir: str, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a jax.profiler trace into log_dir (view with xprof/tensorboard)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def timed(name: str, sink: Optional[dict] = None) -> Iterator[None]:
    """Wall-clock span logged at INFO; optionally recorded into sink[name]."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        log.info("%s took %.3fs", name, dt)
        if sink is not None:
            sink[name] = sink.get(name, 0.0) + dt
