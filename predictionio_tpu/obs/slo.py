"""SLO burn-rate health engine over the local metrics history ring.

A declarative SLO table (the numbers the streaming arc's PRs promised:
append→servable p99, serve p95, zero cache-audit mismatches, bounded
replica lag, bounded plane delta-chain length) evaluated over
:mod:`obs.tsdb`'s sample ring with the SRE-workbook multi-window
pattern: a FAST window (default 60 s) catches a fresh regression, a
SLOW window (default 600 s) filters one-sample blips — an SLO reads
``burning`` only when BOTH windows' burn rates exceed 1.

Burn rate = (fraction of window intervals violating the threshold) /
(error budget, default 10% of intervals), so burn 1.0 means the budget
is being consumed exactly as fast as it accrues.  Verdicts surface at
``/healthz`` (always HTTP 200 — the body carries the health, so load
balancers and humans share one endpoint) and as
``pio_slo_burn_rate{slo,window}`` gauges refreshed on every sampler
tick.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

from predictionio_tpu.obs import metrics as _metrics
from predictionio_tpu.obs.exposition import _quantile_from_buckets

_REG = _metrics.get_registry()
_M_BURN = _REG.gauge(
    "pio_slo_burn_rate",
    "Error-budget burn rate per {slo} and {window} (fast/slow): "
    "violating-interval fraction over the window divided by the error "
    "budget; > 1 in BOTH windows = the SLO is burning (/healthz goes "
    "red)")

# the declarative SLO table: kind decides how an interval (a pair of
# consecutive history samples) is judged against the threshold —
#   histogram_quantile: interval quantile of new observations > threshold
#   counter_delta:      counter increase over the interval > threshold
#   gauge_max:          max series value at the interval's end > threshold
# `match` filters series by a label-body substring ('' = every series)
DEFAULT_SLOS: Tuple[Dict, ...] = (
    {"name": "append_servable_p99", "kind": "histogram_quantile",
     "metric": "pio_follow_fold_duration_seconds", "match": "",
     "q": 0.99, "threshold": 10.0,
     "help": "append-to-servable fold-tick p99 <= 10 s (PR 13's gate)"},
    {"name": "serve_p95", "kind": "histogram_quantile",
     "metric": "pio_http_request_duration_seconds",
     "match": 'route="/queries.json"', "q": 0.95, "threshold": 0.25,
     "help": "query latency p95 <= 250 ms"},
    {"name": "cache_audit", "kind": "counter_delta",
     "metric": "pio_serve_cache_audit_mismatch_total", "match": "",
     "threshold": 0.0,
     "help": "response-cache online audit mismatches == 0 (PR 16's "
             "zero-staleness contract)"},
    {"name": "replica_lag", "kind": "gauge_max",
     "metric": "pio_store_replica_lag_events", "match": "",
     "threshold": 10000.0,
     "help": "sharded-store replica lag <= 10k events"},
    {"name": "plane_chain", "kind": "gauge_max",
     "metric": "pio_model_plane_chain_len", "match": "",
     "threshold": 16.0,
     "help": "delta-arena chain length <= 16 (keyframe cadence healthy)"},
)

# cluster-scope rows, armed only on a replication publisher
# (``arm_cluster_slos``).  They evaluate over the SAME local sample
# ring as everything else: the federation layer re-exports its derived
# signals as local publisher metrics (obs.cluster), and the per-peer
# repl-lag gauge already lives here — no second evaluation engine.
CLUSTER_SLOS: Tuple[Dict, ...] = (
    {"name": "cluster_propagation_p99", "kind": "histogram_quantile",
     "metric": "pio_cluster_propagation_seconds", "match": "",
     "q": 0.99, "threshold": 10.0,
     "help": "append -> LAST node's first_serve p99 <= 10 s, read from "
             "stitched cluster_complete lineage records"},
    {"name": "cluster_repl_lag", "kind": "gauge_max",
     "metric": "pio_plane_repl_lag_generations", "match": "",
     "threshold": 8.0,
     "help": "slowest subscriber <= 8 generations behind the publisher"},
    {"name": "cluster_qps_divergence", "kind": "gauge_max",
     "metric": "pio_cluster_qps_divergence", "match": "",
     "threshold": 4.0,
     "help": "hottest node's serve qps <= 4x the cluster mean "
             "(load staying balanced)"},
    {"name": "cluster_p95_divergence", "kind": "gauge_max",
     "metric": "pio_cluster_p95_divergence", "match": "",
     "threshold": 4.0,
     "help": "slowest node's serve p95 <= 4x the cluster mean "
             "(no straggler node)"},
)


def arm_cluster_slos() -> "SloEngine":
    """Fold the cluster-scope rows into the process engine (replication
    publishers call this next to federation start; idempotent) — their
    verdicts then ride /healthz and pio_slo_burn_rate like any local
    SLO."""
    eng = get_engine()
    have = {s["name"] for s in eng.slos}
    extra = tuple(s for s in CLUSTER_SLOS if s["name"] not in have)
    if extra:
        eng.slos = eng.slos + extra
    return eng


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def slo_windows() -> Tuple[float, float]:
    """(fast, slow) burn windows in seconds — PIO_SLO_FAST_S /
    PIO_SLO_SLOW_S (defaults 60 / 600)."""
    return (max(_env_float("PIO_SLO_FAST_S", 60.0), 1.0),
            max(_env_float("PIO_SLO_SLOW_S", 600.0), 1.0))


def slo_budget() -> float:
    """PIO_SLO_BUDGET: allowed violating-interval fraction (default
    0.1 — one interval in ten may breach before burn reads 1)."""
    return min(max(_env_float("PIO_SLO_BUDGET", 0.1), 1e-4), 1.0)


def _series_sum_hist(entry: Optional[dict], match: str) -> Optional[dict]:
    """Slot-wise sum of every histogram series whose label body contains
    ``match``; None when nothing matches."""
    if not entry or entry.get("type") != "histogram":
        return None
    acc = None
    for key, v in entry.get("series", {}).items():
        if match and match not in key:
            continue
        if acc is None:
            acc = {"counts": list(v["counts"]), "sum": float(v["sum"]),
                   "count": int(v["count"])}
        else:
            acc["counts"] = [a + b for a, b in zip(acc["counts"],
                                                   v["counts"])]
            acc["sum"] += float(v["sum"])
            acc["count"] += int(v["count"])
    return acc


def _series_total(entry: Optional[dict], match: str) -> Optional[float]:
    if not entry or "series" not in entry:
        return None
    vals = [float(v) for k, v in entry["series"].items()
            if not match or match in k]
    return sum(vals) if vals else None


def _series_max(entry: Optional[dict], match: str) -> Optional[float]:
    if not entry or "series" not in entry:
        return None
    vals = [float(v) for k, v in entry["series"].items()
            if not match or match in k]
    return max(vals) if vals else None


def _interval_verdict(slo: Dict, prev: dict, cur: dict,
                      buckets: Dict[str, List[float]]):
    """(bad, value) for one consecutive-sample interval, or None when
    the interval carries no signal for this SLO (no series yet, or a
    quantile window with zero new observations)."""
    metric = slo["metric"]
    e_prev = prev.get("m", {}).get(metric)
    e_cur = cur.get("m", {}).get(metric)
    kind = slo["kind"]
    if kind == "gauge_max":
        v = _series_max(e_cur, slo.get("match", ""))
        if v is None:
            return None
        return v > slo["threshold"], v
    if kind == "counter_delta":
        c0 = _series_total(e_prev, slo.get("match", ""))
        c1 = _series_total(e_cur, slo.get("match", ""))
        if c1 is None:
            return None
        delta = c1 - (c0 or 0.0)
        if delta < 0:   # a worker restarted and its counter reset
            delta = c1
        return delta > slo["threshold"], delta
    if kind == "histogram_quantile":
        h1 = _series_sum_hist(e_cur, slo.get("match", ""))
        if h1 is None:
            return None
        h0 = _series_sum_hist(e_prev, slo.get("match", ""))
        counts = list(h1["counts"])
        total = h1["count"]
        if h0 is not None and h0["count"] <= h1["count"]:
            counts = [a - b for a, b in zip(h1["counts"], h0["counts"])]
            total = h1["count"] - h0["count"]
        if total <= 0:
            return None   # no new observations this interval
        bounds = buckets.get(metric)
        if not bounds:
            return None
        cum, pairs = 0.0, []
        for le, c in zip(list(bounds) + [float("inf")], counts):
            cum += max(c, 0)
            pairs.append((le, cum))
        q = _quantile_from_buckets(pairs, total, float(slo.get("q", 0.99)))
        return q > slo["threshold"], q
    return None


class SloEngine:
    """Evaluates the SLO table over a sample ring; caches the last
    verdict for /healthz and keeps the burn gauges fresh."""

    def __init__(self, slos: Optional[Tuple[Dict, ...]] = None):
        self.slos = tuple(slos if slos is not None else DEFAULT_SLOS)
        self._lock = threading.Lock()
        self._last: Optional[dict] = None

    def evaluate(self, samples: List[dict],
                 buckets: Dict[str, List[float]]) -> dict:
        fast_s, slow_s = slo_windows()
        budget = slo_budget()
        now = samples[-1]["t"] if samples else 0.0
        verdicts: Dict[str, dict] = {}
        for slo in self.slos:
            windows = {}
            last_value = None
            for wname, wlen in (("fast", fast_s), ("slow", slow_s)):
                bad = seen = 0
                for prev, cur in zip(samples, samples[1:]):
                    if now - cur["t"] > wlen:
                        continue
                    res = _interval_verdict(slo, prev, cur, buckets)
                    if res is None:
                        continue
                    seen += 1
                    if res[0]:
                        bad += 1
                    last_value = res[1]
                if seen == 0:
                    windows[wname] = {"burn": 0.0, "intervals": 0}
                    continue
                burn = (bad / seen) / budget
                windows[wname] = {"burn": round(burn, 3),
                                  "intervals": seen}
                _M_BURN.set(burn, slo=slo["name"], window=wname)
            fast = windows.get("fast", {})
            slow = windows.get("slow", {})
            if fast.get("intervals", 0) == 0 \
                    and slow.get("intervals", 0) == 0:
                verdict = "no_data"
            elif fast.get("burn", 0) > 1.0 and slow.get("burn", 0) > 1.0:
                verdict = "burning"
            elif fast.get("burn", 0) > 1.0 or slow.get("burn", 0) > 1.0:
                verdict = "warn"
            else:
                verdict = "ok"
            verdicts[slo["name"]] = {
                "verdict": verdict,
                "threshold": slo["threshold"],
                "metric": slo["metric"],
                "kind": slo["kind"],
                "lastValue": (round(last_value, 6)
                              if isinstance(last_value, float)
                              else last_value),
                "windows": windows,
                "help": slo.get("help", ""),
            }
        order = ("burning", "warn", "ok", "no_data")
        present = [v["verdict"] for v in verdicts.values()]
        status = next((s for s in order if s in present), "no_data")
        doc = {"status": status, "budget": budget,
               "windows": {"fastSeconds": fast_s, "slowSeconds": slow_s},
               "samples": len(samples), "slos": verdicts}
        with self._lock:
            self._last = doc
        return doc

    def healthz(self) -> dict:
        """The /healthz body: evaluate over the live ring (taking a
        fresh sample first so a just-started server answers from data,
        not ``no_data`` staleness)."""
        from predictionio_tpu.obs import tsdb as _tsdb

        sampler = _tsdb.get_sampler()
        try:
            sampler.sample_now()
        except Exception:
            pass
        with self._lock:
            if self._last is not None:
                return self._last
        return self.evaluate(sampler.samples(), sampler._buckets_copy())


_engine: Optional[SloEngine] = None
_engine_lock = threading.Lock()


def get_engine() -> SloEngine:
    global _engine
    with _engine_lock:
        if _engine is None:
            _engine = SloEngine()
        return _engine


def set_engine(engine: Optional[SloEngine]) -> None:
    """Swap the process engine (tests; None resets to lazy default)."""
    global _engine
    with _engine_lock:
        _engine = engine


def handle_healthz_request(handler, path: str) -> bool:
    """Serve /healthz on any JsonHandler server; returns True when the
    path was ours.  Always HTTP 200 — the JSON ``status`` field carries
    the verdict (ok | warn | burning | no_data)."""
    if path != "/healthz":
        return False
    if not _metrics.get_registry().enabled:
        handler.send_json({"status": "no_data",
                           "reason": "metrics disabled (PIO_METRICS=off)"})
        return True
    handler.send_json(get_engine().healthz())
    return True
