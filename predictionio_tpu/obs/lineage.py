"""Generation lineage: cross-process freshness tracing.

PR 5's flight recorder traces one REQUEST inside one process; the
streaming pipeline now spans a follower loop, a dedicated publisher
process, and K prefork serving workers.  This module traces one MODEL
GENERATION across all of them: every fold tick mints a lineage id
(``ln-<hex>``), carried through the publish job, the delta-arena
manifest header (``plane.py`` writes it into ``meta["info"]``;
``PlaneWatcher`` reads it back after compose), and closed by each
worker at install plus at the first query served against that
generation — yielding an exact per-generation waterfall::

    append_observed -> fold.apply -> fold.rellr -> fold.emit ->
    publish -> plane.write -> watcher_wake -> compose ->
    install (per worker, + cache_invalidation child) ->
    first_serve (per worker)

Each process appends *stages* to a bounded record ring persisted to
``<lineage dir>/<worker tag>.json`` (the same sibling-merge pattern as
``/metrics`` and ``/traces.json``), so ANY worker can answer
``/lineage.json`` (index) and ``/lineage/<gen>.json`` (full waterfall)
for the whole group: the merge unions every process's stages by lineage
id.  A record whose origin process died mid-publish (SIGKILL) is left
``open`` on disk; the merge closes it as ``abandoned`` as soon as a
newer generation reaches publish — no cooperation from the dead process
needed, nothing leaks.

Lineage dir precedence (:func:`lineage_dir`): ``PIO_LINEAGE_DIR``, else
``<PIO_METRICS_DIR>/lineage`` (prefork groups), else ``<storage
localfs/sharedfs METADATA path>/lineage``, else in-memory only.  Kill
switch: ``PIO_LINEAGE=off``.  This propagation contract is what the
multi-node fabric (ROADMAP item 1) will reuse verbatim: a replicated
manifest carries the same ``lineageId`` to other kernels.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import uuid
from collections import deque
from pathlib import Path
from typing import Dict, List, Optional

from predictionio_tpu.obs import metrics as _metrics

_REG = _metrics.get_registry()
_M_RECORDS = _REG.counter(
    "pio_lineage_records_total",
    "Lineage records begun by this process (one per fold tick that "
    "reached the fold stage)")
_M_STAGES = _REG.counter(
    "pio_lineage_stages_total",
    "Lineage stages recorded by this process, by stage name")

# stage order used to sanity-sort ties and by renderers; merge order is
# by wall-clock start, this is only the canonical pipeline sequence.
# The repl.* stages are the replication channel's hops on a subscriber
# node (plan is publisher-side, per peer): the cross-node stretch of the
# same waterfall.
STAGE_ORDER = (
    "append_observed", "fold.apply", "fold.rellr", "fold.emit",
    "publish", "plane.write", "repl.plan", "repl.recv", "repl.verify",
    "repl.land", "watcher_wake", "compose", "install",
    "cache_invalidation", "first_serve",
)
# a record is complete once the publish side AND at least one worker's
# install + first-serve are visible in the merged view.  repl.land
# counts as publish-equivalent: on a subscriber node the replicated
# flip IS the publish (the publisher's own stages may not be visible
# locally), which also lets supersession close a reconnecting
# subscriber's pre-resync orphans (see merge_records).
_PUBLISH_STAGES = frozenset({"publish", "plane.write", "repl.land"})


def cluster_node() -> Optional[str]:
    """This process's cluster node name (PIO_CLUSTER_NODE; deploy sets
    it whenever replication is wired).  None = single-node deployment —
    stages carry no node field and nothing changes."""
    return os.environ.get("PIO_CLUSTER_NODE") or None


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def lineage_dir(storage=None) -> Optional[Path]:
    """Where this process persists lineage records for siblings (see
    module docstring for the precedence); None = in-memory ring only."""
    env = os.environ.get("PIO_LINEAGE_DIR")
    if env:
        return Path(env)
    md = os.environ.get("PIO_METRICS_DIR")
    if md:
        return Path(md) / "lineage"
    if storage is not None:
        try:
            src = storage.config.sources[storage.config.repositories["METADATA"]]
            if src.get("type") in ("localfs", "sharedfs") and src.get("path"):
                return Path(src["path"]) / "lineage"
        except (KeyError, AttributeError):
            pass
    return None


class LineageRecorder:
    """Per-process bounded record ring + the cross-process merge.

    Thread-safe; every mutator tolerates an unknown lineage id by
    creating a *partial* record (a serving worker contributes install/
    first-serve stages for a generation whose record was begun in the
    publisher process — the merge reunites them by id)."""

    # stage writes within this window coalesce into one ring write;
    # begin/close/flush-flagged stages persist immediately so a SIGKILL
    # can lose at most a window of *intermediate* stages, never the
    # record itself
    PERSIST_THROTTLE_S = 0.5

    def __init__(self, ring: Optional[int] = None,
                 directory: Optional[os.PathLike] = None,
                 tag: Optional[str] = None,
                 enabled: Optional[bool] = None):
        if enabled is None:
            enabled = os.environ.get("PIO_LINEAGE", "").lower() not in (
                "off", "0", "false")
        self.enabled = enabled
        size = ring if ring is not None else max(
            _env_int("PIO_LINEAGE_RING", 64), 1)
        self._ring: deque = deque(maxlen=size)
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()
        self.dir: Optional[Path] = Path(directory) if directory else None
        self._tag = tag
        self._dirty = False
        self._last_persist = 0.0
        self._flush_timer: Optional[threading.Timer] = None

    @property
    def tag(self) -> str:
        return self._tag or _metrics.worker_tag()

    def configure(self, directory: Optional[os.PathLike],
                  tag: Optional[str] = None) -> None:
        with self._lock:
            self.dir = Path(directory) if directory else None
            if tag is not None:
                self._tag = tag

    # -- record lifecycle ----------------------------------------------------

    def new_id(self) -> str:
        return f"ln-{uuid.uuid4().hex[:12]}"

    def _find(self, lid: str) -> Optional[dict]:
        for doc in reversed(self._ring):
            if doc.get("lid") == lid:
                return doc
        return None

    def _ensure(self, lid: str, origin: bool) -> dict:
        doc = self._find(lid)
        if doc is None:
            doc = {"lid": lid, "start": time.time(), "generation": None,
                   "outcome": "open", "stages": []}
            if origin:
                doc["origin"] = self.tag
            self._ring.append(doc)
        elif origin and "origin" not in doc:
            doc["origin"] = self.tag
        return doc

    def begin(self, lid: str, start: Optional[float] = None) -> None:
        """Open a lineage record in THIS process (the fold tick's
        origin).  Persisted immediately: a publisher SIGKILLed
        mid-publish leaves the open record on disk for the merge to
        close as ``abandoned``."""
        if not self.enabled:
            return
        with self._lock:
            doc = self._ensure(lid, origin=True)
            if start is not None:
                doc["start"] = float(start)
            self._dirty = True
        _M_RECORDS.inc()
        self._persist()

    def stage(self, lid: str, name: str, start: Optional[float] = None,
              duration_s: float = 0.0, parent: Optional[str] = None,
              flush: bool = False, node: Optional[str] = None,
              **attrs) -> None:
        """Append one stage to ``lid``'s record (creating a partial
        record when this process never saw ``begin`` — the cross-process
        case).  ``attrs`` values must be JSON-able scalars.  ``node``
        overrides the stage's cluster-node stamp (replication daemons
        hosting several logical nodes in one process); by default the
        stamp comes from PIO_CLUSTER_NODE at the source, so stitched
        records attribute every stage without ingest-time guessing."""
        if not self.enabled:
            return
        s: Dict = {"stage": name, "start": float(start if start is not None
                                                 else time.time()),
                   "duration_s": round(float(duration_s), 6),
                   "worker": self.tag}
        nd = node or cluster_node()
        if nd:
            s["node"] = nd
        if parent:
            s["parent"] = parent
        if attrs:
            s["attrs"] = attrs
        with self._lock:
            doc = self._ensure(lid, origin=False)
            doc["stages"].append(s)
            if doc["start"] > s["start"]:
                doc["start"] = s["start"]
            self._dirty = True
        _M_STAGES.inc(1, stage=name)
        if flush:
            self._persist()
        else:
            self._request_persist()

    def note_generation(self, lid: str, generation: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            doc = self._ensure(lid, origin=False)
            doc["generation"] = int(generation)
            self._dirty = True
        self._request_persist()

    def close(self, lid: str, outcome: str = "published") -> None:
        """Origin-side close after a successful publish; the merged
        outcome (`complete`/`abandoned`) is computed at read time from
        every process's stages."""
        if not self.enabled:
            return
        with self._lock:
            doc = self._ensure(lid, origin=True)
            doc["outcome"] = outcome
            self._dirty = True
        self._persist()

    def ingest(self, records, node: Optional[str] = None) -> int:
        """Merge record fragments received from ANOTHER node (the
        replication ack payload, or a federation pull of a subscriber's
        ``/lineage/<gen>.json``) into this process's ring — the
        publisher-side half of cross-node lineage stitching.  Only the
        raw fields (lid, start, generation, stages) are taken; derived
        fields (outcome, workers, durationMs) are recomputed at merge
        time.  Stages dedupe on the merge key, so re-ingesting the same
        fragment (push + pull overlap, shared-dir topologies) is a
        no-op.  Node attribution is SOURCE-stamped only — a stage
        without a ``node`` field stays unattributed rather than being
        guessed from the sender (``node`` here is informational): in a
        shared-lineage-dir topology a subscriber's fragment can carry
        the publisher's own stages back, and stamping those with the
        sender's name would mark its lane complete for work it never
        did.  Returns the number of stages actually added."""
        if not self.enabled:
            return 0
        added = 0
        with self._lock:
            for rdoc in records or ():
                lid = rdoc.get("lid")
                if not isinstance(lid, str) or not lid.startswith("ln-"):
                    continue
                doc = self._ensure(lid, origin=False)
                seen = {(s.get("stage"), s.get("worker"),
                         round(float(s.get("start") or 0), 6))
                        for s in doc["stages"]}
                for s in rdoc.get("stages", ()):
                    if not isinstance(s, dict) or not s.get("stage"):
                        continue
                    key = (s.get("stage"), s.get("worker"),
                           round(float(s.get("start") or 0), 6))
                    if key in seen:
                        continue
                    seen.add(key)
                    cp = {"stage": str(s["stage"]),
                          "start": float(s.get("start") or 0),
                          "duration_s": round(
                              float(s.get("duration_s") or 0), 6),
                          "worker": str(s.get("worker") or "")}
                    if s.get("node"):
                        cp["node"] = str(s["node"])
                    if s.get("parent"):
                        cp["parent"] = str(s["parent"])
                    if isinstance(s.get("attrs"), dict):
                        cp["attrs"] = dict(s["attrs"])
                    doc["stages"].append(cp)
                    if doc["start"] > cp["start"] > 0:
                        doc["start"] = cp["start"]
                    added += 1
                if rdoc.get("generation") is not None \
                        and doc.get("generation") is None:
                    try:
                        doc["generation"] = int(rdoc["generation"])
                    except (TypeError, ValueError):
                        pass
            if added:
                self._dirty = True
        if added:
            self._request_persist()
        return added

    def export(self, limit: int = 8) -> List[dict]:
        """The newest merged records as raw push fragments (the ack
        payload a subscriber ships back to its publisher): only the raw
        fields ingest() accepts, bounded to the last ``limit`` records
        so an ack stays a few KB."""
        out = []
        for d in self.merged()[:max(limit, 1)]:
            out.append({"lid": d.get("lid"), "start": d.get("start"),
                        "generation": d.get("generation"),
                        "stages": d.get("stages", [])})
        return out

    # -- persistence + cross-process merge -----------------------------------

    def _request_persist(self) -> None:
        if self.dir is None:
            return
        delay = self.PERSIST_THROTTLE_S - (
            time.monotonic() - self._last_persist)
        if delay <= 0:
            self._persist()
            return
        with self._lock:
            if self._flush_timer is not None:
                return
            t = self._flush_timer = threading.Timer(delay, self._timer_flush)
            t.daemon = True
        t.start()

    def _timer_flush(self) -> None:
        with self._lock:
            self._flush_timer = None
        self.flush()

    def _persist(self) -> None:
        if self.dir is None:
            return
        with self._io_lock:
            with self._lock:
                payload = {"worker": self.tag, "flushedAt": time.time(),
                           "records": [dict(d, stages=list(d["stages"]))
                                       for d in self._ring]}
                self._dirty = False
            self._last_persist = time.monotonic()
            path = self.dir / f"{self.tag}.json"
            tmp = path.with_name(path.name + f".tmp{os.getpid()}")
            try:
                os.makedirs(self.dir, exist_ok=True)
                with open(tmp, "w") as f:
                    json.dump(payload, f)
                os.replace(tmp, path)
            except OSError:
                with self._lock:
                    self._dirty = True
                with contextlib.suppress(OSError):
                    os.unlink(tmp)

    def flush(self) -> None:
        if self._dirty:
            self._persist()

    def _sibling_docs(self) -> List[dict]:
        if self.dir is None:
            return []
        self.flush()
        try:
            names = [n for n in os.listdir(self.dir) if n.endswith(".json")]
        except OSError:
            return []
        docs: List[dict] = []
        now = time.time()
        stale_after = _metrics.sibling_stale_s()
        for name in names:
            path = self.dir / name
            try:
                mtime = os.stat(path).st_mtime
            except OSError:
                continue
            if now - mtime > stale_after:
                # a dead group member's leftovers; never our own file
                # (our live ring re-creates it on the next flush)
                if name != f"{self.tag}.json":
                    try:
                        os.unlink(path)
                        _metrics.STALE_SIBLINGS.inc(1, kind="lineage")
                    except OSError:
                        pass
                continue
            try:
                with open(path) as f:
                    payload = json.load(f)
                docs.extend(payload.get("records", ()))
            except (OSError, json.JSONDecodeError):
                continue   # sibling mid-write; next read heals
        return docs

    def merged(self) -> List[dict]:
        """Cross-process merged records, newest first: stages unioned by
        lineage id across every sibling's persisted ring + our live one,
        with the merged outcome computed (see :func:`merge_records`)."""
        with self._lock:
            own = [dict(d, stages=list(d["stages"])) for d in self._ring]
        return merge_records(self._sibling_docs() + own)

    def index(self, limit: int = 100) -> dict:
        """The /lineage.json body: merged per-generation summaries,
        newest first (cluster-annotated when a provider is armed)."""
        entries = []
        for d in self.merged()[:limit]:
            annotate_cluster(d)
            entry = {
                "lid": d.get("lid"),
                "generation": d.get("generation"),
                "start": d.get("start"),
                "outcome": d.get("outcome"),
                "origin": d.get("origin"),
                "workers": d.get("workers"),
                "stageCount": len(d.get("stages", ())),
                "durationMs": d.get("durationMs"),
            }
            cl = d.get("cluster")
            if cl:
                entry["cluster"] = {"expected": len(cl["expected"]),
                                    "done": len(cl["done"]),
                                    "missing": cl["missing"]}
            entries.append(entry)
        return {"worker": self.tag, "records": entries}

    def get(self, lid: str) -> Optional[dict]:
        for d in self.merged():
            if d.get("lid") == lid:
                return annotate_cluster(d)
        return None

    def get_generation(self, generation: int) -> Optional[dict]:
        """The merged record of one plane/server generation; when a
        generation id was reused across deployments, the record with the
        most stages (then newest) wins."""
        best = None
        for d in self.merged():
            if d.get("generation") != generation:
                continue
            if best is None or (len(d.get("stages", ())),
                                d.get("start", 0)) > (
                                    len(best.get("stages", ())),
                                    best.get("start", 0)):
                best = d
        return annotate_cluster(best)


def merge_records(docs: List[dict]) -> List[dict]:
    """Union per-process record fragments by lineage id.

    Stages dedupe on (stage, worker, start) — a stage persisted by both
    the origin's ring and a re-read of its own file appears once.  The
    merged outcome:

    - ``complete``  — a publish-side stage plus at least one worker's
      install AND first_serve are visible;
    - ``published`` — the origin closed it but no worker has served
      against it yet;
    - ``abandoned`` — still open, and a NEWER record reached publish:
      the origin died (or gave up) mid-flight — the supersession is the
      close, so dead publishers leak nothing;
    - ``open``      — still in flight (the newest record while a fold
      or publish is running).

    On a subscriber node ``repl.land`` is the publish-equivalent marker
    (the replicated flip IS the local publish), so supersession closes
    a reconnecting subscriber's pre-resync orphans — a record whose
    transfer was cut short (repl.recv with no land) goes ``abandoned``
    as soon as a newer generation lands, exactly like the SIGKILLed
    publisher case.
    """
    by_lid: Dict[str, dict] = {}
    for doc in docs:
        lid = doc.get("lid")
        if not lid:
            continue
        tgt = by_lid.get(lid)
        if tgt is None:
            tgt = by_lid[lid] = {
                "lid": lid, "start": doc.get("start", 0),
                "generation": None, "outcome": "open",
                "origin": None, "_seen": set(), "stages": []}
        if doc.get("start") and doc["start"] < tgt["start"]:
            tgt["start"] = doc["start"]
        if doc.get("origin") and not tgt["origin"]:
            tgt["origin"] = doc["origin"]
        if doc.get("generation") is not None:
            g = int(doc["generation"])
            if tgt["generation"] is None or g > tgt["generation"]:
                tgt["generation"] = g
        if doc.get("outcome") not in (None, "open"):
            tgt["outcome"] = doc["outcome"]
        for s in doc.get("stages", ()):
            key = (s.get("stage"), s.get("worker"),
                   round(float(s.get("start") or 0), 6))
            if key in tgt["_seen"]:
                continue
            tgt["_seen"].add(key)
            tgt["stages"].append(s)
    records = []
    for rec in by_lid.values():
        rec.pop("_seen")
        rec["stages"].sort(key=lambda s: (s.get("start", 0),
                                          _stage_rank(s.get("stage"))))
        names = {s.get("stage") for s in rec["stages"]}
        workers = sorted({s.get("worker") for s in rec["stages"]
                          if s.get("worker")})
        rec["workers"] = workers
        published = bool(names & _PUBLISH_STAGES) \
            or rec["outcome"] == "published"
        if published and "install" in names and "first_serve" in names:
            rec["outcome"] = "complete"
        elif published:
            rec["outcome"] = "published"
        rec["_published"] = published
        if rec["stages"]:
            end = max(s.get("start", 0) + s.get("duration_s", 0)
                      for s in rec["stages"])
            rec["durationMs"] = round(max(end - rec["start"], 0) * 1e3, 3)
        else:
            rec["durationMs"] = 0.0
        records.append(rec)
    # supersession closes orphans: an open record older than any record
    # that reached publish was abandoned by a dead/stuck origin
    latest_published = max(
        (r["start"] for r in records if r["_published"]), default=None)
    for rec in records:
        if not rec["_published"] and rec["outcome"] == "open" \
                and latest_published is not None \
                and rec["start"] < latest_published:
            rec["outcome"] = "abandoned"
        rec.pop("_published")
    records.sort(key=lambda r: r.get("start", 0), reverse=True)
    return records


def _stage_rank(name: Optional[str]) -> int:
    try:
        return STAGE_ORDER.index(name)
    except ValueError:
        return len(STAGE_ORDER)


# -- cluster stitching --------------------------------------------------------

def apply_cluster_outcome(doc: dict, expected,
                          live=None) -> dict:
    """Annotate one merged record with the cluster view: a per-node
    lane summary under ``doc["cluster"]`` and the stitched outcome —
    ``cluster_complete`` only when EVERY expected subscriber node's
    install + first_serve stages are visible; a record that completed
    on some nodes but still lags on another is demoted back to
    ``published`` (the cluster, not the node, is the unit of
    observation).  ``live`` (when given) distinguishes a lagging node
    that is still connected (lane ``open``) from one that died mid-
    transfer (lane ``abandoned``).  Mutates and returns ``doc``."""
    expected = sorted({str(n) for n in (expected or ()) if n})
    live_set = None if live is None else {str(n) for n in live}
    lanes: Dict[str, dict] = {
        n: {"names": set(), "stages": 0} for n in expected}
    serve_end = None
    for s in doc.get("stages", ()):
        if s.get("stage") == "first_serve":
            end = float(s.get("start") or 0) + float(
                s.get("duration_s") or 0)
            if serve_end is None or end > serve_end:
                serve_end = end
        lane = lanes.get(s.get("node"))
        if lane is not None:
            lane["names"].add(s.get("stage"))
            lane["stages"] += 1
    done, missing, nodes_doc = [], [], {}
    for n in expected:
        names = lanes[n]["names"]
        ok = "install" in names and "first_serve" in names
        (done if ok else missing).append(n)
        if ok:
            status = "complete"
        elif live_set is not None and n not in live_set:
            status = "abandoned"
        elif lanes[n]["stages"] == 0:
            status = "missing"
        else:
            status = "open"
        nodes_doc[n] = {"status": status, "stages": lanes[n]["stages"]}
    cluster = {"expected": expected, "done": done, "missing": missing,
               "nodes": nodes_doc}
    if expected:
        if not missing and doc.get("outcome") == "complete":
            doc["outcome"] = "cluster_complete"
            if serve_end is not None:
                cluster["propagationMs"] = round(max(
                    serve_end - float(doc.get("start") or 0), 0) * 1e3, 3)
        elif missing and doc.get("outcome") == "complete":
            doc["outcome"] = "published"
    doc["cluster"] = cluster
    return doc


# publisher-side hook: deploy --plane-publish registers a callable
# returning {"expected": [subscriber nodes ever seen], "live":
# [currently connected]} so every lineage read answers with the
# stitched cluster outcome; None = single-node semantics unchanged
_cluster_provider = None


def set_cluster_provider(fn) -> None:
    global _cluster_provider
    _cluster_provider = fn


def annotate_cluster(doc: Optional[dict]) -> Optional[dict]:
    """Apply the registered cluster view to one merged record; no-op
    when no provider is registered (single-node) or the view is
    empty."""
    if doc is None or _cluster_provider is None:
        return doc
    try:
        view = _cluster_provider()
    except Exception:
        return doc
    if view and view.get("expected"):
        apply_cluster_outcome(doc, view["expected"], view.get("live"))
    return doc


# -- process singleton --------------------------------------------------------

_lineage: Optional[LineageRecorder] = None
_lineage_lock = threading.Lock()


def get_lineage() -> LineageRecorder:
    global _lineage
    with _lineage_lock:
        if _lineage is None:
            _lineage = LineageRecorder()
        return _lineage


def set_lineage(recorder: Optional[LineageRecorder]) -> None:
    """Swap the process recorder (tests; None resets to lazy default)."""
    global _lineage
    with _lineage_lock:
        _lineage = recorder


def arm(storage=None, directory: Optional[os.PathLike] = None,
        tag: Optional[str] = None) -> LineageRecorder:
    """Point the process recorder at this deployment's lineage dir so
    records become visible to sibling workers and the dashboard;
    servers call this at startup (same contract as ``tracing.arm``)."""
    rec = get_lineage()
    rec.configure(
        directory if directory is not None else lineage_dir(storage), tag)
    return rec


def render_lineage_text(doc: dict, width: int = 44) -> str:
    """ASCII waterfall of one merged lineage record (``pio lineage``
    output): one row per stage, bars proportional to offset/duration
    within the generation's end-to-end span."""
    total_ms = max(float(doc.get("durationMs") or 0.0), 1e-6)
    t0 = float(doc.get("start") or 0.0)
    lines = [
        "generation %s lineage %s: %s in %.1f ms (origin %s, workers %s)"
        % (doc.get("generation", "?"), doc.get("lid", "?"),
           doc.get("outcome", "?"), total_ms, doc.get("origin", "?"),
           ",".join(doc.get("workers") or []) or "?")]
    for s in doc.get("stages", ()):
        off_ms = max((float(s.get("start", t0)) - t0) * 1e3, 0.0)
        dur_ms = float(s.get("duration_s", 0.0)) * 1e3
        i0 = min(int(off_ms / total_ms * width), width - 1)
        i1 = min(max(int((off_ms + dur_ms) / total_ms * width), i0 + 1),
                 width)
        bar = " " * i0 + "#" * (i1 - i0) + " " * (width - i1)
        name = ("  " if s.get("parent") else "") + str(s.get("stage", "?"))
        attrs = s.get("attrs") or {}
        attr_txt = (" " + " ".join(f"{k}={v}"
                                   for k, v in sorted(attrs.items()))
                    if attrs else "")
        lines.append("  %-20s %-14s %9.3f ms |%s|%s"
                     % (name[:20], str(s.get("worker", ""))[:14],
                        dur_ms, bar, attr_txt))
    if not doc.get("stages"):
        lines.append("  (no stages recorded)")
    return "\n".join(lines) + "\n"


def render_lineage_cluster_text(doc: dict, width: int = 44) -> str:
    """ASCII waterfall of one stitched record with a per-node lane
    (``pio lineage --cluster``): publisher lane first, then one lane
    per expected subscriber node, all bars on the shared time axis so
    a lagging node reads as a right-shifted lane."""
    cluster = doc.get("cluster") or {}
    nodes_doc = cluster.get("nodes") or {}
    total_ms = max(float(doc.get("durationMs") or 0.0), 1e-6)
    t0 = float(doc.get("start") or 0.0)
    head = ("generation %s lineage %s: %s in %.1f ms "
            "(cluster %d/%d nodes%s)"
            % (doc.get("generation", "?"), doc.get("lid", "?"),
               doc.get("outcome", "?"), total_ms,
               len(cluster.get("done") or ()),
               len(cluster.get("expected") or ()),
               ", propagation %.1f ms" % cluster["propagationMs"]
               if cluster.get("propagationMs") is not None else ""))
    lanes: Dict[Optional[str], List[dict]] = {None: []}
    for n in nodes_doc:
        lanes[n] = []
    for s in doc.get("stages", ()):
        lanes.setdefault(s.get("node") if s.get("node") in nodes_doc
                         else None, []).append(s)
    lines = [head]

    def emit(title: str, stages: List[dict]) -> None:
        lines.append(title)
        for s in stages:
            off_ms = max((float(s.get("start", t0)) - t0) * 1e3, 0.0)
            dur_ms = float(s.get("duration_s", 0.0)) * 1e3
            i0 = min(int(off_ms / total_ms * width), width - 1)
            i1 = min(max(int((off_ms + dur_ms) / total_ms * width),
                         i0 + 1), width)
            bar = " " * i0 + "#" * (i1 - i0) + " " * (width - i1)
            name = (("  " if s.get("parent") else "")
                    + str(s.get("stage", "?")))
            lines.append("  %-20s %-14s %9.3f ms |%s|"
                         % (name[:20], str(s.get("worker", ""))[:14],
                            dur_ms, bar))
        if not stages:
            lines.append("  (no stages recorded)")

    emit("-- publisher (origin %s)" % doc.get("origin", "?"), lanes[None])
    for n in sorted(nodes_doc):
        nd = nodes_doc[n]
        emit("-- node %s [%s, %d stages]"
             % (n, nd.get("status", "?"), nd.get("stages", 0)), lanes[n])
    return "\n".join(lines) + "\n"


# -- shared HTTP endpoints ----------------------------------------------------

def handle_lineage_request(handler, path: str) -> bool:
    """Serve /lineage.json and /lineage/<gen|lid>.json on any
    JsonHandler server; returns True when the path was one of ours.
    Unauthenticated like /metrics — lineage carries timing structure,
    not event payloads."""
    if path == "/lineage.json":
        rec = get_lineage()
        if not rec.enabled:
            handler.send_error_json(503, "lineage disabled (PIO_LINEAGE=off)")
            return True
        handler.send_json(rec.index())
        return True
    if path.startswith("/lineage/") and path.endswith(".json"):
        rec = get_lineage()
        if not rec.enabled:
            handler.send_error_json(503, "lineage disabled (PIO_LINEAGE=off)")
            return True
        token = path[len("/lineage/"):-len(".json")]
        if token.startswith("ln-"):
            doc = rec.get(token)
        else:
            try:
                doc = rec.get_generation(int(token))
            except ValueError:
                handler.send_error_json(
                    400, f"lineage key {token!r} is neither a generation "
                    "number nor an ln- id")
                return True
        if doc is None:
            handler.send_error_json(
                404, f"no lineage record for {token!r}")
        else:
            handler.send_json(doc)
        return True
    return False
