"""Process-local metrics registry with cross-process aggregation.

Counter / Gauge / Histogram over a thread-safe registry, designed for the
prefork SO_REUSEPORT model (api/prefork.py): each worker process owns a
plain in-memory registry (near-zero hot-path cost — one lock hop and a
dict update per record), and a :class:`SnapshotFlusher` persists its
snapshot to ``<PIO_METRICS_DIR>/<tag>.json`` (tag = the worker's
``PIO_METRICS_TAG``/``PIO_WRITER_TAG``).  A scrape of ANY worker merges
every sibling's snapshot file with its own live registry
(:func:`aggregate_snapshot`), so one ``GET /metrics`` sees the whole
server group.  Counters and gauges sum across workers; histograms sum
bucket-wise.

Naming contract (enforced at registration, linted by
``scripts/check_metrics_names.py``): every metric name matches
``pio_[a-z0-9_]+`` and carries a non-empty help string.

``PIO_METRICS=off`` disables recording globally (the bench's
instrumentation-overhead guard compares against exactly this mode);
exposition then serves whatever was recorded before the switch.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time as _time
from bisect import bisect_left
from typing import Dict, List, Optional, Sequence, Tuple

NAME_RE = re.compile(r"^pio_[a-z0-9_]+$")

# log-scaled latency buckets (seconds): 500 µs … 60 s, the envelope of a
# single-event append on one end and a cold-compile train span on the other
LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)
# power-of-two size buckets for batch/occupancy histograms
SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096)


def _label_key(labels: Dict[str, str]) -> str:
    """Canonical series key: the Prometheus label body, sorted by name.
    Doubles as the on-disk snapshot key so merge needs no re-parsing."""
    if not labels:
        return ""
    return ",".join(
        '%s="%s"' % (k, str(v).replace("\\", "\\\\").replace('"', '\\"')
                     .replace("\n", "\\n"))
        for k, v in sorted(labels.items()))


class _Metric:
    """Common series bookkeeping; subclasses define the value shape."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str):
        self._reg = registry
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._series: Dict[str, object] = {}

    def _snapshot_series(self):
        with self._lock:
            return dict(self._series)

    def clear_series(self) -> None:
        """Drop every series (identity gauges on server restart within
        one process; test isolation)."""
        with self._lock:
            self._series.clear()


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if not self._reg.enabled:
            return
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        return float(self._series.get(_label_key(labels), 0.0))


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        if not self._reg.enabled:
            return
        key = _label_key(labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if not self._reg.enabled:
            return
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def remove(self, **labels: str) -> None:
        """Drop one labeled series entirely (vs. set(0): the series
        disappears from /metrics).  For per-peer gauges whose peer went
        away — a dead replication subscriber's lag series must not
        linger at its last value and trip lag alerts forever."""
        key = _label_key(labels)
        with self._lock:
            self._series.pop(key, None)

    def value(self, **labels: str) -> float:
        return float(self._series.get(_label_key(labels), 0.0))


def _exemplar_window_s() -> float:
    try:
        return max(float(os.environ.get("PIO_EXEMPLAR_WINDOW_S", "60")), 0.1)
    except ValueError:
        return 60.0


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, registry, name, help,
                 buckets: Sequence[float] = LATENCY_BUCKETS):
        super().__init__(registry, name, help)
        self.buckets: Tuple[float, ...] = tuple(sorted(buckets))

    def observe(self, value: float, exemplar: Optional[str] = None,
                **labels: str) -> None:
        """Record an observation.  ``exemplar`` (keyword-only by
        convention; it is NOT a label) attaches a trace id: the series
        keeps the max-value observation's id per rolling
        PIO_EXEMPLAR_WINDOW_S window, linking the histogram's tail back
        to a retrievable flight-recorder trace."""
        if not self._reg.enabled:
            return
        key = _label_key(labels)
        i = bisect_left(self.buckets, value)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                # one cumulative-count slot per bucket + the +Inf slot
                s = self._series[key] = {
                    "counts": [0] * (len(self.buckets) + 1),
                    "sum": 0.0, "count": 0}
            s["counts"][i] += 1
            s["sum"] += value
            s["count"] += 1
            if exemplar:
                ex = s.get("ex")
                now = _time.time()
                if (ex is None or value >= ex[0]
                        or now - ex[2] > _exemplar_window_s()):
                    s["ex"] = [value, exemplar, now]

    def _snapshot_series(self):
        with self._lock:
            out = {}
            for k, v in self._series.items():
                d = {"counts": list(v["counts"]), "sum": v["sum"],
                     "count": v["count"]}
                if "ex" in v:
                    d["ex"] = list(v["ex"])
                out[k] = d
            return out


class MetricsRegistry:
    """Thread-safe named-metric registry.  Registration is idempotent:
    asking for an existing name returns the existing metric (and raises
    on a kind mismatch), so modules can declare their instruments at
    import time without coordinating order."""

    def __init__(self, enabled: Optional[bool] = None):
        if enabled is None:
            enabled = os.environ.get("PIO_METRICS", "").lower() not in (
                "off", "0", "false")
        self.enabled = enabled
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _register(self, cls, name: str, help: str, **kw) -> _Metric:
        if not NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} must match {NAME_RE.pattern}")
        if not help or not help.strip():
            raise ValueError(f"metric {name!r} needs a non-empty help string")
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}")
                return m
            m = self._metrics[name] = cls(self, name, help, **kw)
            return m

    def counter(self, name: str, help: str) -> Counter:
        return self._register(Counter, name, help)

    def gauge(self, name: str, help: str) -> Gauge:
        return self._register(Gauge, name, help)

    def histogram(self, name: str, help: str,
                  buckets: Sequence[float] = LATENCY_BUCKETS) -> Histogram:
        return self._register(Histogram, name, help, buckets=buckets)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def snapshot(self) -> dict:
        """JSON-able full-state dump, the unit of cross-process exchange."""
        out = {}
        for m in self.metrics():
            entry = {"type": m.kind, "help": m.help,
                     "series": m._snapshot_series()}
            if isinstance(m, Histogram):
                entry["buckets"] = list(m.buckets)
            out[m.name] = entry
        return out


def _merge_exemplar(a, b):
    """Pick the cross-worker exemplar: prefer a fresh one over a stale
    one (a dead worker's max must not pin the link forever), then the
    larger observed value."""
    if a is None:
        return b
    if b is None:
        return a
    now = _time.time()
    window = _exemplar_window_s()
    a_fresh = now - a[2] <= window
    b_fresh = now - b[2] <= window
    if a_fresh != b_fresh:
        return a if a_fresh else b
    return a if a[0] >= b[0] else b


def merge_snapshots(snapshots: Sequence[dict]) -> dict:
    """Sum snapshots across workers: counters/gauges add per series,
    histograms add bucket-wise (boundaries must agree — they come from
    the same code in every worker) and keep one exemplar per series."""
    merged: dict = {}
    for snap in snapshots:
        for name, entry in snap.items():
            tgt = merged.get(name)
            if tgt is None:
                tgt = merged[name] = {
                    "type": entry["type"], "help": entry["help"],
                    "series": {}}
                if "buckets" in entry:
                    tgt["buckets"] = list(entry["buckets"])
            for key, val in entry["series"].items():
                cur = tgt["series"].get(key)
                if entry["type"] == "histogram":
                    if cur is None:
                        cur = tgt["series"][key] = {
                            "counts": list(val["counts"]),
                            "sum": val["sum"], "count": val["count"]}
                    else:
                        cur["counts"] = [a + b for a, b in
                                         zip(cur["counts"], val["counts"])]
                        cur["sum"] += val["sum"]
                        cur["count"] += val["count"]
                    ex = _merge_exemplar(cur.get("ex"), val.get("ex"))
                    if ex is not None:
                        cur["ex"] = list(ex)
                else:
                    tgt["series"][key] = (cur or 0.0) + val
    return merged


# -- process-default registry -------------------------------------------------

_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


def set_enabled(enabled: bool) -> None:
    """Runtime switch for the default registry (the bench's
    instrumentation-overhead guard toggles this)."""
    _REGISTRY.enabled = enabled


def worker_tag() -> str:
    """This process's metrics identity: the active snapshot flusher's tag
    (authoritative — the prefork parent assigns itself ``w0-<pid>``
    explicitly and restores its environment afterwards), else
    PIO_METRICS_TAG (deploy workers) or PIO_WRITER_TAG (event-server
    workers), else pid-based."""
    with _flusher_lock:
        if _flusher is not None:
            return _flusher.tag
    return (os.environ.get("PIO_METRICS_TAG")
            or os.environ.get("PIO_WRITER_TAG")
            or f"pid-{os.getpid()}")


# the prefork health view: one series per live worker, merged at scrape
WORKER_UP = _REGISTRY.gauge(
    "pio_worker_up", "1 per worker process contributing to this scrape")

# dead-worker hygiene for every sibling-file merge (/metrics snapshots,
# /traces.json rings, /lineage.json rings): files whose mtime exceeds
# PIO_OBS_SIBLING_STALE_S are a dead group member's leftovers — evicted
# (unlinked) from the merge and counted here by kind
STALE_SIBLINGS = _REGISTRY.counter(
    "pio_obs_stale_siblings_total",
    "Dead-worker sibling files evicted from cross-worker merges after "
    "PIO_OBS_SIBLING_STALE_S (default 600 s), by kind "
    "(metrics | traces | lineage)")


def sibling_stale_s() -> float:
    """PIO_OBS_SIBLING_STALE_S: sibling files older than this are
    evicted from /metrics, /traces.json, and /lineage.json merges
    (default 600 s — long enough to ride out a stop-the-world pause,
    short enough that a SIGKILLed worker's gauges don't haunt the group
    for a day)."""
    try:
        return max(float(os.environ.get("PIO_OBS_SIBLING_STALE_S", "600")),
                   1.0)
    except ValueError:
        return 600.0

# per-worker resident memory, refreshed on every snapshot flush and
# scrape: with the shared model plane, N workers mapping one arena show
# near-baseline anonymous RSS each (file-backed model pages are shared
# page cache) — the bench's plane_memory_guard reads exactly this view
PROCESS_RSS = _REGISTRY.gauge(
    "pio_process_rss_bytes",
    "Resident-set bytes of this process, one {worker} series per live "
    "worker (Linux /proc/self/statm; absent elsewhere).  NOTE: "
    "file-backed pages (mmapped model-plane arenas) count in EVERY "
    "mapping worker's RSS — sum PSS, not this, for node totals")

_PAGE_BYTES = (os.sysconf("SC_PAGE_SIZE")
               if hasattr(os, "sysconf") else 4096)


def update_process_rss(tag: Optional[str] = None) -> None:
    """Refresh this process's pio_process_rss_bytes series (no-op where
    /proc is unavailable).  ``tag`` overrides the worker label — the
    snapshot flusher passes its own (calling worker_tag() from inside
    the flusher-lock hold would deadlock)."""
    try:
        with open("/proc/self/statm") as f:
            rss = int(f.read().split()[1]) * _PAGE_BYTES
    except (OSError, ValueError, IndexError):
        return
    PROCESS_RSS.set(rss, worker=tag or worker_tag())


def mark_worker_up(tag: Optional[str] = None) -> None:
    """Declare THIS process's worker identity.  Clears previous local
    pio_worker_up series first: a process only ever IS one worker, and a
    programmatic server restarted in-process (tests) must not keep
    advertising its old tag.  Also SEEDS pio_process_rss_bytes for this
    worker: a freshly-forked worker that has served zero requests must
    still report an RSS row on the group's first scrape (the snapshot
    flusher's first flush would otherwise race the first scrape and the
    worker would be invisible to the memory dashboards)."""
    tag = tag or worker_tag()
    WORKER_UP.clear_series()
    WORKER_UP.set(1, worker=tag)
    update_process_rss(tag)


class SnapshotFlusher:
    """Background persister of the registry snapshot for cross-worker
    scrapes.  Writes ``<dir>/<tag>.json`` atomically (tmp+rename) every
    ``interval`` seconds and on demand (:meth:`flush`)."""

    def __init__(self, directory: str, tag: str,
                 registry: Optional[MetricsRegistry] = None,
                 interval: Optional[float] = None):
        self.dir = directory
        self.tag = tag
        self.registry = registry or _REGISTRY
        if interval is None:
            try:
                interval = float(os.environ.get("PIO_METRICS_FLUSH_S", "1.0"))
            except ValueError:
                interval = 1.0
        self.interval = max(interval, 0.05)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def path(self) -> str:
        return os.path.join(self.dir, f"{self.tag}.json")

    def flush(self) -> None:
        update_process_rss(self.tag)
        tmp = self.path + f".tmp{os.getpid()}"
        try:
            os.makedirs(self.dir, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(self.registry.snapshot(), f)
            os.replace(tmp, self.path)
        except OSError:
            # the dir may be torn down mid-shutdown; a missed flush only
            # staleness-lags siblings' view, never corrupts it
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def start(self) -> None:
        if self._thread is not None:
            return
        self.flush()

        def loop():
            while not self._stop.wait(self.interval):
                self.flush()

        self._thread = threading.Thread(
            target=loop, daemon=True, name="pio-metrics-flush")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self.flush()


_flusher: Optional[SnapshotFlusher] = None
_flusher_lock = threading.Lock()


def start_worker_flusher(directory: Optional[str] = None,
                         tag: Optional[str] = None) -> Optional[SnapshotFlusher]:
    """Arm cross-worker aggregation for this process.  No-op without a
    metrics dir (single-worker servers stay purely in-memory).  A second
    call replaces the previous flusher (programmatic servers in one
    process, e.g. tests) — the registry itself is process-global either
    way."""
    global _flusher
    directory = directory or os.environ.get("PIO_METRICS_DIR")
    if not directory:
        return None
    if tag is None:
        # resolve from env here, NOT via worker_tag() — that helper reads
        # the flusher under _flusher_lock, which this block holds
        tag = (os.environ.get("PIO_METRICS_TAG")
               or os.environ.get("PIO_WRITER_TAG")
               or f"pid-{os.getpid()}")
    with _flusher_lock:
        if _flusher is not None:
            _flusher.stop()
        _flusher = SnapshotFlusher(directory, tag)
        mark_worker_up(tag)
        _flusher.start()
        return _flusher


def stop_worker_flusher() -> None:
    global _flusher
    with _flusher_lock:
        if _flusher is not None:
            _flusher.stop()
            _flusher = None


def aggregate_snapshot(registry: Optional[MetricsRegistry] = None) -> dict:
    """The scrape view: this process's LIVE registry merged with every
    sibling worker's persisted snapshot.  Flushes our own file first so
    alternating scrapes across workers converge within one flush
    interval instead of two."""
    registry = registry or _REGISTRY
    if registry is _REGISTRY:
        update_process_rss()
    snaps = [registry.snapshot()]
    with _flusher_lock:
        fl = _flusher
    if fl is not None:
        fl.flush()
        # a sibling whose file stopped updating is dead (SIGKILLed/OOMed):
        # its counters still count — the events it acked are on disk — but
        # its GAUGES describe the current state of a process that no
        # longer exists (in-flight requests, worker_up) and must read 0,
        # or an idle server reports the dead worker's last values forever
        stale_after = max(10.0 * fl.interval, 15.0)
        evict_after = sibling_stale_s()
        try:
            names = sorted(os.listdir(fl.dir))
        except OSError:
            names = []
        now = _time.time()
        for name in names:
            if not name.endswith(".json") or name == f"{fl.tag}.json":
                continue
            path = os.path.join(fl.dir, name)
            try:
                mtime = os.stat(path).st_mtime
            except OSError:
                continue
            if now - mtime > evict_after:
                # LONG-dead sibling: merging its snapshot forever would
                # keep a killed worker's counters in every scrape until
                # the dir is torn down — evict the file (its acked work
                # already aged out of every rate window)
                try:
                    os.unlink(path)
                    STALE_SIBLINGS.inc(1, kind="metrics")
                except OSError:
                    pass
                continue
            try:
                with open(path) as f:
                    snap = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue  # sibling mid-write/teardown; next scrape heals
            if now - mtime > stale_after:
                for entry in snap.values():
                    if entry.get("type") == "gauge":
                        entry["series"] = {k: 0.0 for k in entry["series"]}
            snaps.append(snap)
    return merge_snapshots(snaps)
