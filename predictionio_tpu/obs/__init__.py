"""Observability subsystem: metrics registry, Prometheus/stats.json
exposition, and structured span journals.

The reference EventServer shipped a ``--stats`` flag with a
``stats.json`` endpoint and leaned on the Spark UI for everything else
(SURVEY.md §5); this package is the TPU-native replacement the prefork
multi-worker servers need — a process-local registry
(:mod:`predictionio_tpu.obs.metrics`) whose snapshots cross the
SO_REUSEPORT process boundary via per-worker files, text exposition at
``GET /metrics`` + reference-parity ``GET /stats.json``
(:mod:`predictionio_tpu.obs.exposition`), and a per-run span journal for
training/evaluation (:mod:`predictionio_tpu.obs.spans`).

Everything here is stdlib-only and import-safe from the storage layer
(no jax, no predictionio_tpu.api imports).
"""

from predictionio_tpu.obs.metrics import (  # noqa: F401
    MetricsRegistry,
    get_registry,
    set_enabled,
)
