"""Metric exposition: Prometheus text format, a parser for it (the CLI
pretty-printer and bench scrapes reuse one implementation), and the
reference-parity ``stats.json`` window collector.

The reference's EventServerStats (``--stats`` flag) kept per-(appId,
statusCode, event) counters in two views — since server start and a
rolling current window — served at ``GET /stats.json``.
:class:`StatsCollector` reproduces that: ``record()`` lands in both the
since-start and the current-window map; when the window (default 60 s,
``PIO_STATS_WINDOW_S``) elapses, the current map is published as the
last completed window and a fresh one starts.
"""

from __future__ import annotations

import datetime as _dt
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from predictionio_tpu.obs import metrics as _metrics


# -- Prometheus text format ---------------------------------------------------

def _fmt_value(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _series_line(name: str, labels: str, value: float,
                 extra_label: str = "") -> str:
    body = ",".join(x for x in (labels, extra_label) if x)
    if body:
        return f"{name}{{{body}}} {_fmt_value(value)}"
    return f"{name} {_fmt_value(value)}"


def render_prometheus(snapshot: dict) -> str:
    """Prometheus text exposition (v0.0.4) of a registry snapshot (or a
    cross-worker merge of snapshots)."""
    lines: List[str] = []
    for name in sorted(snapshot):
        entry = snapshot[name]
        lines.append(f"# HELP {name} {entry['help']}")
        lines.append(f"# TYPE {name} {entry['type']}")
        series = entry["series"]
        if entry["type"] == "histogram":
            buckets = entry["buckets"]
            for key in sorted(series):
                s = series[key]
                cum = 0
                for le, n in zip(buckets, s["counts"]):
                    cum += n
                    lines.append(_series_line(
                        name + "_bucket", key, cum, f'le="{_fmt_value(le)}"'))
                inf_line = _series_line(
                    name + "_bucket", key, s["count"], 'le="+Inf"')
                ex = s.get("ex")
                if ex:
                    # OpenMetrics-style exemplar on the +Inf bucket: the
                    # trace id of the max-latency observation in the
                    # current exemplar window — the metrics→traces link.
                    # The middleware only honors [A-Za-z0-9._:-] request
                    # ids, but escape label-style anyway: a programmatic
                    # observe(exemplar=...) caller is not so constrained
                    rid = (str(ex[1]).replace("\\", "\\\\")
                           .replace('"', '\\"').replace("\n", "\\n"))
                    inf_line += (' # {trace_id="%s"} %s %s'
                                 % (rid, _fmt_value(ex[0]),
                                    _fmt_value(ex[2])))
                lines.append(inf_line)
                lines.append(_series_line(name + "_sum", key, s["sum"]))
                lines.append(_series_line(name + "_count", key, s["count"]))
        else:
            for key in sorted(series):
                lines.append(_series_line(name, key, series[key]))
    return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str):
    """Parse Prometheus text into ``(families, types)``:

    - families: {line_name: [(labels_dict, value), ...]} where line_name
      keeps the ``_bucket``/``_sum``/``_count`` suffixes literal;
    - types: {metric_name: "counter"|"gauge"|"histogram"}.
    """
    families: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    types: Dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        if " # {" in line:
            # strip the OpenMetrics exemplar suffix (see parse_exemplars
            # for reading it); a label VALUE containing ' # {' would be
            # truncated here — our own label escaping never produces one
            line = line.split(" # {", 1)[0]
        try:
            if "{" in line:
                name, rest = line.split("{", 1)
                body, value_s = rest.rsplit("}", 1)
                labels: Dict[str, str] = {}
                for part in _split_label_body(body):
                    k, _, v = part.partition("=")
                    if len(v) >= 2 and v[0] == '"' and v[-1] == '"':
                        v = v[1:-1]
                    labels[k] = _unescape_label_value(v)
            else:
                name, value_s = line.rsplit(None, 1)
                labels = {}
            families.setdefault(name.strip(), []).append(
                (labels, float(value_s)))
        except ValueError:
            continue  # tolerate exposition lines we didn't write
    return families, types


def _unescape_label_value(s: str) -> str:
    """Inverse of metrics._label_key's escaping.  A single left-to-right
    scan, NOT chained str.replace: sequential replaces process '\\\\n'
    (escaped backslash + literal n) in the wrong order and corrupt it
    into a newline."""
    if "\\" not in s:
        return s
    out: List[str] = []
    i, n = 0, len(s)
    while i < n:
        c = s[i]
        if c == "\\" and i + 1 < n:
            nxt = s[i + 1]
            if nxt == "\\" or nxt == '"':
                out.append(nxt)
                i += 2
                continue
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


def _split_label_body(body: str) -> List[str]:
    """Split 'a="x",b="y,z"' on commas outside quotes."""
    parts, cur, in_q, esc = [], [], False, False
    for ch in body:
        if esc:
            cur.append(ch)
            esc = False
            continue
        if ch == "\\":
            cur.append(ch)
            esc = True
            continue
        if ch == '"':
            in_q = not in_q
            cur.append(ch)
            continue
        if ch == "," and not in_q:
            parts.append("".join(cur))
            cur = []
            continue
        cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return [p for p in (s.strip() for s in parts) if p]


def parse_exemplars(text: str) -> Dict[str, List[Tuple[Dict[str, str],
                                                       str, float]]]:
    """Extract the exemplars render_prometheus attaches to ``+Inf``
    bucket lines: ``{line_name: [(labels, trace_id, value), ...]}``."""
    out: Dict[str, List[Tuple[Dict[str, str], str, float]]] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if line.startswith("#") or " # {" not in line:
            continue
        main, _, ex = line.partition(" # {")
        body, _, tail = ex.partition("}")
        k, _, v = body.partition("=")
        if k.strip() != "trace_id":
            continue
        trace_id = v.strip().strip('"')
        try:
            ex_value = float(tail.split()[0])
        except (ValueError, IndexError):
            continue
        try:
            name = main.split("{", 1)[0]
            fams, _t = parse_prometheus_text(main)
            labels = fams[name][0][0]
        except (KeyError, IndexError):
            continue
        out.setdefault(name, []).append((labels, trace_id, ex_value))
    return out


def family_total(families: dict, name: str,
                 **match: str) -> float:
    """Sum every series of ``name`` whose labels include ``match``."""
    total = 0.0
    for labels, value in families.get(name, ()):
        if all(labels.get(k) == v for k, v in match.items()):
            total += value
    return total


def _quantile_from_buckets(buckets: List[Tuple[float, float]],
                           total: float, q: float) -> float:
    """Estimate a quantile from cumulative (le, count) pairs by
    midpoint-rank interpolation inside the winning bucket: the r-th of m
    observations in a bucket sits at fraction (r − ½)/m of its width.
    The old target/cum ratio degenerated to the bucket's UPPER bound for
    high quantiles of a sparsely-hit bucket (a single observation
    reported p99 ≈ le, overstating the measured latency by up to a whole
    log-scaled bucket)."""
    import math

    target = q * total
    prev_le, prev_cum = 0.0, 0.0
    for le, cum in buckets:
        if cum >= target:
            if le == float("inf"):
                return prev_le
            m = cum - prev_cum
            if m <= 0:
                return prev_le
            # the quantile falls on the r-th observation in this bucket
            r = max(math.ceil(target - prev_cum), 1)
            frac = min(max((r - 0.5) / m, 0.0), 1.0)
            return prev_le + (le - prev_le) * frac
        prev_le, prev_cum = le, cum
    return prev_le


def summarize_prometheus(text: str) -> str:
    """Human-readable digest of a /metrics payload for `pio metrics`:
    counters/gauges per series; histograms as count/sum/avg and
    bucket-interpolated p50/p95/p99."""
    families, types = parse_prometheus_text(text)
    out: List[str] = []
    hist_names = sorted(n for n, t in types.items() if t == "histogram")
    plain = sorted(n for n, t in types.items() if t in ("counter", "gauge"))
    for name in plain:
        out.append(f"{name} ({types[name]})")
        for labels, value in sorted(
                families.get(name, ()), key=lambda lv: sorted(lv[0].items())):
            lbl = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
            out.append(f"  {lbl or '(no labels)':60s} {_fmt_value(value)}")
    for name in hist_names:
        out.append(f"{name} (histogram)")
        # group bucket series by their non-le labels
        groups: Dict[str, List[Tuple[float, float]]] = {}
        for labels, value in families.get(name + "_bucket", ()):
            le = labels.get("le", "")
            rest = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items())
                            if k != "le")
            groups.setdefault(rest, []).append(
                (float("inf") if le == "+Inf" else float(le), value))
        for rest in sorted(groups):
            buckets = sorted(groups[rest])
            count = next((v for lb, v in families.get(name + "_count", ())
                          if ",".join(f'{k}="{x}"' for k, x in
                                      sorted(lb.items())) == rest), 0.0)
            total = next((v for lb, v in families.get(name + "_sum", ())
                          if ",".join(f'{k}="{x}"' for k, x in
                                      sorted(lb.items())) == rest), 0.0)
            if count <= 0:
                continue
            p50 = _quantile_from_buckets(buckets, count, 0.50)
            # clamp p50 ≤ p95 ≤ p99: per-bucket interpolation of a sparse
            # histogram can otherwise invert adjacent quantiles
            p95 = max(_quantile_from_buckets(buckets, count, 0.95), p50)
            p99 = max(_quantile_from_buckets(buckets, count, 0.99), p95)
            out.append(
                f"  {rest or '(no labels)':40s} count={_fmt_value(count)} "
                f"sum={total:.4g} avg={total / count:.4g} "
                f"p50≈{p50:.4g} p95≈{p95:.4g} p99≈{p99:.4g}")
    return "\n".join(out) + "\n"


def metrics_payload() -> bytes:
    """The ``GET /metrics`` body: cross-worker aggregate in Prometheus
    text format."""
    return render_prometheus(_metrics.aggregate_snapshot()).encode()


# -- stats.json ---------------------------------------------------------------

def _stats_window_s() -> float:
    try:
        return max(float(os.environ.get("PIO_STATS_WINDOW_S", "60")), 0.1)
    except ValueError:
        return 60.0


class StatsCollector:
    """Reference-parity EventServerStats: per-(appId, status,
    entityType/event) counters in a since-start view and a rolling
    current window (plus the last COMPLETED window, the stable
    per-interval rate view)."""

    def __init__(self, window_s: Optional[float] = None):
        self.window_s = window_s if window_s is not None else _stats_window_s()
        self.start_time = _dt.datetime.now(_dt.timezone.utc)
        self._lock = threading.Lock()
        self._since_start: Dict[tuple, int] = {}
        self._current: Dict[tuple, int] = {}
        self._last_window: Dict[tuple, int] = {}
        # lazily anchored to the first observed clock value, so an
        # injected test clock and the real monotonic clock both work
        self._window_start: Optional[float] = None
        self._window_start_dt = self.start_time

    def record(self, app_id: Optional[int], status: int,
               event: Optional[str] = None,
               entity_type: Optional[str] = None,
               now: Optional[float] = None) -> None:
        key = (app_id, int(status), event, entity_type)
        now = time.monotonic() if now is None else now
        with self._lock:
            self._roll_locked(now)
            self._since_start[key] = self._since_start.get(key, 0) + 1
            self._current[key] = self._current.get(key, 0) + 1

    def _roll_locked(self, now: float) -> None:
        if self._window_start is None:
            self._window_start = now
            return
        elapsed = now - self._window_start
        if elapsed >= self.window_s:
            # 'last window' means the window ADJACENT to now: after an
            # idle gap spanning multiple windows the just-completed one
            # was empty — publishing the pre-gap counts would report an
            # arbitrarily old burst as the current rate
            self._last_window = (
                self._current if elapsed < 2 * self.window_s else {})
            self._current = {}
            self._window_start = now
            self._window_start_dt = _dt.datetime.now(_dt.timezone.utc)

    @staticmethod
    def _entries(counts: Dict[tuple, int],
                 app_id: Optional[int]) -> List[dict]:
        out = []
        for (aid, status, event, etype), n in sorted(
                counts.items(), key=lambda kv: repr(kv[0])):
            if app_id is not None and aid != app_id:
                continue
            e: dict = {"status": status, "count": n}
            if aid is not None:
                e["appId"] = aid
            if event is not None:
                e["event"] = event
            if etype is not None:
                e["entityType"] = etype
            out.append(e)
        return out

    def to_json(self, app_id: Optional[int] = None,
                now: Optional[float] = None) -> dict:
        """``app_id`` filters the views to one app (the event server's
        authenticated response); None exposes everything."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._roll_locked(now)
            return {
                "startTime": self.start_time.isoformat(),
                "window": {
                    "start": self._window_start_dt.isoformat(),
                    "seconds": self.window_s,
                },
                "statsSinceStart": self._entries(self._since_start, app_id),
                "statsCurrent": self._entries(self._current, app_id),
                "statsLastWindow": self._entries(self._last_window, app_id),
            }
