"""Structured span journal for training/evaluation runs.

``utils.tracing.timed`` logged wall-clock spans and accumulated them in a
dict; this extends that into a persisted artifact: one JSONL file per
workflow run (train or eval), each line a span with parent/child links,
written next to the engine instances so ``pio dashboard`` can render the
breakdown of every completed run.

Parent/child structure comes from a per-thread stack: a span opened
while another is active on the same thread becomes its child.  The
ACTIVE journal travels via a contextvar, so any ``timed()`` call inside
``engine.train`` — engine code never imports this module — lands in the
run's journal automatically.

Journal location (:func:`spans_dir`): ``PIO_SPANS_DIR`` if set, else
``<storage localfs/sharedfs METADATA path>/spans/`` (next to the engine
instances), else ``~/.cache/predictionio_tpu/spans``.  File name is the
engine/evaluation instance id: ``<instance_id>.jsonl``.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
from pathlib import Path
from typing import Iterator, List, Optional

_CURRENT: contextvars.ContextVar[Optional["SpanJournal"]] = (
    contextvars.ContextVar("pio_span_journal", default=None))


def current_journal() -> Optional["SpanJournal"]:
    return _CURRENT.get()


class SpanJournal:
    """Collects spans for one run and writes them as JSONL on close."""

    def __init__(self, path):
        self.path = Path(path)
        self._lock = threading.Lock()
        self._spans: List[dict] = []
        self._next_id = 1
        self._tls = threading.local()

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[dict]:
        with self._lock:
            span_id = self._next_id
            self._next_id += 1
        stack = self._stack()
        parent = stack[-1] if stack else None
        rec = {"id": span_id, "parent": parent, "name": name,
               "start": time.time()}
        if attrs:
            rec["attrs"] = {k: v for k, v in attrs.items()}
        stack.append(span_id)
        t0 = time.perf_counter()
        try:
            yield rec
        except BaseException:
            rec["error"] = True
            raise
        finally:
            rec["duration_s"] = time.perf_counter() - t0
            rec["end"] = rec["start"] + rec["duration_s"]
            stack.pop()
            with self._lock:
                self._spans.append(rec)

    def write(self) -> None:
        """Persist atomically (tmp+rename): a crashed run leaves either
        the previous journal or the full new one, never a torn file."""
        with self._lock:
            spans = sorted(self._spans, key=lambda s: s["id"])
        self.path.parent.mkdir(parents=True, exist_ok=True)
        tmp = self.path.with_suffix(self.path.suffix + f".tmp{os.getpid()}")
        with open(tmp, "w") as f:
            for rec in spans:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
        tmp.replace(self.path)

    @contextlib.contextmanager
    def activate(self) -> Iterator["SpanJournal"]:
        """Make this the process-current journal (timed() feeds it) for
        the duration; the journal is written on exit, success or not."""
        token = _CURRENT.set(self)
        try:
            yield self
        finally:
            _CURRENT.reset(token)
            try:
                self.write()
            except OSError:
                import logging

                logging.getLogger("pio.trace").exception(
                    "span journal write failed: %s", self.path)


def read_journal(path) -> List[dict]:
    """Load a journal; missing file → []."""
    p = Path(path)
    if not p.exists():
        return []
    out = []
    for line in p.read_text().splitlines():
        line = line.strip()
        if line:
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


def spans_dir(storage=None) -> Path:
    """Where this deployment's span journals live (see module docstring
    for the precedence)."""
    env = os.environ.get("PIO_SPANS_DIR")
    if env:
        return Path(env)
    if storage is not None:
        try:
            src = storage.config.sources[storage.config.repositories["METADATA"]]
            if src.get("type") in ("localfs", "sharedfs") and src.get("path"):
                return Path(src["path"]) / "spans"
        except (KeyError, AttributeError):
            pass
    return Path.home() / ".cache" / "predictionio_tpu" / "spans"


def journal_path(storage, instance_id: str) -> Path:
    safe = "".join(c for c in instance_id if c.isalnum() or c in "_-")
    return spans_dir(storage) / f"{safe}.jsonl"
