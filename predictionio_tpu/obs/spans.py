"""Structured span collection: train/eval journals and the building
block the request flight recorder (``obs.tracing``) shares.

``utils.tracing.timed`` logged wall-clock spans and accumulated them in a
dict; :class:`SpanCollector` extends that into structured records with
parent/child links.  Two consumers build on it:

- :class:`SpanJournal` — one JSONL file per workflow run (train or
  eval), written next to the engine instances so ``pio dashboard`` can
  render the breakdown of every completed run;
- ``obs.tracing.Trace`` — the per-HTTP-request live trace of the flight
  recorder.

Parent/child structure comes from a per-thread stack: a span opened
while another is active on the same thread becomes its child.  The
ACTIVE journal travels via a contextvar, so any ``timed()`` call inside
``engine.train`` — engine code never imports this module — lands in the
run's journal automatically.

Journal location (:func:`spans_dir`): ``PIO_SPANS_DIR`` if set, else
``<storage localfs/sharedfs METADATA path>/spans/`` (next to the engine
instances), else ``~/.cache/predictionio_tpu/spans``.  File name is the
engine/evaluation instance id: ``<instance_id>.jsonl``.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
from pathlib import Path
from typing import Iterator, List, Optional

_CURRENT: contextvars.ContextVar[Optional["SpanJournal"]] = (
    contextvars.ContextVar("pio_span_journal", default=None))


def current_journal() -> Optional["SpanJournal"]:
    return _CURRENT.get()


class SpanCollector:
    """Accumulates spans with parent/child links (per-thread stacks).

    Span record shape (shared by journals, traces, and the dashboard
    renderers): ``{id, parent, name, start, duration_s, end, attrs?,
    error?}`` — ``start``/``end`` are wall-clock epoch seconds,
    ``duration_s`` is measured on the monotonic clock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._spans: List[dict] = []
        self._next_id = 1
        self._tls = threading.local()

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[dict]:
        with self._lock:
            span_id = self._next_id
            self._next_id += 1
        stack = self._stack()
        parent = stack[-1] if stack else None
        rec = {"id": span_id, "parent": parent, "name": name,
               "start": time.time()}
        if attrs:
            rec["attrs"] = {k: v for k, v in attrs.items()}
        stack.append(span_id)
        t0 = time.perf_counter()
        try:
            yield rec
        except BaseException:
            rec["error"] = True
            raise
        finally:
            rec["duration_s"] = time.perf_counter() - t0
            rec["end"] = rec["start"] + rec["duration_s"]
            stack.pop()
            with self._lock:
                self._spans.append(rec)
            if parent is None:
                self._on_root_complete()

    def add_span(self, name: str, start: float, duration_s: float,
                 parent: Optional[int] = None,
                 attrs: Optional[dict] = None) -> dict:
        """Record an already-measured span (e.g. serve-tail stage laps
        reconstructed from accumulated wall times) without paying a
        contextmanager per stage on the hot path."""
        with self._lock:
            span_id = self._next_id
            self._next_id += 1
            rec = {"id": span_id, "parent": parent, "name": name,
                   "start": start, "duration_s": duration_s,
                   "end": start + duration_s}
            if attrs:
                rec["attrs"] = dict(attrs)
            self._spans.append(rec)
        return rec

    def spans(self) -> List[dict]:
        with self._lock:
            return sorted(self._spans, key=lambda s: s["id"])

    def _on_root_complete(self) -> None:
        """Hook: a top-level span just finished (journals flush here)."""


class SpanJournal(SpanCollector):
    """Collects spans for one run and persists them as JSONL
    incrementally: every completed ROOT span flushes the buffered
    records, so a crashed train/eval run keeps every phase that finished
    before the crash instead of losing the whole journal (the old
    write-once-at-close behavior)."""

    def __init__(self, path):
        super().__init__()
        self.path = Path(path)
        self._file = None
        self._flushed = 0   # count of spans already appended to the file

    def _on_root_complete(self) -> None:
        try:
            self.flush()
        except OSError:
            import logging

            logging.getLogger("pio.trace").exception(
                "span journal flush failed: %s", self.path)

    def flush(self) -> None:
        """Append every not-yet-persisted completed span to the file and
        flush to the OS, so a SIGKILLed process loses at most the spans
        still open (never a completed root and its children)."""
        with self._lock:
            pending = sorted(self._spans[self._flushed:],
                             key=lambda s: s["id"])
            self._flushed = len(self._spans)
            if not pending:
                return
            if self._file is None:
                self.path.parent.mkdir(parents=True, exist_ok=True)
                # "w": a journal owns its path for exactly one run; any
                # stale file from a recycled instance id must not prepend
                # a previous run's spans
                self._file = open(self.path, "w")
            for rec in pending:
                self._file.write(json.dumps(rec, sort_keys=True) + "\n")
            self._file.flush()

    def write(self) -> None:
        """Final drain + close (kept under its historical name: callers
        treat it as 'persist everything now')."""
        self.flush()
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
            elif not self._spans:
                # a run that recorded nothing still leaves an empty
                # journal, preserving the old write()'s contract that the
                # file exists after a completed run
                self.path.parent.mkdir(parents=True, exist_ok=True)
                self.path.touch()

    @contextlib.contextmanager
    def activate(self) -> Iterator["SpanJournal"]:
        """Make this the process-current journal (timed() feeds it) for
        the duration; the journal is fully persisted on exit, success or
        not (and incrementally while running)."""
        token = _CURRENT.set(self)
        try:
            yield self
        finally:
            _CURRENT.reset(token)
            try:
                self.write()
            except OSError:
                import logging

                logging.getLogger("pio.trace").exception(
                    "span journal write failed: %s", self.path)


def read_journal(path) -> List[dict]:
    """Load a journal; missing file → [].  A torn final line (crash
    mid-append) is skipped, matching the incremental-append format."""
    p = Path(path)
    if not p.exists():
        return []
    out = []
    for line in p.read_text().splitlines():
        line = line.strip()
        if line:
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


def spans_dir(storage=None) -> Path:
    """Where this deployment's span journals live (see module docstring
    for the precedence)."""
    env = os.environ.get("PIO_SPANS_DIR")
    if env:
        return Path(env)
    if storage is not None:
        try:
            src = storage.config.sources[storage.config.repositories["METADATA"]]
            if src.get("type") in ("localfs", "sharedfs") and src.get("path"):
                return Path(src["path"]) / "spans"
        except (KeyError, AttributeError):
            pass
    return Path.home() / ".cache" / "predictionio_tpu" / "spans"


def journal_path(storage, instance_id: str) -> Path:
    safe = "".join(c for c in instance_id if c.isalnum() or c in "_-")
    return spans_dir(storage) / f"{safe}.jsonl"
