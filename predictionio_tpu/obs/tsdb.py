"""Local metrics time-series ring — Prometheus-shaped history, no
Prometheus.

A :class:`MetricsSampler` thread snapshots the cross-worker aggregate
view (``metrics.aggregate_snapshot`` — the same merge a ``/metrics``
scrape performs) every ``PIO_TSDB_INTERVAL_S`` seconds into a bounded
in-memory ring (``PIO_TSDB_RING`` samples), served as
``/metrics/history.json``.  ``pio top`` renders qps/p95/lag/state-bytes
sparklines from consecutive samples, and the SLO engine
(:mod:`obs.slo`) evaluates its burn-rate windows over the same ring —
both without an external TSDB, which matches the deployment story:
one node, many workers, zero infrastructure.

Samples are *reduced*: counters/gauges keep their per-series values,
histograms keep per-series (counts, sum, count) with the bucket
boundaries hoisted once per metric — a ring of 360 samples at the
default 5 s interval is 30 minutes of history in a few MB.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from predictionio_tpu.obs import metrics as _metrics


def tsdb_interval_s() -> float:
    """PIO_TSDB_INTERVAL_S: seconds between history samples (default 5)."""
    try:
        return max(float(os.environ.get("PIO_TSDB_INTERVAL_S", "5.0")), 0.1)
    except ValueError:
        return 5.0


def tsdb_ring() -> int:
    """PIO_TSDB_RING: samples kept (default 360 — 30 min at 5 s)."""
    try:
        return max(int(os.environ.get("PIO_TSDB_RING", "360")), 2)
    except ValueError:
        return 360


def reduce_snapshot(snap: dict) -> Dict[str, dict]:
    """One history sample's metric map from a full registry snapshot:
    drop help strings, keep per-series values (histograms keep their
    cumulative bucket counts — quantile-over-window needs them)."""
    out: Dict[str, dict] = {}
    for name, entry in snap.items():
        kind = entry.get("type")
        if kind == "histogram":
            out[name] = {"type": kind, "series": {
                k: {"counts": list(v["counts"]), "sum": v["sum"],
                    "count": v["count"]}
                for k, v in entry.get("series", {}).items()}}
        else:
            out[name] = {"type": kind,
                         "series": dict(entry.get("series", {}))}
    return out


class MetricsSampler:
    """Background ring of reduced metric samples + the /metrics/history
    payload.  One per process; sampling the AGGREGATE view means any
    worker's history describes the whole prefork group."""

    def __init__(self, interval: Optional[float] = None,
                 ring: Optional[int] = None):
        self.interval = interval if interval is not None else tsdb_interval_s()
        self._ring: deque = deque(maxlen=ring or tsdb_ring())
        self._buckets: Dict[str, List[float]] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sample_now(self) -> dict:
        """Take one sample synchronously (also the thread's tick)."""
        snap = _metrics.aggregate_snapshot()
        sample = {"t": time.time(), "m": reduce_snapshot(snap)}
        with self._lock:
            for name, entry in snap.items():
                if entry.get("type") == "histogram" and "buckets" in entry:
                    self._buckets[name] = list(entry["buckets"])
            self._ring.append(sample)
        self._evaluate_slos()
        return sample

    def _evaluate_slos(self) -> None:
        """Refresh the SLO burn-rate gauges on every sample so /metrics
        carries them without anyone polling /healthz."""
        try:
            from predictionio_tpu.obs import slo as _slo

            _slo.get_engine().evaluate(self.samples(), self._buckets_copy())
        except Exception:
            pass   # SLO evaluation must never kill the sampler

    def samples(self, limit: Optional[int] = None) -> List[dict]:
        with self._lock:
            out = list(self._ring)
        if limit is not None and limit > 0:
            out = out[-limit:]
        return out

    def _buckets_copy(self) -> Dict[str, List[float]]:
        with self._lock:
            return {k: list(v) for k, v in self._buckets.items()}

    def history(self, limit: int = 120) -> dict:
        """The /metrics/history.json body."""
        return {
            "worker": _metrics.worker_tag(),
            "intervalSeconds": self.interval,
            "buckets": self._buckets_copy(),
            "samples": self.samples(limit),
        }

    def start(self) -> None:
        if self._thread is not None:
            return
        self.sample_now()

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.sample_now()
                except Exception:
                    pass   # a torn sibling file mid-merge; next tick heals

        self._thread = threading.Thread(
            target=loop, daemon=True, name="pio-tsdb-sample")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


_sampler: Optional[MetricsSampler] = None
_sampler_lock = threading.Lock()


def get_sampler() -> MetricsSampler:
    global _sampler
    with _sampler_lock:
        if _sampler is None:
            _sampler = MetricsSampler()
        return _sampler


def set_sampler(sampler: Optional[MetricsSampler]) -> None:
    """Swap the process sampler (tests; None resets to lazy default)."""
    global _sampler
    with _sampler_lock:
        if _sampler is not None and sampler is not _sampler:
            _sampler.stop()
        _sampler = sampler


def start_sampler() -> MetricsSampler:
    """Arm the history ring for this process — servers call this at
    startup, next to ``tracing.arm``; repeated calls are no-ops."""
    s = get_sampler()
    s.start()
    return s


def handle_history_request(handler, path: str) -> bool:
    """Serve /metrics/history.json on any JsonHandler server; returns
    True when the path was ours.  ``?limit=N`` bounds the sample count
    — the cluster federation scrapes with a small limit so a round
    over K nodes moves KBs, not the whole ring."""
    if path != "/metrics/history.json":
        return False
    if not _metrics.get_registry().enabled:
        handler.send_error_json(503, "metrics disabled (PIO_METRICS=off)")
        return True
    try:
        limit = int((handler.route[1] or {}).get("limit", "120"))
    except (ValueError, TypeError):
        limit = 120
    handler.send_json(get_sampler().history(limit))
    return True
