"""Request flight recorder: always-on, tail-sampled HTTP request traces.

Every request on the event server, query server, and dashboard opens a
live :class:`Trace` (keyed by the X-Request-ID the http_util middleware
already mints/propagates); instrumented layers append spans to it
through the ``current_trace()`` contextvar (``utils.tracing.timed``, the
storage group commit, snapshot scans, and the UR serve tail all feed
it).  At request end the :class:`FlightRecorder` makes the *tail
sampling* decision (Dapper/Canopy style — record everything cheaply,
keep only what matters):

- ``slow``    — duration ≥ ``PIO_TRACE_SLOW_MS`` (default 250 ms);
- ``error``   — response status ≥ 500 (or the connection died mid-write);
- ``debug``   — the request carried an ``X-PIO-Debug`` header;
- ``sampled`` — 1-in-``PIO_TRACE_SAMPLE_N`` uniform keep (default 1000,
  ``0`` disables), the ambient baseline that keeps /traces.json useful
  even when nothing is wrong.

Everything else is dropped at request end: a boring request costs one
small object, two contextvar ops, and one branch — the bench's
serve_scale section guards the end-to-end cost at ≤3%.

Retained traces land in a bounded per-worker ring (``PIO_TRACE_RING``,
default 128) and are persisted to ``<traces dir>/<worker tag>.json`` so
ANY worker of a prefork group (or a dashboard sharing the storage) can
answer ``/traces.json`` (index) and ``/traces/<rid>.json`` (full
waterfall) for the whole group — the same sibling-snapshot pattern as
the cross-worker /metrics merge.

Traces dir precedence (:func:`traces_dir`): ``PIO_TRACE_DIR``, else
``<PIO_METRICS_DIR>/traces`` (prefork groups), else ``<storage
localfs/sharedfs METADATA path>/traces`` (next to span journals), else
in-memory only.  Kill switch: ``PIO_TRACING=off``.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import random
import threading
import time
from collections import deque
from pathlib import Path
from typing import Dict, List, Optional

from predictionio_tpu.obs import metrics as _metrics
from predictionio_tpu.obs.spans import SpanCollector

_REG = _metrics.get_registry()
_M_RETAINED = _REG.counter(
    "pio_traces_retained_total",
    "Traces kept by the flight recorder, by tail-sampling reason "
    "(slow/error/debug/sampled)")
_M_EVICTED = _REG.counter(
    "pio_trace_ring_evictions_total",
    "Retained traces evicted from the ring buffer by newer ones")

_CURRENT: contextvars.ContextVar[Optional["Trace"]] = (
    contextvars.ContextVar("pio_trace", default=None))

# span/attr naming contract (linted by scripts/check_metrics_names.py):
# lowercase snake with optional dots, like metric names without the
# pio_ prefix — keeps waterfall rows greppable and dashboards stable
SPAN_NAME_PATTERN = r"^[a-z][a-z0-9_.]*$"


def current_trace() -> Optional["Trace"]:
    return _CURRENT.get()


def trace_span(name: str, **attrs):
    """Span on the current request trace, or a no-op when none is active
    — the one-liner instrumented layers use so they never import more
    than this function."""
    t = _CURRENT.get()
    if t is None:
        return contextlib.nullcontext()
    return t.span(name, **attrs)


class Trace(SpanCollector):
    """One request's live trace: span collector + request envelope."""

    def __init__(self, rid: str, method: str = "", debug: bool = False):
        super().__init__()
        self.rid = rid
        self.method = method
        self.debug = debug
        self.start = time.time()
        self._t0 = time.perf_counter()
        self.route = ""
        self.status = 0

    def to_doc(self, worker: str, reason: str) -> dict:
        dur = time.perf_counter() - self._t0
        return {
            "rid": self.rid,
            "start": self.start,
            "durationMs": round(dur * 1e3, 4),
            "method": self.method,
            "route": self.route,
            "status": self.status,
            "worker": worker,
            "reason": reason,
            "spans": self.spans(),
        }

    def duration_s(self) -> float:
        return time.perf_counter() - self._t0

    @contextlib.contextmanager
    def activate(self):
        token = _CURRENT.set(self)
        try:
            yield self
        finally:
            _CURRENT.reset(token)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def traces_dir(storage=None) -> Optional[Path]:
    """Where this process persists retained traces for siblings (see
    module docstring for the precedence); None = in-memory ring only."""
    env = os.environ.get("PIO_TRACE_DIR")
    if env:
        return Path(env)
    md = os.environ.get("PIO_METRICS_DIR")
    if md:
        return Path(md) / "traces"
    if storage is not None:
        try:
            src = storage.config.sources[storage.config.repositories["METADATA"]]
            if src.get("type") in ("localfs", "sharedfs") and src.get("path"):
                return Path(src["path"]) / "traces"
        except (KeyError, AttributeError):
            pass
    return None


class FlightRecorder:
    """Per-process retained-trace ring + the tail-sampling policy."""

    # persistence is coalesced to at most one ring write per window: a
    # retention inside the window arms a one-shot deferred flush instead
    # of rewriting the whole ring inline per request (an unauthenticated
    # X-PIO-Debug spammer must not turn every request into an O(ring)
    # disk write), so a sibling can still fetch any retained trace
    # within ~this many seconds
    PERSIST_THROTTLE_S = 0.5

    def __init__(self, ring: Optional[int] = None,
                 slow_ms: Optional[float] = None,
                 sample_n: Optional[int] = None,
                 directory: Optional[os.PathLike] = None,
                 tag: Optional[str] = None,
                 enabled: Optional[bool] = None):
        if enabled is None:
            enabled = os.environ.get("PIO_TRACING", "").lower() not in (
                "off", "0", "false")
        self.enabled = enabled
        self.slow_ms = slow_ms if slow_ms is not None else _env_float(
            "PIO_TRACE_SLOW_MS", 250.0)
        self.sample_n = sample_n if sample_n is not None else _env_int(
            "PIO_TRACE_SAMPLE_N", 1000)
        size = ring if ring is not None else max(
            _env_int("PIO_TRACE_RING", 128), 1)
        self._ring: deque = deque(maxlen=size)
        self._lock = threading.Lock()
        # serializes the snapshot+write+rename; the ring lock is never
        # held across file I/O
        self._io_lock = threading.Lock()
        self.dir: Optional[Path] = Path(directory) if directory else None
        self._tag = tag
        self._dirty = False
        self._last_persist = 0.0
        self._flush_timer: Optional[threading.Timer] = None

    @property
    def tag(self) -> str:
        return self._tag or _metrics.worker_tag()

    def configure(self, directory: Optional[os.PathLike],
                  tag: Optional[str] = None) -> None:
        with self._lock:
            self.dir = Path(directory) if directory else None
            if tag is not None:
                self._tag = tag

    # -- request lifecycle ---------------------------------------------------

    def begin(self, rid: str, method: str = "",
              debug: bool = False) -> Optional[Trace]:
        if not self.enabled:
            return None
        return Trace(rid, method, debug=debug)

    def finish(self, trace: Optional[Trace], status: int,
               route: str = "") -> Optional[str]:
        """Request-end tail-sampling decision; returns the retention
        reason, or None when the trace was dropped."""
        if trace is None:
            return None
        trace.status = status
        trace.route = route
        reason = None
        if trace.debug:
            reason = "debug"
        elif status >= 500 or status == 0:
            reason = "error"
        elif trace.duration_s() * 1e3 >= self.slow_ms:
            reason = "slow"
        elif self.sample_n > 0 and random.randrange(self.sample_n) == 0:
            reason = "sampled"
        if reason is None:
            return None
        doc = trace.to_doc(self.tag, reason)
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                _M_EVICTED.inc()
            self._ring.append(doc)
            self._dirty = True
        _M_RETAINED.inc(1, reason=reason)
        self._request_persist()
        return reason

    def record(self, doc: dict) -> None:
        """Inject a pre-built trace doc (tests)."""
        with self._lock:
            self._ring.append(doc)
            self._dirty = True
        self._persist()

    # -- persistence + cross-worker merge ------------------------------------

    def _request_persist(self) -> None:
        """Persist now when outside the throttle window; otherwise arm
        ONE deferred flush at the window's end, so bursts of retentions
        coalesce into a single ring write while a sibling can still
        fetch any retained trace within PERSIST_THROTTLE_S."""
        if self.dir is None:
            return
        delay = self.PERSIST_THROTTLE_S - (
            time.monotonic() - self._last_persist)
        if delay <= 0:
            self._persist()
            return
        with self._lock:
            if self._flush_timer is not None:
                return
            t = self._flush_timer = threading.Timer(delay, self._timer_flush)
            t.daemon = True
        t.start()

    def _timer_flush(self) -> None:
        with self._lock:
            self._flush_timer = None
        self.flush()

    def _persist(self) -> None:
        if self.dir is None:
            return
        # _io_lock serializes concurrent retentions' writes (handler
        # threads share one tag file; unserialized writers would race on
        # the tmp file and the second os.replace would lose its traces)
        with self._io_lock:
            with self._lock:
                payload = {"worker": self.tag, "flushedAt": time.time(),
                           "traces": list(self._ring)}
                self._dirty = False
            self._last_persist = time.monotonic()
            path = self.dir / f"{self.tag}.json"
            tmp = path.with_name(path.name + f".tmp{os.getpid()}")
            try:
                os.makedirs(self.dir, exist_ok=True)
                with open(tmp, "w") as f:
                    json.dump(payload, f)
                os.replace(tmp, path)
            except OSError:
                # mid-teardown dir removal: a missed persist only
                # staleness-lags the siblings' view — but the ring is
                # still dirty, so a later flush can retry
                with self._lock:
                    self._dirty = True
                with contextlib.suppress(OSError):
                    os.unlink(tmp)

    def flush(self) -> None:
        if self._dirty:
            self._persist()

    def _sibling_docs(self) -> List[dict]:
        """Every worker's persisted ring (including our own file's —
        deduped by rid later), newest files first."""
        if self.dir is None:
            return []
        self.flush()   # serve-own-retentions-immediately, like /metrics
        try:
            names = [n for n in os.listdir(self.dir) if n.endswith(".json")]
        except OSError:
            return []
        docs: List[dict] = []
        now = time.time()
        stale_after = _metrics.sibling_stale_s()
        for name in names:
            path = self.dir / name
            try:
                mtime = os.stat(path).st_mtime
            except OSError:
                continue
            if now - mtime > stale_after:
                # a dead group member's leftovers: evict from the merge
                # and reclaim the disk — but never our OWN file (the
                # live in-memory ring is merged separately and the next
                # retention re-creates it)
                if name != f"{self.tag}.json":
                    try:
                        os.unlink(path)
                        _metrics.STALE_SIBLINGS.inc(1, kind="traces")
                    except OSError:
                        pass
                continue
            try:
                with open(path) as f:
                    payload = json.load(f)
                docs.extend(payload.get("traces", ()))
            except (OSError, json.JSONDecodeError):
                continue   # sibling mid-write; next read heals
        return docs

    def _merged(self) -> List[dict]:
        by_rid: Dict[str, dict] = {}
        with self._lock:
            own = list(self._ring)
        for doc in self._sibling_docs() + own:
            prev = by_rid.get(doc.get("rid", ""))
            if prev is None or doc.get("start", 0) >= prev.get("start", 0):
                by_rid[doc.get("rid", "")] = doc
        return sorted(by_rid.values(),
                      key=lambda d: d.get("start", 0), reverse=True)

    def index(self, limit: int = 200) -> dict:
        """The /traces.json body: cross-worker merged summaries, newest
        first."""
        entries = [{k: d.get(k) for k in
                    ("rid", "start", "durationMs", "method", "route",
                     "status", "worker", "reason")}
                   | {"spanCount": len(d.get("spans", ()))}
                   for d in self._merged()[:limit]]
        return {"worker": self.tag, "traces": entries}

    def get(self, rid: str) -> Optional[dict]:
        """Full waterfall for one request id, from our ring or any
        sibling's persisted ring."""
        with self._lock:
            for doc in reversed(self._ring):
                if doc.get("rid") == rid:
                    return doc
        for doc in self._merged():
            if doc.get("rid") == rid:
                return doc
        return None


_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    global _recorder
    with _recorder_lock:
        if _recorder is None:
            _recorder = FlightRecorder()
        return _recorder


def set_recorder(recorder: Optional[FlightRecorder]) -> None:
    """Swap the process recorder (tests; None resets to lazy default)."""
    global _recorder
    with _recorder_lock:
        _recorder = recorder


def arm(storage=None, directory: Optional[os.PathLike] = None,
        tag: Optional[str] = None) -> FlightRecorder:
    """Point the process recorder at this deployment's traces dir so
    retained traces become visible to sibling workers and the dashboard.
    Servers call this at startup; a missing dir keeps the ring
    in-memory-only (endpoints still serve this process's traces)."""
    rec = get_recorder()
    rec.configure(directory if directory is not None else traces_dir(storage),
                  tag)
    return rec


def render_waterfall_text(doc: dict, width: int = 40) -> str:
    """ASCII waterfall of one trace doc (``pio trace`` output): spans
    indented by depth, bars proportional to their offset/duration within
    the request."""
    total_ms = max(float(doc.get("durationMs") or 0.0), 1e-6)
    t0 = float(doc.get("start") or 0.0)
    lines = [
        "trace %s: %s %s -> %s in %.2f ms (worker %s, kept: %s)" % (
            doc.get("rid", "?"), doc.get("method", ""), doc.get("route", ""),
            doc.get("status", 0), total_ms, doc.get("worker", "?"),
            doc.get("reason", "?"))]
    depth = {None: -1}
    for s in sorted(doc.get("spans", ()), key=lambda x: x.get("id", 0)):
        depth[s.get("id")] = d = depth.get(s.get("parent"), -1) + 1
        off_ms = max((float(s.get("start", t0)) - t0) * 1e3, 0.0)
        dur_ms = float(s.get("duration_s", 0.0)) * 1e3
        i0 = min(int(off_ms / total_ms * width), width - 1)
        i1 = min(max(int((off_ms + dur_ms) / total_ms * width), i0 + 1), width)
        bar = " " * i0 + "#" * (i1 - i0) + " " * (width - i1)
        name = "  " * d + str(s.get("name", "?"))
        err = " !" if s.get("error") else ""
        attrs = s.get("attrs") or {}
        attr_txt = (" " + " ".join(f"{k}={v}"
                                   for k, v in sorted(attrs.items()))
                    if attrs else "")
        lines.append("  %-28s %9.3f ms |%s|%s%s"
                     % (name[:28], dur_ms, bar, err, attr_txt))
    if not doc.get("spans"):
        lines.append("  (no spans recorded below the request envelope)")
    return "\n".join(lines) + "\n"


# -- shared HTTP endpoints ----------------------------------------------------

def handle_trace_request(handler, path: str) -> bool:
    """Serve /traces.json and /traces/<rid>.json on any JsonHandler
    server; returns True when the path was one of ours.  Unauthenticated
    like /metrics: traces carry route/timing structure, not event
    payloads."""
    if path == "/traces.json":
        rec = get_recorder()
        if not rec.enabled:
            handler.send_error_json(503, "tracing disabled (PIO_TRACING=off)")
            return True
        handler.send_json(rec.index())
        return True
    if path.startswith("/traces/") and path.endswith(".json"):
        rec = get_recorder()
        if not rec.enabled:
            handler.send_error_json(503, "tracing disabled (PIO_TRACING=off)")
            return True
        rid = path[len("/traces/"):-len(".json")]
        doc = rec.get(rid)
        if doc is None:
            handler.send_error_json(
                404, f"no retained trace for request id {rid!r}")
        else:
            handler.send_json(doc)
        return True
    return False
