"""Cluster observability: federate subscriber metrics and lineage onto
the publisher.

PR 19 made the model plane multi-node; this module makes the CLUSTER
the unit of observation.  A :class:`ClusterFederation` thread on the
publisher scrapes every replication subscriber's ``/metrics/history.json``
(endpoints are announced in the replication sync frames — no separate
service discovery) with a bounded timeout, keeps a per-node liveness
view (a node that stops answering is reported ``up: false`` with its
staleness, never silently dropped), and pulls each subscriber's recent
``/lineage/<lid>.json`` records to complete the stitched cross-node
lineage story — the ack-payload push covers ``repl.*`` stages, the pull
covers the ``install``/``first_serve`` stages that happen AFTER the
subscriber last acked.

Federated signals are re-exported as LOCAL publisher metrics so the
existing tsdb ring and SLO engine evaluate cluster health with zero new
machinery:

- ``pio_cluster_propagation_seconds`` — append → last-node
  ``first_serve``, read from stitched ``cluster_complete`` lineage
  records (NOT client-side wall clocks), observed once per lineage id;
- ``pio_cluster_qps_divergence`` / ``pio_cluster_p95_divergence`` —
  hottest/slowest node over the cluster mean (1.0 = perfectly even);
- ``pio_cluster_node_up{node}`` / ``pio_cluster_nodes`` /
  ``pio_cluster_scrapes_total{node,outcome}`` — the scrape loop's own
  health.

Served as ``/cluster/metrics.json`` (latest per-node view) and
``/cluster/history.json`` (bounded ring of federated samples) on the
publisher only; ``pio top --cluster`` renders per-node columns from
them.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from predictionio_tpu.obs import lineage as _lineage
from predictionio_tpu.obs import metrics as _metrics
from predictionio_tpu.obs.exposition import _quantile_from_buckets
from predictionio_tpu.obs.slo import (
    _series_max,
    _series_sum_hist,
    _series_total,
)

log = logging.getLogger("pio.cluster")

_REG = _metrics.get_registry()

# propagation spans network + install cadence, not request latency:
# wider buckets than LATENCY_BUCKETS, topping out at minutes
PROPAGATION_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                       10.0, 30.0, 60.0, 120.0)

_M_NODES = _REG.gauge(
    "pio_cluster_nodes",
    "Subscriber nodes this publisher has ever seen over replication "
    "(the federation scrape list; disconnects mark, never remove)")
_M_UP = _REG.gauge(
    "pio_cluster_node_up",
    "1 when the named subscriber node answered its last federation "
    "scrape, 0 otherwise — stale nodes stay visible at 0 rather than "
    "disappearing")
_M_SCRAPES = _REG.counter(
    "pio_cluster_scrapes_total",
    "Federation scrape attempts by subscriber node and outcome "
    "(ok|error; error includes a node that never announced an HTTP "
    "endpoint)")
_M_PROP = _REG.histogram(
    "pio_cluster_propagation_seconds",
    "append_observed -> LAST node's first_serve, read from stitched "
    "cluster_complete lineage records (one observation per lineage id) "
    "— the cluster-truth propagation the multinode bench reports",
    buckets=PROPAGATION_BUCKETS)
_M_QPS_DIV = _REG.gauge(
    "pio_cluster_qps_divergence",
    "Hottest node's serve qps over the cluster mean (1.0 = perfectly "
    "balanced; computed over nodes that answered their last scrape)")
_M_P95_DIV = _REG.gauge(
    "pio_cluster_p95_divergence",
    "Slowest node's serve p95 over the cluster mean (1.0 = uniform "
    "latency; computed over nodes that answered their last scrape)")


def cluster_scrape_s() -> float:
    """PIO_CLUSTER_SCRAPE_S: seconds between federation scrapes
    (default 5 — same cadence as the local tsdb ring)."""
    try:
        return max(float(os.environ.get("PIO_CLUSTER_SCRAPE_S", "5.0")),
                   0.1)
    except ValueError:
        return 5.0


def cluster_scrape_timeout_s() -> float:
    """PIO_CLUSTER_SCRAPE_TIMEOUT_S: per-node HTTP timeout (default 2).
    Bounded so one wedged node cannot stall the whole scrape round."""
    try:
        return max(float(os.environ.get(
            "PIO_CLUSTER_SCRAPE_TIMEOUT_S", "2.0")), 0.1)
    except ValueError:
        return 2.0


def cluster_ring() -> int:
    """PIO_CLUSTER_RING: federated samples kept (default 240 — 20 min
    at the 5 s default scrape)."""
    try:
        return max(int(os.environ.get("PIO_CLUSTER_RING", "240")), 2)
    except ValueError:
        return 240


def _fetch_json(url: str, timeout: float) -> Any:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8", "replace"))


def _node_stats(history: dict) -> Dict[str, Any]:
    """One node's headline numbers from its scraped history body:
    serving generation, repl lag, qps and serve p95 over the scraped
    sample window.  Missing metrics stay None (a node that serves no
    queries has no p95 — that is signal, not an error)."""
    out: Dict[str, Any] = {"generation": None, "replLag": None,
                           "qps": None, "p95": None}
    samples = history.get("samples") or []
    if not samples:
        return out
    cur = samples[-1].get("m", {})
    gen = _series_max(cur.get("pio_model_plane_generation"), "")
    if gen is not None:
        out["generation"] = int(gen)
    lag = _series_max(cur.get("pio_plane_repl_lag_generations"), "")
    if lag is not None:
        out["replLag"] = lag
    if len(samples) < 2:
        return out
    first = samples[0].get("m", {})
    dt = float(samples[-1].get("t", 0)) - float(samples[0].get("t", 0))
    if dt > 0:
        c0 = _series_total(first.get("pio_http_requests_total"), "")
        c1 = _series_total(cur.get("pio_http_requests_total"), "")
        if c1 is not None:
            delta = c1 - (c0 or 0.0)
            if delta < 0:          # a worker restarted mid-window
                delta = c1
            out["qps"] = round(delta / dt, 3)
    h1 = _series_sum_hist(cur.get("pio_http_request_duration_seconds"),
                          'route="/queries.json"')
    bounds = (history.get("buckets") or {}).get(
        "pio_http_request_duration_seconds")
    if h1 is not None and bounds:
        h0 = _series_sum_hist(
            first.get("pio_http_request_duration_seconds"),
            'route="/queries.json"')
        counts = list(h1["counts"])
        total = h1["count"]
        if h0 is not None and h0["count"] <= h1["count"]:
            counts = [a - b for a, b in zip(h1["counts"], h0["counts"])]
            total = h1["count"] - h0["count"]
        if total > 0:
            cum, pairs = 0.0, []
            for le, c in zip(list(bounds) + [float("inf")], counts):
                cum += max(c, 0)
                pairs.append((le, cum))
            out["p95"] = round(_quantile_from_buckets(
                pairs, total, 0.95), 6)
    return out


def _divergence(values: List[float]) -> float:
    """max/mean over the reporting nodes; 1.0 when fewer than two nodes
    report or nothing flows (no traffic is not an imbalance)."""
    vals = [float(v) for v in values if v is not None and v > 0]
    if len(vals) < 2:
        return 1.0
    mean = sum(vals) / len(vals)
    if mean <= 0:
        return 1.0
    return max(vals) / mean


class ClusterFederation:
    """The publisher's scrape loop over its replication peers.

    ``peers_fn`` returns the replicator's peer registry (node →
    {addr, httpPort, connected, lastSeen}); nodes are scraped whether
    or not their replication session is currently connected — a node
    mid-reconnect still serves, and a dead one must keep showing as
    down, not vanish."""

    def __init__(self, peers_fn: Callable[[], Dict[str, Dict[str, Any]]],
                 interval: Optional[float] = None,
                 timeout: Optional[float] = None,
                 ring: Optional[int] = None):
        self.peers_fn = peers_fn
        self.interval = (interval if interval is not None
                         else cluster_scrape_s())
        self.timeout = (timeout if timeout is not None
                        else cluster_scrape_timeout_s())
        self._nodes: Dict[str, Dict[str, Any]] = {}
        self._ring: deque = deque(maxlen=ring or cluster_ring())
        self._prop_seen: deque = deque(maxlen=512)
        self._prop_seen_set: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- scrape ----------------------------------------------------------------

    def scrape_once(self) -> dict:
        now = time.time()
        try:
            peers = self.peers_fn() or {}
        except Exception:
            peers = {}
        for node in sorted(peers):
            p = peers[node]
            with self._lock:
                st = self._nodes.setdefault(node, {
                    "node": node, "up": False, "lastOkAt": 0.0,
                    "error": None, "generation": None, "replLag": None,
                    "qps": None, "p95": None})
                st["connected"] = bool(p.get("connected"))
            addr = str(p.get("addr") or "127.0.0.1")
            port = int(p.get("httpPort") or 0)
            if not port:
                self._mark(node, now, ok=False,
                           error="no HTTP endpoint announced")
                continue
            base = f"http://{addr}:{port}"
            try:
                hist = _fetch_json(
                    f"{base}/metrics/history.json?limit=8", self.timeout)
                stats = _node_stats(hist if isinstance(hist, dict)
                                    else {})
                self._mark(node, now, ok=True,
                           endpoint=f"{addr}:{port}", stats=stats)
            except Exception as e:
                self._mark(node, now, ok=False,
                           endpoint=f"{addr}:{port}", error=str(e))
                continue
            try:
                self._pull_lineage(base, node)
            except Exception:
                log.debug("cluster: lineage pull from %s failed", node,
                          exc_info=True)
        _M_NODES.set(len(peers))
        self._observe_propagation()
        self._update_divergence()
        with self._lock:
            nodes = {n: dict(s) for n, s in self._nodes.items()}
        sample = {"t": now, "nodes": nodes}
        with self._lock:
            self._ring.append(sample)
        return sample

    def _mark(self, node: str, now: float, ok: bool,
              endpoint: Optional[str] = None,
              stats: Optional[Dict[str, Any]] = None,
              error: Optional[str] = None) -> None:
        with self._lock:
            st = self._nodes[node]
            if endpoint:
                st["endpoint"] = endpoint
            st["up"] = ok
            if ok:
                st["lastOkAt"] = now
                st["error"] = None
                st.update(stats or {})
            else:
                st["error"] = error
            last_ok = st.get("lastOkAt") or 0.0
            st["staleSeconds"] = (round(now - last_ok, 3)
                                  if last_ok else None)
        _M_UP.set(1.0 if ok else 0.0, node=node)
        _M_SCRAPES.inc(node=node, outcome="ok" if ok else "error")

    def _pull_lineage(self, base: str, node: str) -> None:
        """The pull half of stitching: fetch the subscriber's newest
        lineage records and merge them locally (dedupe makes the
        overlap with ack-payload push a no-op)."""
        rec = _lineage.get_lineage()
        if not rec.enabled:
            return
        idx = _fetch_json(f"{base}/lineage.json", self.timeout)
        entries = (idx.get("records") or [])[:4] \
            if isinstance(idx, dict) else []
        for e in entries:
            lid = e.get("lid")
            if not isinstance(lid, str) or not lid.startswith("ln-"):
                continue
            doc = _fetch_json(f"{base}/lineage/{lid}.json", self.timeout)
            if isinstance(doc, dict):
                rec.ingest([doc], node=node)

    def _observe_propagation(self) -> None:
        """Feed the propagation histogram from freshly-completed
        stitched records — once per lineage id, so the SLO quantile
        counts generations, not scrape rounds."""
        rec = _lineage.get_lineage()
        if not rec.enabled:
            return
        try:
            docs = rec.merged()[:16]
        except Exception:
            return
        for doc in docs:
            lid = doc.get("lid")
            if not lid or lid in self._prop_seen_set:
                continue
            _lineage.annotate_cluster(doc)
            if doc.get("outcome") != "cluster_complete":
                continue
            prop_ms = (doc.get("cluster") or {}).get("propagationMs")
            if prop_ms is None:
                continue
            if len(self._prop_seen) == self._prop_seen.maxlen:
                self._prop_seen_set.discard(self._prop_seen[0])
            self._prop_seen.append(lid)
            self._prop_seen_set.add(lid)
            _M_PROP.observe(float(prop_ms) / 1e3)

    def _update_divergence(self) -> None:
        with self._lock:
            up = [s for s in self._nodes.values() if s.get("up")]
        _M_QPS_DIV.set(_divergence([s.get("qps") for s in up]))
        _M_P95_DIV.set(_divergence([s.get("p95") for s in up]))

    # -- serving ---------------------------------------------------------------

    def metrics_doc(self) -> dict:
        """The /cluster/metrics.json body: latest per-node view."""
        with self._lock:
            nodes = {n: dict(s) for n, s in sorted(self._nodes.items())}
        return {"role": "publisher",
                "node": _lineage.cluster_node(),
                "scrapeIntervalSeconds": self.interval,
                "scrapeTimeoutSeconds": self.timeout,
                "generatedAt": time.time(),
                "nodes": nodes}

    def history_doc(self, limit: int = 120) -> dict:
        """The /cluster/history.json body: the federated sample ring."""
        with self._lock:
            samples = list(self._ring)
        if limit > 0:
            samples = samples[-limit:]
        return {"role": "publisher",
                "scrapeIntervalSeconds": self.interval,
                "samples": samples}

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.scrape_once()
                except Exception:
                    log.exception("cluster: federation scrape failed")

        self._thread = threading.Thread(
            target=loop, daemon=True, name="pio-cluster-scrape")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


# -- process singleton --------------------------------------------------------

_federation: Optional[ClusterFederation] = None
_federation_lock = threading.Lock()


def get_federation() -> Optional[ClusterFederation]:
    """The armed federation, or None on non-publisher processes (the
    /cluster endpoints 404 there — federation is publisher-only)."""
    with _federation_lock:
        return _federation


def set_federation(fed: Optional[ClusterFederation]) -> None:
    global _federation
    with _federation_lock:
        old, _federation = _federation, fed
    if old is not None and old is not fed:
        old.stop()


# -- shared HTTP endpoints ----------------------------------------------------

def handle_cluster_request(handler, path: str) -> bool:
    """Serve /cluster/metrics.json and /cluster/history.json on any
    JsonHandler server; returns True when the path was ours."""
    if path not in ("/cluster/metrics.json", "/cluster/history.json"):
        return False
    fed = get_federation()
    if fed is None:
        handler.send_error_json(
            404, "cluster federation not armed (publisher-only endpoint"
            " — deploy with --plane-publish)")
        return True
    if path == "/cluster/metrics.json":
        handler.send_json(fed.metrics_doc())
        return True
    try:
        limit = int((handler.route[1] or {}).get("limit", "120"))
    except (ValueError, TypeError):
        limit = 120
    handler.send_json(fed.history_doc(limit))
    return True
