from predictionio_tpu.core.base import (  # noqa: F401
    BaseAlgorithm,
    BaseDataSource,
    BaseEngine,
    BaseEvaluator,
    BasePreparator,
    BaseServing,
    Doer,
)
