"""Core base types (reference: core/src/main/scala/io/prediction/core/).

The reference's ``Base*`` abstract classes carry the type plumbing between the
workflow and the user-facing controller API; ``Doer`` instantiates a component
class with its ``Params``.  The JAX rebuild keeps the same split: ``core``
holds the minimal contracts the workflow drives, ``controller`` the
user-facing API.

Design departure (TPU-first): the reference splits every component into
P(parallel/RDD) and L(local) variants because Spark distributes via RDDs.
Under JAX there is one execution model — host-orchestrated jitted programs
over device-sharded arrays — so there is a single variant; distribution is
expressed by `jax.sharding` annotations on the arrays, not by class split.
"""

from __future__ import annotations

import abc
import inspect
from typing import Any, Generic, List, Optional, Sequence, Type, TypeVar

from predictionio_tpu.controller.params import EmptyParams, Params

P = TypeVar("P", bound=Params)
TD = TypeVar("TD")   # training data
PD = TypeVar("PD")   # prepared data
M = TypeVar("M")     # model
Q = TypeVar("Q")     # query
PR = TypeVar("PR")   # prediction
A = TypeVar("A")     # actual (ground truth for eval)


class Doer(Generic[P]):
    """A component instantiated with its Params (reference: Doer.scala)."""

    params_class: Type[Params] = EmptyParams

    def __init__(self, params: Optional[Params] = None):
        if params is None or (
            type(params) is EmptyParams and self.params_class is not EmptyParams
        ):
            # EmptyParams stands for "use this component's defaults" — the
            # reference's EngineParams defaults every block to EmptyParams.
            params = self.params_class()
        self.params = params

    @classmethod
    def with_params(cls, params_json: Any) -> "Doer":
        return cls(cls.params_class.from_json(params_json))


class BaseDataSource(Doer[P], Generic[P, TD, Q, A], abc.ABC):
    @abc.abstractmethod
    def read_training(self) -> TD: ...

    def read_eval(self) -> Sequence[tuple]:
        """Yield (training_data, eval_query_actual_pairs) folds for evaluation.

        Reference: BaseDataSource.readEvalBase; default = no eval data.
        """
        return []


class BasePreparator(Doer[P], Generic[P, TD, PD], abc.ABC):
    @abc.abstractmethod
    def prepare(self, training_data: TD) -> PD: ...


class BaseAlgorithm(Doer[P], Generic[P, PD, M, Q, PR], abc.ABC):
    @abc.abstractmethod
    def train(self, prepared_data: PD) -> M: ...

    @abc.abstractmethod
    def predict(self, model: M, query: Q) -> PR: ...

    #: True when batch_predict is safe to use for DEPLOY-TIME serving —
    #: i.e. it reads exactly the same state per query as predict() (some
    #: overrides are eval-only: UR's substitutes model-recorded history
    #: for live-store lookups to avoid leaking held-out events).  Serving
    #: micro-batching (create_server) engages only when every algorithm
    #: sets this.
    serving_batchable: bool = False

    def batch_predict(self, model: M, queries: Sequence[Q]) -> List[PR]:
        """Vectorized predict used by evaluation (reference:
        PAlgorithm.batchPredict). Override for a jit/vmap fast path."""
        return [self.predict(model, q) for q in queries]


class BaseServing(Doer[P], Generic[P, Q, PR], abc.ABC):
    @abc.abstractmethod
    def serve(self, query: Q, predictions: Sequence[PR]) -> PR: ...


class BaseEvaluator(Doer[P], abc.ABC):
    @abc.abstractmethod
    def evaluate_base(self, engine, engine_params_list, params): ...


class BaseEngine(abc.ABC):
    @abc.abstractmethod
    def train(self, engine_params) -> Any: ...

    @abc.abstractmethod
    def eval(self, engine_params) -> Any: ...


def doer_name(obj: Any) -> str:
    cls = obj if inspect.isclass(obj) else type(obj)
    return f"{cls.__module__}.{cls.__qualname__}"
