"""Provenance-invalidated top-k response cache for the serve hot path.

The pruned host tail (PR 7) bottoms out around ~0.9 ms p50 because every
query still pays history→score→mask→topk→assemble, while the fold engine
PROVES almost nothing changed between generations (PR 13: ~3 re-selected
rows per million-item tick, PR 15: the exact changed-row/changed-id sets
ride the delta manifest).  This module memoizes whole responses and uses
those changed sets to keep entries alive across generation swaps, so
Zipf-shaped traffic becomes a dict hit plus response re-assembly.

Exactness contract (zero staleness, bit-identical to the uncached tail):

- The KEY covers every query-side input of the answer: the effective k
  (``min(query.num, n_items)``), the canonical business-rule key
  (``_mask_rule_key`` — sorted fields, quantized dates), the per-event-
  type history id fingerprint, and the blacklist id set.  History and
  blacklist are recomputed from the live store / current model on every
  lookup, so an event append reroutes to a new key immediately — user
  drift never needs invalidation, only model drift does.
- A LOOKUP only serves an entry created against the IDENTICAL model
  object (in-flight queries on a superseded generation bypass; a put
  from a superseded generation is refused).
- A SWAP (``QueryServerState._install`` → :meth:`ResponseCache.on_swap`)
  intersects the new generation's provenance against each entry:

  * per event type, a changed primary row ``r`` can only move the signal
    score of histories that hit a target in ``old_idx[r] ∪ new_idx[r]``
    (posting membership of ``r`` changes exactly at those target ids) —
    entries whose recorded history intersects those *affected targets*
    drop, everything else provably scores bit-identically;
  * entries whose RESULT ids intersect the changed rows or the
    popularity-moved ids drop (belt over the same suspenders);
  * any popularity movement drops entries that used (or fell short of)
    backfill — ``pop_norm`` and the backfill order may shift;
  * a properties change drops entries that carried business rules;
  * ``use_llr_weights`` deployments drop signal entries on every swap (a
    single N bump moves every LLR weight, so scores drift globally —
    counts-based scoring, the default, is swap-stable).

  A model arriving WITHOUT provenance (retrain, restage, plane keyframe
  after a rebuild, missing/mismatched prev token) flushes everything.
- Online self-check: every ``PIO_SERVE_CACHE_AUDIT_N``-th hit recomputes
  the tail and compares bit-exactly; a mismatch increments
  ``pio_serve_cache_audit_mismatch_total`` (alert on nonzero), logs, and
  full-flushes.  ``PIO_SERVE_CACHE=off`` is the kill-switch oracle.

Provenance sources, normalized by :func:`_swap_provenance`:

- in-process swaps (embedded follower): ``model._plane_prov`` — the fold
  engine's emit stash, valid iff its ``prev`` weakref is the cached
  generation (streaming/fold._carry_serving_state);
- plane workers: ``model._serve_prov`` — the publisher serializes the
  same changed sets into the arena (streaming/plane), valid iff its
  ``prevGeneration`` equals the cached generation's plane generation.

Knobs: ``PIO_SERVE_CACHE`` (on|off, default on), ``PIO_SERVE_CACHE_MAX``
(entries, default 4096), ``PIO_SERVE_CACHE_TTL_S`` (0 = no TTL),
``PIO_SERVE_CACHE_AUDIT_N`` (default 1000, 0 = off).
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time as _time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.obs import metrics as _obs_metrics

log = logging.getLogger("pio.serve.response_cache")

_REG = _obs_metrics.get_registry()
_M_CACHE = _REG.counter(
    "pio_serve_cache_total",
    "Response-cache lookups by outcome: hit (answer served from cache), "
    "miss (computed and filled), bypass (cache on but this query/model "
    "not cacheable — superseded generation, eval hist_override)")
_M_INVAL = _REG.counter(
    "pio_serve_cache_invalidations_total",
    "Response-cache entries dropped, by reason: no_provenance (swap "
    "without a usable changed-set — full flush), intersect (entry's "
    "history/result ids meet the swap's changed sets), backfill "
    "(popularity moved under a backfill-using entry), props (business-"
    "rule entry under a properties change), llr (use_llr_weights drifts "
    "scores every tick), audit (online self-check mismatch — full "
    "flush), disabled (engine without response-cache support installed), "
    "ttl, evict")
_M_ENTRIES = _REG.gauge(
    "pio_serve_cache_entries",
    "Live response-cache entries (one per distinct (history fingerprint, "
    "rule set, k, blacklist) answer)")
_M_AUDIT = _REG.counter(
    "pio_serve_cache_audit_mismatch_total",
    "Online response-cache self-check failures: a cached answer differed "
    "from the recomputed tail.  MUST stay 0 — nonzero means the "
    "invalidation proof was violated; the cache full-flushes and should "
    "be killed with PIO_SERVE_CACHE=off while the bug is found")

_EMPTY64 = np.zeros(0, np.int64)


def cache_enabled() -> bool:
    """The PIO_SERVE_CACHE kill switch (default on)."""
    return os.environ.get("PIO_SERVE_CACHE", "on").lower() not in (
        "off", "0", "false", "no")


def _cache_max() -> int:
    try:
        return max(int(os.environ.get("PIO_SERVE_CACHE_MAX", "4096")), 1)
    except ValueError:
        return 4096


def _cache_ttl_s() -> float:
    try:
        return max(float(os.environ.get("PIO_SERVE_CACHE_TTL_S", "0")), 0.0)
    except ValueError:
        return 0.0


def _audit_n() -> int:
    try:
        return max(int(os.environ.get("PIO_SERVE_CACHE_AUDIT_N", "1000")), 0)
    except ValueError:
        return 1000


def make_key(num: int, rule_key, hist: Optional[Dict[str, np.ndarray]],
             black_ids: Sequence[int]) -> tuple:
    """The full response key.  ``hist`` arrays are the per-event-type
    sorted-unique id lists the scorer consumes (raw bytes — exact, no
    hash collisions); the blacklist canonicalizes to its sorted-unique
    id SET (duplicates/order can't change masking)."""
    hk = (tuple(sorted((n, h.tobytes()) for n, h in hist.items()
                       if len(h)))
          if hist else ())
    bk = (np.unique(np.asarray(black_ids, np.int64)).tobytes()
          if black_ids else b"")
    return (int(num), rule_key, hk, bk)


class _Entry:
    __slots__ = ("items", "hist", "result_ids", "used_backfill",
                 "has_rules", "llr_sensitive", "ts")

    def __init__(self, items, hist, result_ids, used_backfill,
                 has_rules, llr_sensitive, ts):
        self.items = items                  # tuple[(item_str, score), ...]
        self.hist = hist                    # {name: sorted int64 ids}
        self.result_ids = result_ids        # sorted int64 primary ids
        self.used_backfill = used_backfill
        self.has_rules = has_rules
        self.llr_sensitive = llr_sensitive
        self.ts = ts


def _intersects(a: np.ndarray, b: np.ndarray) -> bool:
    """Nonempty intersection of two ASCENDING id arrays (searchsorted —
    both sides are pre-sorted, np.isin would re-sort per call)."""
    if not len(a) or not len(b):
        return False
    if len(b) < len(a):
        a, b = b, a
    pos = np.searchsorted(b, a)
    np.minimum(pos, len(b) - 1, out=pos)
    return bool((b[pos] == a).any())


def _is_ur_model(model) -> bool:
    """Duck check for the one model family the cache understands (the
    install path is engine-agnostic)."""
    return (hasattr(model, "indicator_idx") and hasattr(model, "item_dict")
            and hasattr(model, "popularity"))


def _swap_provenance(new, cur) -> Optional[dict]:
    """Normalize the new generation's provenance RELATIVE TO ``cur`` into
    ``{"inv": {name: changed primary rows}, "pop": changed ids,
    "props_changed": bool}`` — or None when any piece is unknown (the
    caller full-flushes).  Absence of a type in the fold stash means
    either carried-identical (provable by object identity) or rebuilt
    (unknown rows → None)."""
    if cur is None:
        return None
    sp = new.__dict__.get("_serve_prov")
    if sp is not None:
        # plane-composed generation: validity keyed to the PLANE
        # generation the publisher diffed against
        if int(sp.get("prev_gen") or -1) != int(
                cur.__dict__.get("_plane_generation") or -2):
            return None
        if set(new.indicator_idx) != set(cur.indicator_idx):
            return None
        inv = {}
        for name in new.indicator_idx:
            rows = sp["inv"].get(name)
            if rows is None:
                return None
            inv[name] = np.asarray(rows, np.int64)
        pop = sp.get("pop")
        if pop is None:
            return None
        return {"inv": inv, "pop": np.asarray(pop, np.int64),
                "props_changed": bool(sp.get("props_changed"))}
    prov = new.__dict__.get("_plane_prov")
    if not prov:
        return None
    ref = prov.get("prev")
    if ref is None or ref() is not cur:
        return None
    serve = prov.get("serve")
    if serve is None:
        return None     # fold couldn't prove the changed sets this tick
    if set(serve["inv"]) != set(new.indicator_idx) \
            or set(new.indicator_idx) != set(cur.indicator_idx):
        return None
    return {"inv": {n: np.asarray(v, np.int64)
                    for n, v in serve["inv"].items()},
            "pop": np.asarray(serve["pop"], np.int64),
            "props_changed":
                new.item_properties is not cur.item_properties}


def _affected_targets(prov: dict, new, cur) -> Dict[str, np.ndarray]:
    """Per event type, the target-space ids whose posting lists could
    have changed: ``unique(valid(old_idx[changed] ∪ new_idx[changed]))``.
    A history that avoids all of them gathers the identical posting rows
    (and, counts-based, the identical scores) from both generations."""
    aff: Dict[str, np.ndarray] = {}
    for name, rows in prov["inv"].items():
        parts: List[np.ndarray] = []
        if len(rows):
            for m in (cur, new):
                idx = np.asarray(m.indicator_idx[name])
                r = rows[rows < idx.shape[0]]
                if len(r):
                    vals = idx[r].ravel()
                    vals = vals[vals >= 0]
                    if len(vals):
                        parts.append(vals.astype(np.int64))
        aff[name] = (np.unique(np.concatenate(parts)) if parts
                     else _EMPTY64)
    return aff


class ResponseCache:
    """Bounded thread-safe LRU of whole top-k answers, armed on the model
    object the query server currently serves.  One instance per process
    (module singleton); prefork siblings each run their own, invalidated
    through the plane-carried provenance."""

    def __init__(self):
        self._lock = threading.Lock()
        self._data: "collections.OrderedDict[tuple, _Entry]" = \
            collections.OrderedDict()
        self._model = None
        self._hits = 0
        # introspection for tests/bench: totals since process start
        self.hit_count = 0
        self.miss_count = 0
        self.last_swap_invalidated = 0
        self.last_swap_reason = ""

    # -- serving side --------------------------------------------------------

    def armed_for(self, model) -> bool:
        """Fast gate for the predict hot path: cache globally on AND this
        exact model object is the installed generation."""
        return (self._model is model and model is not None
                and cache_enabled())

    def lookup(self, model, key: tuple) -> Tuple[Optional[tuple], bool]:
        """(cached items | None, audit_due).  Counts hit/miss/bypass."""
        now = _time.monotonic()
        ttl = _cache_ttl_s()
        audit = False
        with self._lock:
            if self._model is not model:
                outcome = "bypass"
                entry = None
            else:
                entry = self._data.get(key)
                if entry is not None and ttl and now - entry.ts > ttl:
                    del self._data[key]
                    _M_INVAL.inc(1, reason="ttl")
                    entry = None
                if entry is not None:
                    self._data.move_to_end(key)
                    outcome = "hit"
                    self._hits += 1
                    self.hit_count += 1
                    n = _audit_n()
                    audit = bool(n) and self._hits % n == 0
                else:
                    outcome = "miss"
                    self.miss_count += 1
            n_live = len(self._data)
        _M_CACHE.inc(1, outcome=outcome)
        _M_ENTRIES.set(n_live)
        return (entry.items if entry is not None else None), audit

    def count_bypass(self, n: int = 1) -> None:
        """Per-row bypass accounting for batch callers that skip lookup
        wholesale (e.g. hist_override)."""
        if n > 0:
            _M_CACHE.inc(n, outcome="bypass")

    def put(self, model, key: tuple, items, hist, result_ids,
            used_backfill: bool, has_rules: bool,
            llr_sensitive: bool) -> None:
        """Fill after a miss.  Refused when the generation moved under
        the in-flight query (the swap's invalidation sweep must stay
        authoritative) or the switch flipped off."""
        if not cache_enabled():
            return
        hist64 = {n: np.asarray(h, np.int64) for n, h in (hist or {}).items()
                  if len(h)}
        rids = np.unique(np.asarray(result_ids, np.int64))
        entry = _Entry(tuple(items), hist64, rids, bool(used_backfill),
                       bool(has_rules), bool(llr_sensitive),
                       _time.monotonic())
        evicted = 0
        with self._lock:
            if self._model is not model:
                return
            self._data[key] = entry
            self._data.move_to_end(key)
            cap = _cache_max()
            while len(self._data) > cap:
                self._data.popitem(last=False)
                evicted += 1
            n_live = len(self._data)
        if evicted:
            _M_INVAL.inc(evicted, reason="evict")
        _M_ENTRIES.set(n_live)

    def audit_mismatch(self, key: tuple) -> None:
        """An audited hit diverged from the recomputed tail: record it
        loudly and drop EVERYTHING — correctness over hit rate."""
        _M_AUDIT.inc(1)
        log.error("response cache: online audit mismatch (key drop + "
                  "full flush) — cached answer differed from the "
                  "recomputed tail; run with PIO_SERVE_CACHE=off and "
                  "report")
        with self._lock:
            n = len(self._data)
            self._data.clear()
        if n:
            _M_INVAL.inc(n, reason="audit")
        _M_ENTRIES.set(0)

    # -- install side --------------------------------------------------------

    def on_swap(self, models) -> None:
        """QueryServerState._install hook, called UNDER the install lock
        just before the new predictor goes live: re-arm on the new
        generation, dropping exactly the entries its provenance cannot
        prove unchanged."""
        model = (models[0] if isinstance(models, (list, tuple))
                 and len(models) == 1 else None)
        if model is None or not _is_ur_model(model):
            self.disarm()
            return
        with self._lock:
            cur = self._model
            self._model = model
            if cur is model or not self._data:
                self.last_swap_invalidated = 0
                self.last_swap_reason = "noop"
                n_live = len(self._data)
                dropped: Dict[str, int] = {}
            else:
                dropped = self._invalidate_locked(model, cur)
                n_live = len(self._data)
        for reason, n in dropped.items():
            _M_INVAL.inc(n, reason=reason)
        _M_ENTRIES.set(n_live)

    def _invalidate_locked(self, new, cur) -> Dict[str, int]:
        prov = _swap_provenance(new, cur)
        if prov is None:
            n = len(self._data)
            self._data.clear()
            self.last_swap_invalidated = n
            self.last_swap_reason = "no_provenance"
            return {"no_provenance": n} if n else {}
        aff = _affected_targets(prov, new, cur)
        # primary-space union for the result-id intersection check
        parts = [r for r in prov["inv"].values() if len(r)]
        if len(prov["pop"]):
            parts.append(prov["pop"])
        changed_union = (np.unique(np.concatenate(parts)) if parts
                         else _EMPTY64)
        pop_any = bool(len(prov["pop"]))
        dropped: Dict[str, int] = {}
        doomed: List[tuple] = []
        for key, e in self._data.items():
            reason = None
            if e.llr_sensitive:
                reason = "llr"
            elif prov["props_changed"] and e.has_rules:
                reason = "props"
            elif pop_any and e.used_backfill:
                reason = "backfill"
            elif _intersects(e.result_ids, changed_union) or any(
                    _intersects(h, aff.get(n, _EMPTY64))
                    for n, h in e.hist.items()):
                reason = "intersect"
            if reason is not None:
                doomed.append(key)
                dropped[reason] = dropped.get(reason, 0) + 1
        for key in doomed:
            del self._data[key]
        self.last_swap_invalidated = len(doomed)
        self.last_swap_reason = "selective"
        return dropped

    def disarm(self) -> None:
        """Installed models the cache can't reason about (non-UR engines,
        multi-model bundles): serve uncached."""
        with self._lock:
            n = len(self._data)
            self._data.clear()
            self._model = None
        if n:
            _M_INVAL.inc(n, reason="disabled")
        _M_ENTRIES.set(0)

    def clear(self) -> None:
        """Test/bench helper: drop entries AND the armed model."""
        self.disarm()

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)


_CACHE = ResponseCache()


def get_cache() -> ResponseCache:
    return _CACHE
