"""Serving-side subsystems that sit ABOVE one engine's predict math.

``response_cache`` — the provenance-invalidated top-k response cache:
whole-answer memoization across generation swaps, keyed on everything a
response depends on and selectively invalidated by the fold engine's
changed-set provenance (see response_cache module docstring for the
exactness argument).
"""
