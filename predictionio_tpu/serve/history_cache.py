"""Per-worker user-history read cache with event-append invalidation.

The serve tail's residual cost on a RESPONSE-cache hit is the history
read itself: the response cache (serve/response_cache) keys on a
fingerprint of the user's live history, so every lookup still walks
``LEventStore.find_by_entity`` per event type (~0.5 ms) before it can
even probe.  This module memoizes that read — the raw
``target_entity_id`` strings per (app, entity, event type, limit), a
value independent of any model generation — and invalidates it on the
event-store mutations this process performs (the listener bus in
``storage.base``, notified by every event backend):

- an append for entity E bumps E's version, so only E's entries re-read;
- an event delete, channel remove, or TTL trim (entities unknown) bumps
  the global epoch, flushing everything.

The (epoch, version) token is captured BEFORE the underlying read: an
append racing the read can only make a fresh entry look stale (one
wasted re-read), never let a stale entry look fresh.

Scope: invalidation is per-worker (in-process), exactly as the storage
listener bus is.  In topologies where another process appends to the
same store (multi-host sharedfs ingest beside this worker), disable
with ``PIO_HISTORY_CACHE=off`` — the always-fresh oracle the parity
test compares against.

Knobs: ``PIO_HISTORY_CACHE`` (on|off, default on; re-read per lookup),
``PIO_HISTORY_CACHE_MAX`` (entries, default 4096).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

from predictionio_tpu.models.common import LRUCache
from predictionio_tpu.obs import metrics as _obs_metrics
from predictionio_tpu.storage import base as _storage_base

_REG = _obs_metrics.get_registry()
_M_LOOKUP = _REG.counter(
    "pio_history_cache_total",
    "User-history cache lookups by outcome: hit (served from cache), "
    "miss (cold key, read and filled), stale (entry invalidated by an "
    "append/epoch bump, re-read), bypass (PIO_HISTORY_CACHE=off or the "
    "read was uncacheable)")
_M_ENTRIES = _REG.gauge(
    "pio_history_cache_entries",
    "Resident user-history cache entries in this worker")

# versions dict safety valve: past this many distinct entities, reset by
# bumping the epoch (correct — everything re-reads once)
_MAX_VERSIONS = 65536


def _enabled() -> bool:
    return os.environ.get("PIO_HISTORY_CACHE", "on").strip().lower() not in (
        "off", "0", "false", "no")


class HistoryCache:
    """Bounded LRU of per-entity history reads; see module docstring."""

    def __init__(self, max_entries: Optional[int] = None):
        if max_entries is None:
            max_entries = int(os.environ.get("PIO_HISTORY_CACHE_MAX", 4096))
        self._lru = LRUCache(max_entries)
        self._lock = threading.Lock()
        self._versions: Dict[Tuple[str, str], int] = {}
        self._epoch = 0

    # -- invalidation (storage append-listener bus) --------------------------

    def on_mutation(self, entities: Optional[List[tuple]]) -> None:
        """Listener for ``storage.base.add_append_listener``:
        per-entity version bumps, or a full flush when ``entities`` is
        None (mutation whose entities are unknown)."""
        with self._lock:
            if entities is None:
                self._epoch += 1
                self._versions.clear()
                self._lru.clear()
            else:
                if len(self._versions) + len(entities) > _MAX_VERSIONS:
                    self._epoch += 1
                    self._versions.clear()
                for ent in entities:
                    self._versions[ent] = self._versions.get(ent, 0) + 1
        _M_ENTRIES.set(len(self._lru))

    # -- lookup ---------------------------------------------------------------

    def _token(self, ent: Tuple[str, str]) -> Tuple[int, int]:
        with self._lock:
            return self._epoch, self._versions.get(ent, 0)

    def user_history_targets(self, app_name: str, entity_type: str,
                             entity_id: str, event_name: str,
                             limit: Optional[int],
                             channel_name: Optional[str] = None
                             ) -> Tuple[str, ...]:
        """Raw ``target_entity_id`` strings of the entity's latest
        ``limit`` events named ``event_name`` — exactly what
        ``find_by_entity`` returns, minus the per-model id mapping that
        keeps this value cacheable across generations."""
        if not _enabled():
            _M_LOOKUP.inc(outcome="bypass")
            return self._fetch(app_name, entity_type, entity_id,
                               event_name, limit, channel_name)[0]
        key = (app_name, channel_name, entity_type, entity_id,
               event_name, limit)
        token = self._token((entity_type, entity_id))
        entry = self._lru.get(key, count=False)
        if entry is not None and entry[0] == token:
            _M_LOOKUP.inc(outcome="hit")
            return entry[1]
        value, cacheable = self._fetch(app_name, entity_type, entity_id,
                                       event_name, limit, channel_name)
        if cacheable:
            self._lru.put(key, (token, value))
            _M_ENTRIES.set(len(self._lru))
            _M_LOOKUP.inc(outcome="stale" if entry is not None else "miss")
        else:
            _M_LOOKUP.inc(outcome="bypass")
        return value

    @staticmethod
    def _fetch(app_name: str, entity_type: str, entity_id: str,
               event_name: str, limit: Optional[int],
               channel_name: Optional[str]
               ) -> Tuple[Tuple[str, ...], bool]:
        from predictionio_tpu.store.event_store import LEventStore

        try:
            events = LEventStore.find_by_entity(
                app_name, entity_type, entity_id,
                channel_name=channel_name, event_names=[event_name],
                limit=limit)
        except ValueError:
            # app/channel unresolved — the oracle treats this as an empty
            # history; don't cache (the app may be created next tick)
            return (), False
        return tuple(e.target_entity_id for e in events
                     if e.target_entity_id is not None), True

    def reset_for_tests(self) -> None:
        with self._lock:
            self._epoch = 0
            self._versions.clear()
            self._lru.clear()
        _M_ENTRIES.set(0)


_CACHE = HistoryCache()
_storage_base.add_append_listener(_CACHE.on_mutation)


def get_cache() -> HistoryCache:
    return _CACHE


def user_history_targets(app_name: str, entity_type: str, entity_id: str,
                         event_name: str, limit: Optional[int],
                         channel_name: Optional[str] = None
                         ) -> Tuple[str, ...]:
    return _CACHE.user_history_targets(app_name, entity_type, entity_id,
                                       event_name, limit, channel_name)
