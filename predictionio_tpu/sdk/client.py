"""Python SDK — clients for the Event Server and Query Server REST APIs.

Reference: the PredictionIO-Python-SDK repo (EventClient / EngineClient;
SURVEY.md §2 'SDKs' — separate repos speaking the same REST wire format).
stdlib-only so it is usable outside this package's environment.
"""

from __future__ import annotations

import datetime as _dt
import http.client
import json
import threading
import time
import urllib.parse
from typing import Any, Dict, List, Optional, Sequence


class PIOError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message


class _Conn:
    """One persistent keep-alive connection per client instance.

    Event traffic is many small requests; a fresh TCP connect per event
    (the old urllib path) caps a client at ~1.2k events/s against a local
    server, while connection reuse measures ~4-10k/s.  Connections are
    PER-THREAD (threading.local), so a client shared across N worker
    threads issues N parallel keep-alive connections instead of
    serializing on one socket.  Reconnects transparently once per request
    only when the request provably never reached the server."""

    def __init__(self, base_url: str, timeout: float):
        u = urllib.parse.urlsplit(base_url)
        if u.scheme == "https":
            self._make = lambda: http.client.HTTPSConnection(
                u.hostname, u.port or 443, timeout=timeout)
        else:
            self._make = lambda: http.client.HTTPConnection(
                u.hostname, u.port or 80, timeout=timeout)
        self.prefix = u.path.rstrip("/")
        self._tl = threading.local()

    def request(self, method: str, path_qs: str, body: Any = None) -> Any:
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        tl = self._tl
        # a long-idle keep-alive socket may have been reaped by the
        # server; reconnecting up front keeps the no-retry-after-send
        # rule below from surfacing errors for that routine case
        if (getattr(tl, "conn", None) is not None
                and time.monotonic() - tl.last_use > 30.0):
            tl.conn.close()
            tl.conn = None
        tl.last_use = time.monotonic()
        for attempt in (0, 1):
            if getattr(tl, "conn", None) is None:
                tl.conn = self._make()
            sent = False
            try:
                tl.conn.request(
                    method, self.prefix + path_qs, data, headers)
                sent = True
                resp = tl.conn.getresponse()
                payload = resp.read()
                break
            except Exception as e:
                # any failure leaves http.client's state machine
                # unusable — always drop the socket so the NEXT call
                # starts clean (a kept-but-wedged connection raises
                # CannotSendRequest forever)
                tl.conn.close()
                tl.conn = None
                # retry once, but only when the request provably did
                # not reach the server: connection refused, or the
                # send itself failed (Content-Length framing means a
                # partially-received request is never processed).
                # A failure AFTER the send may mean the server already
                # processed a non-idempotent POST — re-sending would
                # silently duplicate the event, so surface it instead.
                retriable = isinstance(e, (
                    ConnectionRefusedError, ConnectionResetError,
                    BrokenPipeError, http.client.RemoteDisconnected,
                )) and (not sent or method in ("GET", "DELETE"))
                if attempt or not retriable:
                    raise
        if resp.status >= 400:
            try:
                message = json.loads(payload).get("message", "")
            except Exception:
                message = resp.reason
            raise PIOError(resp.status, message)
        return json.loads(payload) if payload else None


class EventClient:
    """Client for the Event Server (reference: EventClient in the SDKs)."""

    def __init__(self, access_key: str, url: str = "http://localhost:7070",
                 channel: Optional[str] = None, timeout: float = 10.0):
        self.access_key = access_key
        self.channel = channel
        self.timeout = timeout
        self._conn = _Conn(url, timeout)

    def _qs(self) -> str:
        params = {"accessKey": self.access_key}
        if self.channel:
            params["channel"] = self.channel
        return urllib.parse.urlencode(params)

    def create_event(
        self,
        event: str,
        entity_type: str,
        entity_id: str,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        properties: Optional[Dict[str, Any]] = None,
        event_time: Optional[_dt.datetime] = None,
    ) -> str:
        body: Dict[str, Any] = {
            "event": event, "entityType": entity_type, "entityId": str(entity_id),
        }
        if target_entity_type:
            body["targetEntityType"] = target_entity_type
        if target_entity_id:
            body["targetEntityId"] = str(target_entity_id)
        if properties:
            body["properties"] = properties
        if event_time:
            body["eventTime"] = event_time.isoformat()
        out = self._conn.request("POST", f"/events.json?{self._qs()}", body)
        return out["eventId"]

    def create_events(self, events: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
        return self._conn.request("POST", f"/batch/events.json?{self._qs()}",
                                  list(events))

    # convenience wrappers matching the reference SDK surface
    def set_user(self, uid: str, properties: Optional[Dict] = None) -> str:
        return self.create_event("$set", "user", uid, properties=properties or {})

    def set_item(self, iid: str, properties: Optional[Dict] = None) -> str:
        return self.create_event("$set", "item", iid, properties=properties or {})

    def record_user_action_on_item(
        self, action: str, uid: str, iid: str, properties: Optional[Dict] = None
    ) -> str:
        return self.create_event(action, "user", uid, "item", iid, properties)

    def get_event(self, event_id: str) -> Dict[str, Any]:
        return self._conn.request("GET", f"/events/{event_id}.json?{self._qs()}")

    def delete_event(self, event_id: str) -> None:
        self._conn.request("DELETE", f"/events/{event_id}.json?{self._qs()}")

    def find_events(self, **filters: str) -> List[Dict[str, Any]]:
        params = {"accessKey": self.access_key, **filters}
        if self.channel:
            params["channel"] = self.channel
        qs = urllib.parse.urlencode(params)
        return self._conn.request("GET", f"/events.json?{qs}")


class EngineClient:
    """Client for a deployed engine (reference: EngineClient in the SDKs)."""

    def __init__(self, url: str = "http://localhost:8000", timeout: float = 10.0):
        self.timeout = timeout
        self._conn = _Conn(url, timeout)

    def send_query(self, query: Dict[str, Any]) -> Dict[str, Any]:
        return self._conn.request("POST", "/queries.json", query)
