"""Python SDK — clients for the Event Server and Query Server REST APIs.

Reference: the PredictionIO-Python-SDK repo (EventClient / EngineClient;
SURVEY.md §2 'SDKs' — separate repos speaking the same REST wire format).
stdlib-only so it is usable outside this package's environment.
"""

from __future__ import annotations

import datetime as _dt
import http.client
import itertools
import json
import os
import random
import threading
import time
import urllib.parse
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

# per-request X-Request-ID minting: unique across processes and across
# client instances in one process, cheap (no uuid4 per request).  The
# server echoes the id and keys its flight-recorder trace on it, so a
# client-side failure is joinable against the server's /traces/<rid>.json
_RID_SEED = f"sdk-{os.getpid():x}-{os.urandom(3).hex()}"
_RID_COUNTER = itertools.count(1)


def _mint_rid() -> str:
    return f"{_RID_SEED}-{next(_RID_COUNTER):x}"


class PIOError(Exception):
    def __init__(self, status: int, message: str,
                 request_id: Optional[str] = None):
        tail = f" [request-id {request_id}]" if request_id else ""
        super().__init__(f"HTTP {status}: {message}{tail}")
        self.status = status
        self.message = message
        self.request_id = request_id


def _backoff_delays(window: float):
    """Bounded exponential backoff with full jitter for reconnects: first
    retry immediate (the dropped-idle-keep-alive case), then ~50 ms
    doubling to a 1 s cap, randomized to 50–100% of the step so a client
    fleet doesn't reconnect in lockstep, until ``window`` seconds have
    elapsed.  Yields the sleep before each retry attempt (0 = retry now);
    the caller stops iterating on success."""
    deadline = time.monotonic() + max(0.0, window)
    yield 0.0
    delay = 0.05
    while time.monotonic() < deadline:
        yield min(delay, max(0.0, deadline - time.monotonic())) * (
            0.5 + random.random() * 0.5)
        delay = min(delay * 2, 1.0)


class _Conn:
    """One persistent keep-alive connection per client instance.

    Event traffic is many small requests; a fresh TCP connect per event
    (the old urllib path) caps a client at ~1.2k events/s against a local
    server, while connection reuse measures ~4-10k/s.  Connections are
    PER-THREAD (threading.local), so a client shared across N worker
    threads issues N parallel keep-alive connections instead of
    serializing on one socket.

    Retry contract: a request that provably never reached the server
    (connection refused, or the send itself failed) is retried with
    bounded exponential backoff + jitter for up to ``retry_window``
    seconds — long enough to ride through an event-store failover
    promotion window instead of erroring on the first refused connect.
    A failure AFTER the send is NEVER retried for non-idempotent methods
    (the server may have committed the event; re-sending would silently
    duplicate it) — the backoff changes nothing about that at-least-once
    contract, it only retries the provably-unprocessed cases.  Callers
    that must retry post-send failures should supply client eventIds so
    the retry is idempotent at read time."""

    def __init__(self, base_url: str, timeout: float,
                 retry_window: float = 8.0):
        u = urllib.parse.urlsplit(base_url)
        if u.scheme == "https":
            self._make = lambda: http.client.HTTPSConnection(
                u.hostname, u.port or 443, timeout=timeout)
        else:
            self._make = lambda: http.client.HTTPConnection(
                u.hostname, u.port or 80, timeout=timeout)
        self.prefix = u.path.rstrip("/")
        self.retry_window = retry_window
        self._tl = threading.local()

    def request(self, method: str, path_qs: str, body: Any = None) -> Any:
        data = json.dumps(body).encode() if body is not None else None
        rid = _mint_rid()
        headers = {"Content-Type": "application/json",
                   "X-Request-ID": rid}
        tl = self._tl
        # a long-idle keep-alive socket may have been reaped by the
        # server; reconnecting up front keeps the no-retry-after-send
        # rule below from surfacing errors for that routine case
        if (getattr(tl, "conn", None) is not None
                and time.monotonic() - tl.last_use > 30.0):
            tl.conn.close()
            tl.conn = None
        tl.last_use = time.monotonic()
        delays = _backoff_delays(self.retry_window)
        while True:
            if getattr(tl, "conn", None) is None:
                tl.conn = self._make()
            sent = False
            try:
                tl.conn.request(
                    method, self.prefix + path_qs, data, headers)
                sent = True
                resp = tl.conn.getresponse()
                payload = resp.read()
                break
            except Exception as e:
                # any failure leaves http.client's state machine
                # unusable — always drop the socket so the NEXT call
                # starts clean (a kept-but-wedged connection raises
                # CannotSendRequest forever)
                tl.conn.close()
                tl.conn = None
                # retry with backoff, but only when the request provably
                # did not reach the server: connection refused, or the
                # send itself failed (Content-Length framing means a
                # partially-received request is never processed).
                # A failure AFTER the send may mean the server already
                # processed a non-idempotent POST — re-sending would
                # silently duplicate the event, so surface it instead.
                retriable = isinstance(e, (
                    ConnectionRefusedError, ConnectionResetError,
                    BrokenPipeError, http.client.RemoteDisconnected,
                )) and (not sent or method in ("GET", "DELETE"))
                sleep = next(delays, None) if retriable else None
                if sleep is None:
                    # transport failures keep their type (callers and the
                    # retry contract depend on it); the request id rides
                    # along as an attribute for log joining
                    e.request_id = rid
                    raise
                if sleep:
                    time.sleep(sleep)
        if resp.status >= 400:
            try:
                message = json.loads(payload).get("message", "")
            except Exception:
                message = resp.reason
            raise PIOError(resp.status, message, request_id=rid)
        return json.loads(payload) if payload else None


def _event_body(
    event: str,
    entity_type: str,
    entity_id: str,
    target_entity_type: Optional[str] = None,
    target_entity_id: Optional[str] = None,
    properties: Optional[Dict[str, Any]] = None,
    event_time: Optional[_dt.datetime] = None,
) -> Dict[str, Any]:
    """One wire-format builder shared by the serial client and the
    pipeline, so the two paths can never diverge."""
    body: Dict[str, Any] = {
        "event": event, "entityType": entity_type, "entityId": str(entity_id),
    }
    if target_entity_type:
        body["targetEntityType"] = target_entity_type
    if target_entity_id:
        body["targetEntityId"] = str(target_entity_id)
    if properties:
        body["properties"] = properties
    if event_time:
        body["eventTime"] = event_time.isoformat()
    return body


class AsyncResult:
    """Handle for a pipelined request (reference: the official Python
    SDK's AsyncRequest/AsyncResponse pair around ``acreate_event``).

    ``result()`` drains the pipeline until this request's response has
    been read, then returns the parsed body (raising PIOError for HTTP
    errors) — responses arrive strictly in request order (HTTP/1.1)."""

    __slots__ = ("_pipe", "_value", "_error", "done", "request_id")

    def __init__(self, pipe: "EventPipeline", request_id: str = ""):
        self._pipe = pipe
        self._value: Any = None
        self._error: Optional[Exception] = None
        self.done = False
        # the X-Request-ID this request was sent with: echoed by the
        # server, keyed into its flight recorder, and carried in any
        # PIOError this handle raises
        self.request_id = request_id

    def result(self) -> Any:
        if not self.done:
            self._pipe.drain_until(self)
        if self._error is not None:
            raise self._error
        return self._value


class _Pipeline:
    """HTTP/1.1-pipelined requests over one keep-alive socket — the
    transport shared by ``EventPipeline`` (ingestion) and
    ``QueryPipeline`` (serving).

    Why: a serial client pays one full round trip per request — request
    construction, send, *wait*, read — and measures well under half of
    what the server sustains on the same box.  Pipelining keeps up to
    ``depth`` requests in flight on the wire: requests are written
    back-to-back into a userspace buffer (flushed at ``_SEND_BUF``
    bytes), and responses — strictly ordered per HTTP/1.1 — are read in
    bulk when the in-flight cap is reached.  ``depth`` bounds the
    responses the server can have queued toward us, so neither side's
    socket buffer can fill and deadlock the pair.  Against the
    event-loop front end, pipelined queries are exactly what feeds the
    cross-request micro-batcher: every request in flight on this socket
    can coalesce into one ``serve_batch_predict`` pass server-side.

    stdlib-only, single-threaded.

    Failure semantics — at-least-once ambiguity: if the server signals
    ``Connection: close`` (or the socket dies) while requests are still
    in flight, every outstanding handle fails with PIOError — but the
    server may already have COMMITTED some of those requests before
    closing; the close only guarantees their acknowledgements will never
    arrive.  A caller that retries failed event handles can therefore
    duplicate events unless it supplies its own ``eventId`` per event
    (the server stores a client-supplied id verbatim, making the retry
    idempotent at read time).  After a server-signaled close the
    pipeline refuses new sends immediately instead of writing requests
    the server will never read.
    """

    _SEND_BUF = 32 * 1024

    def __init__(self, base_url: str, depth: int = 128,
                 timeout: float = 10.0, qs: str = "",
                 retry_window: float = 8.0):
        import socket as _socket

        u = urllib.parse.urlsplit(base_url)

        def connect(port):
            # the pipeline's one TCP connect gets the same bounded
            # backoff-with-jitter as the serial client: a refused connect
            # during a failover promotion window is retried for up to
            # ``retry_window`` seconds before surfacing.  (Nothing has
            # been sent yet, so this never interacts with the
            # no-retry-after-send / at-least-once contract below.)
            delays = _backoff_delays(retry_window)
            while True:
                try:
                    return _socket.create_connection(
                        (u.hostname, port), timeout=timeout)
                except ConnectionRefusedError:
                    sleep = next(delays, None)
                    if sleep is None:
                        raise
                    if sleep:
                        time.sleep(sleep)

        if u.scheme == "https":
            import ssl

            raw = connect(u.port or 443)
            self._sock = ssl.create_default_context().wrap_socket(
                raw, server_hostname=u.hostname)
        else:
            self._sock = connect(u.port or 80)
        self._sock.setsockopt(
            _socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        self._rfile = self._sock.makefile("rb")
        self._host = (u.hostname or "localhost").encode("ascii")
        self._prefix = u.path.rstrip("/")
        self._qs = qs
        # the deadlock-avoidance invariant (see docstring) only holds if
        # queued responses stay well under a default socket buffer
        # (~128 KiB): clamp depth so ~100 B/response can't fill it
        self._depth = max(1, min(depth, 512))
        self._buf = bytearray()
        self._pending: "deque[AsyncResult]" = deque()
        self._closed = False

    # -- request side -------------------------------------------------------

    def _send(self, method: str, path_qs: str, body: Any) -> AsyncResult:
        if self._closed:
            raise PIOError(0, "pipeline is closed")
        data = json.dumps(body).encode()
        rid = _mint_rid()
        self._buf += (
            b"%s %s HTTP/1.1\r\nHost: %s\r\nX-Request-ID: %s\r\n"
            b"Content-Type: application/json\r\nContent-Length: %d\r\n\r\n"
            % (method.encode(), (self._prefix + path_qs).encode(),
               self._host, rid.encode(), len(data))
        ) + data
        h = AsyncResult(self, request_id=rid)
        self._pending.append(h)
        if len(self._buf) >= self._SEND_BUF:
            self._flush_buf()
        if len(self._pending) >= self._depth:
            # drain half: keeps the wire busy while bounding in-flight
            self._drain(len(self._pending) - self._depth // 2)
        return h

    # -- response side ------------------------------------------------------

    def _read_response(self) -> tuple:
        """Returns (status, payload, server_closing).  ``server_closing``
        is True when the response carries ``Connection: close`` — this is
        the LAST response the server will send on this socket, so any
        requests already pipelined after it will never be answered."""
        line = self._rfile.readline(65537)
        if not line:
            raise PIOError(0, "server closed the pipelined connection")
        parts = line.decode("latin-1").split(" ", 2)
        status = int(parts[1])
        length = 0
        closing = False
        while True:
            h = self._rfile.readline(65537)
            if h in (b"\r\n", b"\n", b""):
                break
            name, _, value = h.decode("latin-1").partition(":")
            name = name.strip().lower()
            if name == "content-length":
                length = int(value.strip())
            elif name == "connection":
                closing = value.strip().lower() == "close"
        payload = self._rfile.read(length) if length else b""
        return status, payload, closing

    def _release_socket(self) -> None:
        self._closed = True
        try:
            self._rfile.close()
            self._sock.close()
        except OSError:
            pass

    def _abort(self, err: Exception) -> None:
        """Fail every outstanding handle and release the socket — after
        this, pending ``result()`` calls raise ``err`` instead of
        touching the dead/closed stream."""
        for h in self._pending:
            h.done = True
            # PIOErrors are re-minted per handle so each carries ITS
            # request id (the joinable key against server-side traces)
            h._error = (PIOError(err.status, err.message,
                                 request_id=h.request_id)
                        if isinstance(err, PIOError) else err)
        self._pending.clear()
        del self._buf[:]
        self._release_socket()

    def _flush_buf(self) -> None:
        """Send the userspace buffer; a send-side failure gets the same
        clean-abort treatment as a read-side one (fail every pending
        handle, release the socket) instead of leaving the pipeline
        half-open."""
        try:
            self._sock.sendall(self._buf)
            del self._buf[:]
        except Exception as e:
            self._abort(e)
            raise

    def _drain(self, n: int) -> None:
        if self._buf:
            self._flush_buf()
        for _ in range(min(n, len(self._pending))):
            h = self._pending.popleft()
            h.done = True
            try:
                status, payload, closing = self._read_response()
            except Exception as e:
                h._error = e
                self._abort(e)   # the stream is dead: fail the rest too
                raise
            if status >= 400:
                try:
                    message = json.loads(payload).get("message", "")
                except Exception:
                    message = ""
                h._error = PIOError(status, message,
                                    request_id=h.request_id)
            else:
                h._value = json.loads(payload) if payload else None
            if closing:
                # the server signaled Connection: close — THIS response is
                # the last one it will send.  Fail every handle already
                # pipelined after it (their requests may or may not have
                # been committed before the close; see the class docstring)
                # and refuse new sends, instead of surfacing the same
                # opaque 'server closed' error for everything later.
                self._abort(PIOError(
                    0, "server signaled Connection: close mid-pipeline; "
                       "this request was sent but will never be "
                       "acknowledged (it may or may not have been "
                       "committed — supply client eventIds to retry "
                       "idempotently)"))
                return

    def drain_until(self, handle: AsyncResult) -> None:
        try:
            idx = self._pending.index(handle)
        except ValueError:
            return      # already drained
        self._drain(idx + 1)

    def flush(self) -> None:
        """Send everything buffered and read every outstanding response."""
        self._drain(len(self._pending))

    def close(self) -> None:
        if self._closed:
            return
        try:
            self.flush()
        finally:
            self._release_socket()

    def __enter__(self) -> "_Pipeline":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # on an exception unwind don't force a flush (the stream may be
        # mid-error); fail anything still pending so a later result()
        # raises cleanly instead of draining into a closed socket
        if exc_type is not None:
            self._abort(PIOError(
                0, "pipeline aborted before this response was read"))
        else:
            self.close()


class EventPipeline(_Pipeline):
    """Pipelined single-event ingestion (reference: the official Python
    SDK's ``acreate_event`` path).  Use via ``EventClient.pipeline()``:

        with client.pipeline() as p:
            handles = [p.create_event(...) for _ in events]
        ids = [h.result()["eventId"] for h in handles]   # all done here
    """

    def __init__(self, client: "EventClient", depth: int = 128,
                 timeout: float = 10.0):
        super().__init__(client._base_url, depth=depth, timeout=timeout,
                         qs=client._qs(),
                         retry_window=client.retry_window)

    def create_event(
        self,
        event: str,
        entity_type: str,
        entity_id: str,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        properties: Optional[Dict[str, Any]] = None,
        event_time: Optional[_dt.datetime] = None,
    ) -> AsyncResult:
        body = _event_body(event, entity_type, entity_id,
                           target_entity_type, target_entity_id,
                           properties, event_time)
        return self._send("POST", f"/events.json?{self._qs}", body)

    def record_user_action_on_item(
        self, action: str, uid: str, iid: str,
        properties: Optional[Dict] = None,
    ) -> AsyncResult:
        return self.create_event(action, "user", uid, "item", iid, properties)


class QueryPipeline(_Pipeline):
    """Pipelined /queries.json against a deployed query server.  Keeps
    up to ``depth`` queries in flight on one keep-alive socket; the
    event-loop server answers them strictly in order, and concurrently
    in-flight queries coalesce through the server's cross-request
    micro-batcher when batching is enabled.  Use via
    ``EngineClient.pipeline()``:

        with engine_client.pipeline(depth=32) as p:
            handles = [p.send_query({"user": u, "num": 10}) for u in users]
        predictions = [h.result() for h in handles]
    """

    def __init__(self, client: "EngineClient", depth: int = 64,
                 timeout: float = 10.0):
        super().__init__(client._base_url, depth=depth, timeout=timeout,
                         retry_window=client.retry_window)

    def send_query(self, query: Dict[str, Any]) -> AsyncResult:
        return self._send("POST", "/queries.json", query)


class EventClient:
    """Client for the Event Server (reference: EventClient in the SDKs)."""

    def __init__(self, access_key: str, url: str = "http://localhost:7070",
                 channel: Optional[str] = None, timeout: float = 10.0,
                 retry_window: float = 8.0):
        self.access_key = access_key
        self.channel = channel
        self.timeout = timeout
        # how long connection-refused requests back off before surfacing
        # (failover promotion windows; 0 = fail fast after one retry)
        self.retry_window = retry_window
        self._base_url = url
        self._conn = _Conn(url, timeout, retry_window=retry_window)

    def pipeline(self, depth: int = 128) -> EventPipeline:
        """Open a pipelined single-event ingestion session (see
        EventPipeline).  Use when pushing many events whose ids you don't
        need synchronously — ~4x the serial keep-alive rate measured
        against a local event server."""
        return EventPipeline(self, depth=depth, timeout=self.timeout)

    def _qs(self) -> str:
        params = {"accessKey": self.access_key}
        if self.channel:
            params["channel"] = self.channel
        return urllib.parse.urlencode(params)

    def create_event(
        self,
        event: str,
        entity_type: str,
        entity_id: str,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        properties: Optional[Dict[str, Any]] = None,
        event_time: Optional[_dt.datetime] = None,
    ) -> str:
        body = _event_body(event, entity_type, entity_id,
                           target_entity_type, target_entity_id,
                           properties, event_time)
        out = self._conn.request("POST", f"/events.json?{self._qs()}", body)
        return out["eventId"]

    def create_events(self, events: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
        return self._conn.request("POST", f"/batch/events.json?{self._qs()}",
                                  list(events))

    # convenience wrappers matching the reference SDK surface
    def set_user(self, uid: str, properties: Optional[Dict] = None) -> str:
        return self.create_event("$set", "user", uid, properties=properties or {})

    def set_item(self, iid: str, properties: Optional[Dict] = None) -> str:
        return self.create_event("$set", "item", iid, properties=properties or {})

    def record_user_action_on_item(
        self, action: str, uid: str, iid: str, properties: Optional[Dict] = None
    ) -> str:
        return self.create_event(action, "user", uid, "item", iid, properties)

    def get_event(self, event_id: str) -> Dict[str, Any]:
        return self._conn.request("GET", f"/events/{event_id}.json?{self._qs()}")

    def delete_event(self, event_id: str) -> None:
        self._conn.request("DELETE", f"/events/{event_id}.json?{self._qs()}")

    def find_events(self, **filters: str) -> List[Dict[str, Any]]:
        params = {"accessKey": self.access_key, **filters}
        if self.channel:
            params["channel"] = self.channel
        qs = urllib.parse.urlencode(params)
        return self._conn.request("GET", f"/events.json?{qs}")


class EngineClient:
    """Client for a deployed engine (reference: EngineClient in the SDKs)."""

    def __init__(self, url: str = "http://localhost:8000", timeout: float = 10.0,
                 retry_window: float = 8.0):
        self.timeout = timeout
        self.retry_window = retry_window
        self._base_url = url
        self._conn = _Conn(url, timeout, retry_window=retry_window)

    def send_query(self, query: Dict[str, Any]) -> Dict[str, Any]:
        return self._conn.request("POST", "/queries.json", query)

    def pipeline(self, depth: int = 64) -> QueryPipeline:
        """Open a pipelined query session (see QueryPipeline): many
        queries in flight on one keep-alive socket, answered in order —
        the client-side feed for the server's cross-request
        micro-batcher."""
        return QueryPipeline(self, depth=depth, timeout=self.timeout)

    def freshness(self) -> Dict[str, Any]:
        """The server's ``freshness`` document: live model generation,
        last hot-swap time, and — when the deployment hosts an embedded
        follow-trainer — its lag/outcome status.  Lets a client wait for
        an appended event to become visible by polling ``generation``
        instead of replaying queries.  Served in /stats.json and on
        GET / (the fallback keeps the contract alive under
        PIO_METRICS=off, where /stats.json answers 503)."""
        try:
            doc = self._conn.request("GET", "/stats.json")
        except PIOError:
            doc = self._conn.request("GET", "/")
        return doc.get("freshness", {}) if isinstance(doc, dict) else {}

    def model_generation(self) -> int:
        """Shortcut: the live model's generation counter (0 when the
        server predates the freshness contract)."""
        try:
            return int(self.freshness().get("generation") or 0)
        except (PIOError, ValueError):
            return 0

    def lineage(self, generation: Optional[int] = None) -> Dict[str, Any]:
        """Generation lineage from the deployment: the merged record
        index (``{"records": [...]}``), or one generation's freshness
        waterfall when ``generation`` is given — every stage from
        append-observed through first-serve, contributed by whichever
        processes ran them (cross-process merge).  Lets a client measure
        its own append→servable latency end to end."""
        if generation is None:
            return self._conn.request("GET", "/lineage.json")
        return self._conn.request("GET", f"/lineage/{int(generation)}.json")

    def healthz(self) -> Dict[str, Any]:
        """The deployment's SLO burn-rate verdicts (/healthz — always
        HTTP 200; the ``status`` field carries ok | warn | burning |
        no_data)."""
        return self._conn.request("GET", "/healthz")
