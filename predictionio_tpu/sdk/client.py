"""Python SDK — clients for the Event Server and Query Server REST APIs.

Reference: the PredictionIO-Python-SDK repo (EventClient / EngineClient;
SURVEY.md §2 'SDKs' — separate repos speaking the same REST wire format).
stdlib-only so it is usable outside this package's environment.
"""

from __future__ import annotations

import datetime as _dt
import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional, Sequence


class PIOError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message


def _request(method: str, url: str, body: Any = None, timeout: float = 10.0) -> Any:
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            payload = resp.read()
            return json.loads(payload) if payload else None
    except urllib.error.HTTPError as e:
        try:
            message = json.loads(e.read()).get("message", "")
        except Exception:
            message = e.reason
        raise PIOError(e.code, message) from None


class EventClient:
    """Client for the Event Server (reference: EventClient in the SDKs)."""

    def __init__(self, access_key: str, url: str = "http://localhost:7070",
                 channel: Optional[str] = None, timeout: float = 10.0):
        self.access_key = access_key
        self.base = url.rstrip("/")
        self.channel = channel
        self.timeout = timeout

    def _qs(self) -> str:
        params = {"accessKey": self.access_key}
        if self.channel:
            params["channel"] = self.channel
        return urllib.parse.urlencode(params)

    def create_event(
        self,
        event: str,
        entity_type: str,
        entity_id: str,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        properties: Optional[Dict[str, Any]] = None,
        event_time: Optional[_dt.datetime] = None,
    ) -> str:
        body: Dict[str, Any] = {
            "event": event, "entityType": entity_type, "entityId": str(entity_id),
        }
        if target_entity_type:
            body["targetEntityType"] = target_entity_type
        if target_entity_id:
            body["targetEntityId"] = str(target_entity_id)
        if properties:
            body["properties"] = properties
        if event_time:
            body["eventTime"] = event_time.isoformat()
        out = _request("POST", f"{self.base}/events.json?{self._qs()}", body, self.timeout)
        return out["eventId"]

    def create_events(self, events: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
        return _request("POST", f"{self.base}/batch/events.json?{self._qs()}",
                        list(events), self.timeout)

    # convenience wrappers matching the reference SDK surface
    def set_user(self, uid: str, properties: Optional[Dict] = None) -> str:
        return self.create_event("$set", "user", uid, properties=properties or {})

    def set_item(self, iid: str, properties: Optional[Dict] = None) -> str:
        return self.create_event("$set", "item", iid, properties=properties or {})

    def record_user_action_on_item(
        self, action: str, uid: str, iid: str, properties: Optional[Dict] = None
    ) -> str:
        return self.create_event(action, "user", uid, "item", iid, properties)

    def get_event(self, event_id: str) -> Dict[str, Any]:
        return _request("GET", f"{self.base}/events/{event_id}.json?{self._qs()}",
                        timeout=self.timeout)

    def delete_event(self, event_id: str) -> None:
        _request("DELETE", f"{self.base}/events/{event_id}.json?{self._qs()}",
                 timeout=self.timeout)

    def find_events(self, **filters: str) -> List[Dict[str, Any]]:
        params = {"accessKey": self.access_key, **filters}
        if self.channel:
            params["channel"] = self.channel
        qs = urllib.parse.urlencode(params)
        return _request("GET", f"{self.base}/events.json?{qs}", timeout=self.timeout)


class EngineClient:
    """Client for a deployed engine (reference: EngineClient in the SDKs)."""

    def __init__(self, url: str = "http://localhost:8000", timeout: float = 10.0):
        self.base = url.rstrip("/")
        self.timeout = timeout

    def send_query(self, query: Dict[str, Any]) -> Dict[str, Any]:
        return _request("POST", f"{self.base}/queries.json", query, self.timeout)
