from predictionio_tpu.sdk.client import (  # noqa: F401
    AsyncResult,
    EngineClient,
    EventClient,
    EventPipeline,
    PIOError,
    QueryPipeline,
)
