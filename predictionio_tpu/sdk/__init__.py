from predictionio_tpu.sdk.client import EngineClient, EventClient  # noqa: F401
