"""Streaming model freshness: resident follow-training + hot-swap serving.

PredictionIO's signature gap is event-append → *batch* retrain → redeploy
(PAPER.md §0: real-time event server, Spark batch train).  This package
closes it: :mod:`fold` maintains additive co-occurrence count state and
re-derives only what a delta actually changed, and :mod:`follow` is the
resident trainer (``pio train --follow`` daemon, or embedded in the query
server via ``pio deploy --follow``) that tails the event store from the
snapshot watermark and publishes fresh model generations via atomic
hot-swap.
"""

from predictionio_tpu.streaming.follow import FollowTrainer  # noqa: F401
from predictionio_tpu.streaming.plane import (  # noqa: F401
    ModelPlane,
    PlaneUnsupported,
    PlaneWatcher,
)
