"""Resident follow-trainer: tail the event store, fold, hot-swap.

The :class:`FollowTrainer` is the daemon behind ``pio train --follow``
and the embeddable updater behind ``pio deploy --follow SECS``.  Each
tick it:

1. tails the event store from its watermark (PR 3's ``scan_tail_from``
   delta protocol — only bytes past the per-segment watermark parse);
2. folds the delta into the live model (:mod:`streaming.fold` — additive
   CCO counts, affected-row re-LLR) or, when folding is unsupported for
   the engine/shape, re-trains through the normal (delta-staged) path;
3. publishes the new model generation: a COMPLETED EngineInstance +
   model blob in daemon mode (every ``--auto-reload`` deployment
   converges within its poll interval), and/or an in-process atomic
   hot-swap callback in embedded mode (the query server swaps its
   predictor under its lock — sub-second append→reflected latency);
4. persists its watermark (``follow.json`` next to the span journals),
   so a SIGKILL'd daemon restarts by re-reading exactly the covered
   prefix (``scan_events_up_to``) and folding only the unapplied suffix
   — no double-fold, no blind full retrain.

Consistency edges mirror ``_StagedCache``: any tombstone change or
log-shape mismatch (segment vanished/shrank/recreated) forces a full
restage; ``PIO_FOLLOW_MAX_LAG_EVENTS`` bounds how large a delta is
folded incrementally before a restage is the better deal.  Kill switch:
``PIO_FOLLOW=off`` idles the loop without tearing it down.
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import os
import threading
import time
import uuid
from pathlib import Path
from typing import Callable, Dict, List, Optional

from predictionio_tpu.obs import lineage as _lineage
from predictionio_tpu.obs import metrics as _obs_metrics
from predictionio_tpu.obs import tracing as _tracing
from predictionio_tpu.obs.metrics import LATENCY_BUCKETS
from predictionio_tpu.storage.locator import Storage, get_storage
from predictionio_tpu.streaming.fold import FoldUnsupported, URFoldState

log = logging.getLogger("pio.follow")

_REG = _obs_metrics.get_registry()
_M_FOLDS = _REG.counter(
    "pio_follow_folds_total",
    "Follow-trainer ticks by outcome: fold (incremental), retrain "
    "(full train through the delta-staged path), restage (tombstone/"
    "log-shape change or max-lag breach forced a full rebuild), idle "
    "(no new events), disabled (PIO_FOLLOW=off), error")
_M_FOLD_S = _REG.histogram(
    "pio_follow_fold_duration_seconds",
    "Wall time of one follow tick that published a generation, by "
    "mode: tail scan + fold/retrain + publish when synchronous; with "
    "the pipelined publisher, tail scan + fold only (emit/warm/publish "
    "run off-loop — see pio_follow_fold_phase_duration_seconds)",
    buckets=LATENCY_BUCKETS)
_M_LAG = _REG.gauge(
    "pio_follow_lag_events",
    "Unapplied events behind the live log at the last tick "
    "(0 after a successful fold — the freshness backlog)")
_M_PUBLISH_TS = _REG.gauge(
    "pio_follow_last_publish_timestamp_seconds",
    "Unix time of the last published model generation")
_M_GEN = _REG.gauge(
    "pio_model_generation",
    "Monotonic generation counter of the live model: bumped by every "
    "hot-swap (follow fold, auto-reload, manual /reload) — serving "
    "caches key on the model object this counts")
_M_STATE_BYTES = _REG.gauge(
    "pio_follow_state_bytes",
    "Resident fold-state bytes (sorted-COO counts + accumulated batch "
    "+ pair sets + popularity inputs + indicator tables) — what "
    "PIO_FOLLOW_STATE_BYTES bounds; 0 in retrain mode.  With the "
    "sparse state this grows with the EVENT count, not catalog**2")
_M_STATE_MODE = _REG.gauge(
    "pio_follow_state_mode",
    "Fold-state representation in use: 1 on the active mode label "
    "(sparse | dense | retrain), 0 on the others")
_M_PHASE_S = _REG.histogram(
    "pio_follow_fold_phase_duration_seconds",
    "Wall time of one fold tick's phases: apply (delta application + "
    "marginals), rellr (LLR + top-k recompute incl. the pruned "
    "certificate), emit (URModel construction + incremental serving-"
    "state carry), warm (embedded serving-bundle build + warm + swap), "
    "publish (durable instance/model persistence + watermark).  With "
    "the pipelined publisher, emit/warm/publish overlap the NEXT "
    "tick's apply/rellr",
    buckets=LATENCY_BUCKETS)


def follow_pipeline_enabled() -> bool:
    """``PIO_FOLLOW_PIPELINE=off`` serializes fold+emit+warm+publish on
    the loop thread (the PR-8..11 behavior).  Default on: ``run_forever``
    hands emit+publish to a dedicated publisher thread so the follower
    folds the next delta while the previous generation warms — direct
    ``tick()`` calls (tests, scripts) stay synchronous either way."""
    return os.environ.get("PIO_FOLLOW_PIPELINE", "").lower() not in (
        "off", "0", "false")


def follow_interval_s() -> float:
    """PIO_FOLLOW_INTERVAL_S: seconds between follow ticks (default 2)."""
    try:
        return max(float(os.environ.get("PIO_FOLLOW_INTERVAL_S", "2.0")),
                   0.05)
    except ValueError:
        return 2.0


def follow_max_lag_events() -> int:
    """PIO_FOLLOW_MAX_LAG_EVENTS: a delta larger than this restages
    instead of folding incrementally (default 1M — a backlog that big
    means the follower was down; a fresh bootstrap amortizes better
    than one giant fold)."""
    try:
        return max(int(os.environ.get("PIO_FOLLOW_MAX_LAG_EVENTS",
                                      "1000000")), 1)
    except ValueError:
        return 1_000_000


def follow_enabled() -> bool:
    """PIO_FOLLOW=off idles a running follower without tearing it down."""
    return os.environ.get("PIO_FOLLOW", "").lower() not in (
        "off", "0", "false")


def follow_checkpoint_interval_s() -> float:
    """PIO_FOLLOW_CHECKPOINT_S: minimum seconds between fold-state
    checkpoints (default 60; <= 0 disables checkpointing).  A restart
    re-folds from the newest checkpoint's watermark, so the interval
    bounds the restart's re-fold work — the covered-prefix reparse only
    happens when no valid checkpoint exists."""
    try:
        return float(os.environ.get("PIO_FOLLOW_CHECKPOINT_S", "60"))
    except ValueError:
        return 60.0


def follow_state_path(storage: Storage, engine_id: str,
                      variant: str) -> Optional[Path]:
    """Where the follower persists its watermark — next to the span
    journals under the METADATA localfs/sharedfs path; None (in-memory
    only) for other backends."""
    try:
        src = storage.config.sources[storage.config.repositories["METADATA"]]
    except (KeyError, AttributeError):
        return None
    if src.get("type") not in ("localfs", "sharedfs") or not src.get("path"):
        return None
    safe = "".join(c if c.isalnum() or c in "-_." else "_"
                   for c in f"{engine_id}-{variant}")
    return Path(src["path"]) / "follow" / f"{safe}.json"


class FollowTrainer:
    """Resident trainer: tail → fold → hot-swap, forever.

    ``on_publish(models, info)`` is the embedded hot-swap hook (the
    query server passes its ``swap_models``); ``persist=True`` records a
    COMPLETED EngineInstance + model blob per generation so detached
    deployments converge via ``--auto-reload``.
    """

    def __init__(self, engine, engine_params, engine_id: str,
                 engine_version: str = "1", engine_variant: str = "default",
                 engine_factory: str = "",
                 storage: Optional[Storage] = None,
                 interval: Optional[float] = None,
                 on_publish: Optional[Callable] = None,
                 persist: bool = True,
                 max_lag: Optional[int] = None):
        self.engine = engine
        self.engine_params = engine_params
        self.engine_id = engine_id
        self.engine_version = engine_version
        self.engine_variant = engine_variant
        self.engine_factory = engine_factory or engine_id
        self.storage = storage or get_storage()
        self.interval = float(interval) if interval else follow_interval_s()
        self.on_publish = on_publish
        self.persist = persist
        self.max_lag = max_lag
        self.generation = 0
        self.instance_id: Optional[str] = None
        self.last_outcome = "init"
        self.last_fold_events = 0
        self.last_publish_at: Optional[float] = None
        self.bootstrap_events = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._backoff = 0.0
        # fold-mode state (None in retrain mode / before bootstrap)
        self._fold: Optional[URFoldState] = None
        self._wm: Dict[str, int] = {}
        self._heads: Dict[str, dict] = {}
        self._tombstones = frozenset()
        self._retrain_count = -1
        # a generation whose fold/restage/retrain succeeded but whose
        # publish raised: (models, mode, duration_s) — retried first
        # thing next tick (the in-memory watermark has already advanced,
        # so a 0-event tick would otherwise idle on a stale live model)
        self._pending: Optional[tuple] = None
        self._last_ckpt_at = 0.0
        self._ckpt_cost_s = 0.0
        self._state_bytes = 0
        self._state_mode = "retrain"
        # pipelined publisher (run_forever only; direct tick() stays
        # synchronous): one worker thread emits+publishes generations in
        # order, bounded at one queued job (backpressure on the fold
        # loop), so fold(t+1) overlaps emit+warm+publish(t)
        self._pub_queue = None
        self._pub_thread: Optional[threading.Thread] = None
        self._pub_lock = threading.Lock()
        self._pub_done = threading.Condition(self._pub_lock)
        self._pub_inflight = 0
        self._pub_failed = False
        # events covered by the last PUBLISHED generation — the drain
        # signal (status().coveredEvents): with the pipeline, the
        # resident state runs ahead of what serving has installed
        self._published_events: Optional[int] = None
        # lineage id of the generation currently being published — set
        # just before on_publish so _publish_info can stamp it into the
        # manifest info that rides the model plane to every worker
        self._lineage_id: Optional[str] = None
        # post-publish hooks (the plane replicator's poke rides here:
        # same-process publishes propagate to subscribers without
        # waiting out an inotify/poll period)
        self._publish_listeners: List[Callable[[], None]] = []
        self._resolve_mode()
        self._state_path = follow_state_path(
            self.storage, engine_id, engine_variant) if persist else None

    # -- mode / storage plumbing ---------------------------------------------

    def _resolve_mode(self) -> None:
        """fold mode needs: one URAlgorithm, the identity preparator, a
        UR data source, and a tailing (segment-file) event backend —
        anything else follows by full retrain per tick (still exact,
        still delta-staged through PR 3's cache)."""
        from predictionio_tpu.models.universal_recommender.engine import (
            URAlgorithm,
            URDataSourceParams,
            URPreparator,
        )

        self.mode = "retrain"
        self._algo = None
        _ds, prep, algos, _serving = self.engine.make_components(
            self.engine_params)
        ds_params = self.engine_params.data_source_params
        self.app_name = getattr(ds_params, "app_name", None)
        if self.app_name is None:
            raise FoldUnsupported(
                "follow-trainer needs a data source with an app_name")
        backend = self.storage.l_events
        from predictionio_tpu.storage.base import (
            StoreCapabilityError,
            delta_tail_supported,
        )

        if delta_tail_supported(backend):
            self._backend = backend
        else:
            # degrade loudly, not obscurely: fold mode is impossible on a
            # backend without the delta-tail protocol, so every tick will
            # be a full retrain — name the backend and the missing
            # capability once, up front (localfs/sharedfs/sharded/memory
            # all implement it; see StoreCapabilityError)
            self._backend = None
            log.warning(
                "event backend %s.%s does not support the delta-tail "
                "protocol (scan_tail_from/scan_events_up_to/"
                "tombstone_state): --follow degrades to full "
                "retrain-per-tick (%s)",
                type(backend).__module__, type(backend).__name__,
                StoreCapabilityError.__name__)
        if (len(algos) == 1 and type(algos[0]) is URAlgorithm
                and type(prep) is URPreparator
                and isinstance(ds_params, URDataSourceParams)
                and self._backend is not None):
            self.mode = "fold"
            self._algo = algos[0]
            self._ds_params = ds_params

    def _app_channel(self):
        app = self.storage.apps.get_by_name(self.app_name)
        if app is None:
            raise ValueError(f"app {self.app_name!r} does not exist")
        return app.id, None

    # -- watermark persistence ------------------------------------------------

    def _persist_state(self, wm: Optional[Dict] = None,
                       heads: Optional[Dict] = None,
                       fold_events: Optional[int] = None) -> None:
        """Persist the follow watermark.  The pipelined publisher passes
        the positions of the generation it just published — the loop
        thread's ``self._wm`` may already describe a NEWER fold (safe
        either way: a watermark is covered-prefix-reconstructable — a
        restart rebuilds and re-publishes the state at the watermark —
        but per-generation positions keep the persisted record exact)."""
        if self._state_path is None:
            return
        from predictionio_tpu.storage.snapshot import _fsync_write

        self._state_path.parent.mkdir(parents=True, exist_ok=True)
        _fsync_write(self._state_path, json.dumps({
            "version": 1,
            "watermark": self._wm if wm is None else wm,
            "heads": self._heads if heads is None else heads,
            "generation": self.generation,
            "instanceId": self.instance_id,
            "bootstrapEvents": self.bootstrap_events,
            "lastFoldEvents": (self.last_fold_events
                               if fold_events is None else fold_events),
            "updatedAt": _dt.datetime.now(_dt.timezone.utc).isoformat(),
        }, indent=1, sort_keys=True))

    def _load_state(self) -> Optional[dict]:
        if self._state_path is None:
            return None
        try:
            doc = json.loads(self._state_path.read_text())
        except (OSError, json.JSONDecodeError):
            return None
        if not isinstance(doc, dict) or "watermark" not in doc:
            return None
        return doc

    # -- pipelined publisher --------------------------------------------------
    #
    # run_forever (and only it) hands each folded generation to ONE
    # worker thread that emits the model and publishes it; the fold loop
    # immediately scans/folds the next delta, so fold(t+1) overlaps
    # emit+warm+publish(t).  Ordering and safety:
    # - jobs publish strictly in fold order (one worker, FIFO, bounded
    #   at one queued job — the queue.put is the loop's backpressure);
    # - the watermark/instance persisted with each generation are the
    #   positions captured AT ITS FOLD (passed in the job), so a crash
    #   between publishes restarts from a published-or-reconstructable
    #   point exactly as before;
    # - emit reads the fold state through an _EmitSnapshot (COW-marked
    #   shared arrays), so the loop's next _apply never mutates what an
    #   in-flight emit is reading;
    # - any transition that rebuilds state out of band (restage, retrain
    #   fallback, stop) flushes the queue first.

    def _start_publisher(self) -> None:
        import queue

        if self._pub_queue is not None:
            return
        self._pub_queue = queue.Queue(maxsize=1)
        t = threading.Thread(target=self._publisher_loop, daemon=True,
                             name="pio-follow-publish")
        self._pub_thread = t
        t.start()

    def _publisher_loop(self) -> None:
        import queue

        while True:
            try:
                job = self._pub_queue.get(timeout=0.25)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            if job is None:
                return
            try:
                # an abandoned generation breaks the emit chain: the
                # NEXT snapshot's incremental hints only describe its
                # own fold, so emitting it against the two-generations-
                # old self.model would patch serving state with the
                # abandoned fold's changes missing.  Skip everything
                # until the loop thread restages (which rebuilds the
                # state and clears the flag).
                if not self._pub_failed:
                    self._process_publish_job(job)
            finally:
                with self._pub_lock:
                    self._pub_inflight -= 1
                    self._pub_done.notify_all()

    def _process_publish_job(self, job: dict) -> None:
        attempts = 0
        while not self._stop.is_set():
            try:
                models = job.get("models")
                if models is None:
                    t0 = time.perf_counter()
                    w_emit = time.time()
                    # the job pins its state object: a concurrent loop-
                    # thread restage nulling self._fold must not strand
                    # an in-flight emit
                    models = [job["state"].emit_snapshot(job["snap"])]
                    emit_s = time.perf_counter() - t0
                    _M_PHASE_S.observe(emit_s, phase="emit")
                    if job.get("lineage"):
                        _lineage.get_lineage().stage(
                            job["lineage"], "fold.emit", start=w_emit,
                            duration_s=emit_s)
                    job["models"] = models  # publish retries skip re-emit
                self._publish(models, job["mode"], job["duration_s"],
                              trace=job.get("trace"), wm=job.get("wm"),
                              heads=job.get("heads"),
                              fold_events=job.get("events"),
                              lineage=job.get("lineage"))
                self._published_events = job.get("covered")
                return
            except Exception:
                attempts += 1
                log.exception("pipelined publish failed (attempt %d/3)",
                              attempts)
                if attempts >= 3:
                    # deterministic emit/publish failure: flag the loop
                    # thread to drop the fold state and restage (the
                    # same recovery a synchronous failure takes)
                    self._pub_failed = True
                    return
                self._stop.wait(min(self.interval * attempts, 10.0))

    def _enqueue_publish(self, job: dict) -> None:
        import queue

        with self._pub_lock:
            self._pub_inflight += 1
        while True:
            try:
                self._pub_queue.put(job, timeout=0.25)
                return
            except queue.Full:
                if self._stop.is_set():
                    with self._pub_lock:
                        self._pub_inflight -= 1
                        self._pub_done.notify_all()
                    return

    def _flush_publishes(self, timeout: float = 600.0) -> bool:
        """Block until every enqueued generation has published — called
        before any out-of-band rebuild/republish (restage, retrain
        fallback, stop) so publications stay strictly ordered."""
        if self._pub_queue is None:
            return True
        deadline = time.monotonic() + timeout
        with self._pub_lock:
            while self._pub_inflight > 0:
                rest = deadline - time.monotonic()
                if rest <= 0:
                    return False
                self._pub_done.wait(min(rest, 1.0))
        return True

    # -- fold-state checkpoint ------------------------------------------------
    #
    # Two files next to follow.json: <name>.ckpt.batch (the accumulated
    # columnar batch, via store.columnar.write_batch — dictionaries +
    # property columns included) and <name>.ckpt.npz (the numeric fold
    # state + JSON meta).  Write order batch-then-npz with a shared
    # ckpt_id makes the npz the commit point: a crash between the two
    # renames leaves an id mismatch and the loader falls back to the
    # covered-prefix reparse.  Integrity of the arrays themselves is a
    # crc32 fingerprint over pairs/marginals (URFoldState verifies on
    # restore); config drift is a fingerprint over the serialized
    # engine params.

    def _ckpt_paths(self):
        if self._state_path is None:
            return None, None
        stem = self._state_path.with_suffix("")
        return (stem.parent / (stem.name + ".ckpt.npz"),
                stem.parent / (stem.name + ".ckpt.batch"))

    def _params_fingerprint(self) -> int:
        import zlib

        from predictionio_tpu.controller.engine import (
            serialize_engine_params,
        )

        blob = json.dumps(serialize_engine_params(self.engine_params),
                          sort_keys=True, default=str)
        return int(zlib.crc32(blob.encode()))

    def _maybe_checkpoint(self) -> None:
        interval = follow_checkpoint_interval_s()
        if (interval <= 0 or self.mode != "fold" or self._fold is None
                or self._state_path is None):
            return
        # the write is synchronous in the tick path (a background writer
        # would race the in-place indicator-table mutations the next
        # fold performs), so bound its duty cycle: never spend more than
        # ~10% of wall time checkpointing — a state near the 1 GiB
        # budget self-throttles instead of stalling a fold every
        # interval for the full write duration
        effective = max(interval, 10.0 * self._ckpt_cost_s)
        if time.monotonic() - self._last_ckpt_at < effective \
                and self._last_ckpt_at:
            return
        try:
            t0 = time.perf_counter()
            self._write_checkpoint()
            self._ckpt_cost_s = time.perf_counter() - t0
            self._last_ckpt_at = time.monotonic()
        except Exception:
            # a failed checkpoint must never fail the publish that
            # triggered it — the fallback (covered-prefix reparse) stays
            log.exception("fold-state checkpoint failed; restart will "
                          "reparse the covered prefix")

    def _write_checkpoint(self) -> None:
        import numpy as np

        from predictionio_tpu.store.columnar import write_batch

        npz_path, batch_path = self._ckpt_paths()
        state = self._fold
        arrays, meta = state.checkpoint_arrays()
        ckpt_id = uuid.uuid4().hex
        meta.update({
            "ckptId": ckpt_id,
            "paramsFingerprint": self._params_fingerprint(),
            "watermark": dict(self._wm),
            "heads": dict(self._heads),
            "tombstones": sorted(self._tombstones),
            "followGeneration": self.generation,
            "instanceId": self.instance_id,
        })
        npz_path.parent.mkdir(parents=True, exist_ok=True)
        bt = batch_path.with_name(batch_path.name + ".tmp")
        write_batch(bt, state.batch, meta={"ckptId": ckpt_id})
        os.replace(bt, batch_path)
        nt = npz_path.with_name(npz_path.name + ".tmp")
        arrays = dict(arrays)
        arrays["meta_json"] = np.frombuffer(
            json.dumps(meta).encode(), np.uint8).copy()
        with open(nt, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(nt, npz_path)
        log.info("fold-state checkpoint: %d events, %d B state",
                 len(state.batch), state.state_bytes())

    def _load_checkpoint(self):
        """(state, watermark, heads, tombstones, meta) or None — every
        validation failure logs its reason and falls back."""
        import numpy as np

        from predictionio_tpu.store.columnar import read_batch
        from predictionio_tpu.streaming.fold import URFoldState

        npz_path, batch_path = self._ckpt_paths()
        if npz_path is None or not npz_path.exists() \
                or not batch_path.exists():
            return None
        try:
            with np.load(npz_path) as npz:
                arrays = {k: npz[k] for k in npz.files}
            meta = json.loads(bytes(arrays.pop("meta_json")))
            if meta.get("paramsFingerprint") != self._params_fingerprint():
                log.info("fold-state checkpoint: engine params changed — "
                         "ignoring checkpoint")
                return None
            from predictionio_tpu.streaming.fold import fold_state_impl

            conf = os.environ.get("PIO_FOLLOW_STATE", "").lower()
            if conf in ("sparse", "dense") \
                    and fold_state_impl() != meta.get("impl"):
                # an EXPLICIT representation override (the documented
                # escape hatch) must win over the persisted state — the
                # restage rebuilds in the requested representation
                log.info("fold-state checkpoint: PIO_FOLLOW_STATE=%s "
                         "overrides the checkpoint's %s representation — "
                         "ignoring checkpoint", conf, meta.get("impl"))
                return None
            # tombstone check BEFORE the expensive restore (reading the
            # batch + a full model emit can be seconds at 1M items —
            # pointless work if a delete while down invalidates it all)
            app_id, chan = self._app_channel()
            live_tombs = self._backend.tombstone_state(app_id, chan)
            if live_tombs != frozenset(meta.get("tombstones") or []):
                log.info("follow restart: tombstones changed while down "
                         "— checkpoint unusable, falling back to the "
                         "watermark reparse")
                return None
            batch, _ids, bmeta = read_batch(batch_path, mmap=False)
            if bmeta.get("ckptId") != meta.get("ckptId"):
                log.info("fold-state checkpoint: batch/state id mismatch "
                         "(torn checkpoint) — ignoring")
                return None
            state = URFoldState.restore_checkpoint(
                self._algo.params, self._ds_params, batch, arrays, meta)
        except Exception as e:
            # any corruption shape (torn zip, bad dtype, config drift)
            # must degrade to the non-checkpoint restart, never crash it
            log.warning("fold-state checkpoint unusable (%s) — restart "
                        "falls back to the covered-prefix reparse", e)
            return None
        wm = {str(k): int(v) for k, v in (meta.get("watermark") or
                                          {}).items()}
        heads = dict(meta.get("heads") or {})
        tombs = frozenset(meta.get("tombstones") or [])
        return state, wm, heads, tombs, meta

    # -- bootstrap ------------------------------------------------------------

    def bootstrap(self) -> bool:
        """Make a model live: resume from a fold-state checkpoint (no
        covered-prefix reparse at all), else from a persisted watermark
        (daemon restart — re-reads the covered prefix, folds only the
        suffix), else full restage.  Returns True once a model exists."""
        if self.mode != "fold":
            return self._retrain_tick(force=True) in ("retrain", "idle")
        prior = self._load_state()
        if self._bootstrap_from_checkpoint(prior):
            return True
        if prior is not None and self._bootstrap_from_watermark(prior):
            return True
        return self._restage(publish=True)

    def _bootstrap_from_checkpoint(self, prior: Optional[dict]) -> bool:
        """Resume from the persisted fold state: restore the arrays,
        verify tombstones didn't move while down, re-publish the
        restored generation to an embedded host, and fold ONLY the
        events past the checkpoint's watermark — the covered prefix is
        never reparsed."""
        loaded = self._load_checkpoint()
        if loaded is None:
            return False
        state, wm, heads, tombs, meta = loaded
        self._fold = state
        self._wm, self._heads = wm, heads
        self._tombstones = tombs
        self.generation = int((prior or {}).get(
            "generation", meta.get("followGeneration", 0)))
        self.instance_id = (prior or {}).get(
            "instanceId", meta.get("instanceId"))
        self.bootstrap_events = len(state.batch)
        log.info("follow restart: restored fold state from checkpoint "
                 "(%d covered events, %d B, generation %d) — folding "
                 "only the unapplied suffix", len(state.batch),
                 state.state_bytes(), self.generation)
        # the checkpoint equals an already-published generation; an
        # embedded host still needs its in-process copy swapped in
        if self.on_publish is not None:
            self.on_publish([state.model], self._publish_info("restart"))
        self._published_events = len(state.batch)
        self._update_state_metrics()
        # fold whatever arrived past the checkpoint watermark right now
        # (tick also re-runs the tombstone / log-shape / max-lag edges
        # and restages if the watermark no longer matches the log)
        self.tick()
        return True

    def _bootstrap_from_watermark(self, prior: dict) -> bool:
        app_id, chan = self._app_channel()
        wm = {str(k): int(v) for k, v in prior["watermark"].items()}
        heads = prior.get("heads") or {}
        # tombstones read BEFORE the scan (same safe-side order as
        # _restage/_tick_inner): one landing mid-scan then compares
        # unequal next tick and restages, instead of being recorded as
        # already-applied while its deleted events stay folded in
        tombs = self._backend.tombstone_state(app_id, chan)
        res = self._backend.scan_events_up_to(app_id, chan, wm, heads=heads)
        if res is None:
            log.info("follow restart: persisted watermark no longer "
                     "matches the log — full restage")
            return False
        try:
            self._fold = URFoldState.bootstrap(
                self._algo.params, self._ds_params, res["batch"])
        except (FoldUnsupported, ValueError) as e:
            log.warning("follow restart: bootstrap from covered prefix "
                        "failed (%s); full restage", e)
            return False
        self._wm, self._heads = wm, heads
        self._tombstones = tombs
        self.generation = int(prior.get("generation", 0))
        self.instance_id = prior.get("instanceId")
        self.bootstrap_events = int(res["events"])
        log.info("follow restart: rebuilt state from %d covered events "
                 "(generation %d); folding the unapplied suffix",
                 res["events"], self.generation)
        self._update_state_metrics()
        # the covered prefix equals the last PUBLISHED generation; the
        # embedded host still needs its in-process copy swapped in
        if self.on_publish is not None:
            self.on_publish([self._fold.model], self._publish_info("restart"))
        self._published_events = len(self._fold.batch)
        # fold whatever arrived past the watermark right now
        self.tick()
        return True

    def _restage(self, publish: bool) -> bool:
        """Full rebuild: read the whole log (snapshot-first) and
        re-bootstrap the fold state."""
        if not self._flush_publishes():
            # a publish is wedged past the flush timeout: restaging now
            # would race it — the stuck job could later install its
            # older generation OVER the restaged one and persist an
            # older watermark.  Bail; the next tick retries.
            log.warning("restage deferred: a pipelined publish has not "
                        "drained")
            return False
        app_id, chan = self._app_channel()
        tombs = self._backend.tombstone_state(app_id, chan)
        res = self._backend.snapshot_scan(app_id, chan)
        if res is None:
            res = self._backend.scan_tail_from(app_id, chan, {}, base=None,
                                               heads=None)
        if res is None:
            return False
        try:
            t0 = time.perf_counter()
            self._fold = URFoldState.bootstrap(
                self._algo.params, self._ds_params, res["batch"])
        except ValueError as e:
            # e.g. no primary events yet — but also config errors
            # (blacklist/backfill name typos) that would recur forever:
            # log every retry so the operator sees WHY nothing publishes
            log.warning("follow restage could not bootstrap (%s); "
                        "retrying next tick", e)
            self._fold = None
            return False
        except FoldUnsupported as e:
            log.warning("fold unsupported (%s); falling back to "
                        "retrain mode", e)
            self._fold = None
            self.mode = "retrain"
            return self._retrain_tick(force=True) == "retrain"
        self._wm = dict(res["watermark"])
        self._heads = dict(res.get("heads") or {})
        self._tombstones = tombs
        self.bootstrap_events = len(self._fold.batch)
        self.last_fold_events = len(self._fold.batch)
        self._last_ckpt_at = 0.0   # a fresh state deserves a prompt ckpt
        if publish:
            self._publish_guarded([self._fold.model], "restage",
                                  time.perf_counter() - t0)
            self._published_events = len(self._fold.batch)
        return True

    # -- the tick -------------------------------------------------------------

    def tick(self) -> str:
        """One follow cycle; returns the outcome (also counted in
        pio_follow_folds_total)."""
        if not follow_enabled():
            self.last_outcome = "disabled"
            _M_FOLDS.inc(1, outcome="disabled")
            return "disabled"
        try:
            outcome = self._tick_inner()
        except Exception:
            log.exception("follow tick failed")
            self.last_outcome = "error"
            _M_FOLDS.inc(1, outcome="error")
            self._update_state_metrics()
            raise
        self.last_outcome = outcome
        _M_FOLDS.inc(1, outcome=outcome)
        self._update_state_metrics()
        return outcome

    def _update_state_metrics(self) -> None:
        """Refresh the fold-state gauges (bytes + representation mode)
        and their status() mirror — cheap (an nbytes sum)."""
        if self.mode == "fold" and self._fold is not None:
            self._state_bytes = self._fold.state_bytes()
            self._state_mode = self._fold.state_mode
        else:
            self._state_bytes = 0
            self._state_mode = "retrain"
        _M_STATE_BYTES.set(self._state_bytes)
        for m in ("sparse", "dense", "retrain"):
            _M_STATE_MODE.set(1 if m == self._state_mode else 0, mode=m)

    def _tick_inner(self) -> str:
        if self._pending is not None:
            models, pmode, dur, plid = self._pending
            self._publish(models, pmode, dur, lineage=plid)
            self._pending = None
            if self.mode == "fold" and self._fold is not None:
                self._published_events = len(self._fold.batch)
            return pmode
        if self._pub_failed:
            # the publisher gave up on a generation: same recovery as a
            # synchronous emit/publish failure — drop the state, restage.
            # Flush BEFORE clearing the flag: queued stale jobs must
            # drain as skips (their emit chain is broken), not process.
            self._flush_publishes()
            self._pub_failed = False
            log.warning("pipelined publish abandoned a generation — "
                        "dropping fold state and restaging")
            self._fold = None
        if self.mode != "fold":
            return self._retrain_tick()
        if self._fold is None:
            return "restage" if self._restage(publish=True) else "idle"
        if self._pub_queue is not None:
            # quiescent point for the loop thread: only it mutates the
            # fold state, so checkpointing here (instead of inside the
            # publisher's _publish) can never race the next _apply
            self._maybe_checkpoint()
        app_id, chan = self._app_channel()
        t0 = time.perf_counter()
        w_tick = time.time()
        tombs = self._backend.tombstone_state(app_id, chan)
        if tombs != self._tombstones:
            # a tombstone arrived mid-follow: folded events may be dead —
            # the incremental state cannot subtract, so rebuild from the
            # live log (the same contract as _StagedCache)
            log.info("follow: tombstone set changed — full restage")
            self._fold = None
            return "restage" if self._restage(publish=True) else "idle"
        trace = _tracing.Trace(f"fold-{uuid.uuid4().hex[:12]}")
        with trace.activate(), trace.span("follow_tail"):
            tail = self._backend.scan_tail_from(
                app_id, chan, self._wm, base=self._fold.batch,
                heads=self._heads)
        if tail is None:
            log.info("follow: watermark no longer matches the log — "
                     "full restage")
            self._fold = None
            return "restage" if self._restage(publish=True) else "idle"
        _M_LAG.set(tail["events"])
        if tail["events"] == 0:
            self._wm, self._heads = tail["watermark"], tail["heads"]
            return "idle"
        max_lag = self.max_lag or follow_max_lag_events()
        if tail["events"] > max_lag:
            log.info("follow: %d unapplied events exceed "
                     "PIO_FOLLOW_MAX_LAG_EVENTS=%d — full restage",
                     tail["events"], max_lag)
            self._fold = None
            return "restage" if self._restage(publish=True) else "idle"
        pipelined = self._pub_queue is not None
        # the generation's lineage record opens HERE — the first moment
        # the fold tick observed appended events; every later stage
        # (fold, emit, publish, plane write, watcher wake, compose,
        # install, first serve) hangs off this id
        lin = _lineage.get_lineage()
        lid: Optional[str] = None
        if lin.enabled:
            lid = lin.new_id()
            lin.begin(lid, start=w_tick)
            lin.stage(lid, "append_observed", start=w_tick,
                      duration_s=time.perf_counter() - t0,
                      events=int(tail["events"]))
        w_fold = time.time()
        with trace.activate():
            with trace.span("follow_fold", events=tail["events"]):
                try:
                    if pipelined:
                        snap = self._fold.fold_apply(tail["batch"])
                    else:
                        model = self._fold.fold(tail["batch"])
                except FoldUnsupported as e:
                    log.warning("fold unsupported mid-stream (%s); "
                                "restaging in retrain mode", e)
                    self._fold = None
                    self.mode = "retrain"
                    return self._retrain_tick(force=True)
                except Exception:
                    # fold() mutates incrementally (batch concat, pair
                    # merges, raw popularity appends) — after a partial
                    # apply the state cannot be trusted, and retrying
                    # the same suffix on top of it would double-fold.
                    # Drop it; the next cycle restages from the log.
                    self._fold = None
                    raise
        phases = dict(self._fold.last_phase_s or {})
        for phase, dur in phases.items():
            _M_PHASE_S.observe(dur, phase=phase)
        if lid is not None:
            # lay the fold phases out sequentially from the fold's wall
            # start — apply runs first, the RELLR refresh inside it is
            # accounted separately (fold.py subtracts it from apply)
            cursor = w_fold
            for phase in ("apply", "rellr"):
                dur = float(phases.get(phase, 0.0))
                lin.stage(lid, f"fold.{phase}", start=cursor,
                          duration_s=dur)
                cursor += dur
        covered = len(self._fold.batch)
        self._wm, self._heads = tail["watermark"], tail["heads"]
        self.last_fold_events = int(tail["events"])
        if pipelined:
            self._enqueue_publish({
                "snap": snap, "state": self._fold, "mode": "fold",
                # duration measured HERE (tail scan + fold), not in the
                # publisher: queue wait behind the previous generation's
                # warm and publish-retry backoff are not fold cost, and
                # would inflate the histogram operators alert on (the
                # phase histogram carries emit/warm/publish)
                "duration_s": time.perf_counter() - t0,
                "covered": covered, "wm": dict(self._wm),
                "heads": dict(self._heads),
                "events": int(tail["events"]), "trace": trace,
                "lineage": lid,
            })
        else:
            _M_PHASE_S.observe(
                getattr(self._fold, "last_emit_s", 0.0), phase="emit")
            self._publish_guarded([model], "fold",
                                  time.perf_counter() - t0, trace=trace,
                                  lineage=lid)
            self._published_events = covered
        _M_LAG.set(0)
        return "fold"

    def _retrain_tick(self, force: bool = False) -> str:
        """Fallback path: full Engine.train per tick (delta-staged by
        PR 3's cache), published exactly like a fold."""
        if not self._flush_publishes():
            log.warning("retrain deferred: a pipelined publish has not "
                        "drained")
            return "idle"
        t0 = time.perf_counter()
        changed, commit = self._probe_store()
        if not force and not changed:
            commit()
            return "idle"
        models = self.engine.train(self.engine_params)
        # commit the probe's positions only now: a transient train
        # failure must leave the watermark behind so the next tick
        # retries the same suffix instead of idling forever
        commit()
        self._publish_guarded(models, "retrain", time.perf_counter() - t0)
        return "retrain"

    def _probe_store(self):
        """Cheap new-events probe for retrain mode: watermark tail scan
        on segment-file backends, an event count elsewhere.  Returns
        ``(changed, commit)`` — ``commit()`` applies the observed
        positions and runs only after the tick's train succeeded (or on
        the nothing-new path)."""
        app_id, chan = self._app_channel()
        if self._backend is not None:
            tombs = self._backend.tombstone_state(app_id, chan)
            tomb_changed = tombs != self._tombstones
            tail = self._backend.scan_tail_from(app_id, chan, self._wm,
                                                base=None,
                                                heads=self._heads or None)
            if tail is None:
                def commit():
                    self._tombstones = tombs
                    self._wm, self._heads = {}, {}
                return True, commit
            _M_LAG.set(tail["events"])

            # the commit captures tail positions even on a tombstone-only
            # trigger: the retrain reads the whole log, so the next tick
            # must not re-count the covered suffix as new work
            def commit():
                self._tombstones = tombs
                self._wm, self._heads = tail["watermark"], tail["heads"]
            return tomb_changed or tail["events"] > 0, commit
        n = sum(1 for _ in self.storage.p_events.find(app_id))

        def commit():
            self._retrain_count = n
        return n != self._retrain_count, commit

    # -- publication ----------------------------------------------------------

    def _publish_info(self, mode: str) -> dict:
        info = {
            "mode": mode,
            "generation": self.generation,
            "engineInstanceId": self.instance_id,
            "foldEvents": self.last_fold_events,
            "publishedAt": self.last_publish_at,
            "stateBytes": self._state_bytes,
            "stateMode": self._state_mode,
        }
        if self._lineage_id is not None:
            # rides the plane manifest's info dict to every consumer:
            # PlaneWatcher reads it back out of plane.load so the
            # install/first-serve stages land on the SAME record this
            # fold tick opened, from processes that never saw the fold
            info["lineageId"] = self._lineage_id
        return info

    def _publish_guarded(self, models, mode: str, duration_s: float,
                         trace: Optional[_tracing.Trace] = None,
                         lineage: Optional[str] = None) -> None:
        """Publish, retaining the generation in ``_pending`` so a
        transient publish failure is retried first thing next tick
        instead of stranding an already-folded generation unpublished."""
        self._pending = (models, mode, duration_s, lineage)
        self._publish(models, mode, duration_s, trace=trace,
                      lineage=lineage)
        self._pending = None

    def _publish(self, models, mode: str, duration_s: float,
                 trace: Optional[_tracing.Trace] = None,
                 wm: Optional[Dict] = None, heads: Optional[Dict] = None,
                 fold_events: Optional[int] = None,
                 lineage: Optional[str] = None) -> None:
        """Atomic model publication: durable instance record (daemon) +
        in-process hot-swap (embedded), then watermark persistence —
        the watermark only advances AFTER the generation it describes is
        published, so a crash between the two re-folds, never skips.
        The pipelined publisher passes the generation's own ``wm``/
        ``heads``/``fold_events`` (the loop thread may already be ahead)."""
        from predictionio_tpu.controller.engine import (
            serialize_engine_params,
        )
        from predictionio_tpu.storage.base import EngineInstance
        from predictionio_tpu.workflow import persistence

        if trace is None:
            trace = _tracing.Trace(f"fold-{uuid.uuid4().hex[:12]}")
        self.generation += 1
        t_pub0 = time.perf_counter()
        w_pub = time.time()
        t_warm = 0.0
        self._lineage_id = lineage
        try:
            with trace.activate(), trace.span(
                    "model_swap", mode=mode, generation=self.generation,
                    events=self.last_fold_events):
                if self.persist:
                    now = _dt.datetime.now(_dt.timezone.utc)
                    params_json = serialize_engine_params(self.engine_params)
                    instance = EngineInstance(
                        id="", status="TRAINING", start_time=now,
                        end_time=None,
                        engine_id=self.engine_id,
                        engine_version=self.engine_version,
                        engine_variant=self.engine_variant,
                        engine_factory=self.engine_factory,
                        data_source_params=params_json["data_source_params"],
                        preparator_params=params_json["preparator_params"],
                        algorithms_params=params_json["algorithms_params"],
                        serving_params=params_json["serving_params"])
                    with trace.span("follow_publish"):
                        iid = self.storage.engine_instances.insert(instance)
                        try:
                            persistence.save_models(self.storage, iid, models)
                            instance.status = "COMPLETED"
                            instance.end_time = _dt.datetime.now(
                                _dt.timezone.utc)
                            self.storage.engine_instances.update(instance)
                        except BaseException:
                            # best-effort: the retry inserts a fresh row;
                            # this one must not linger forever-TRAINING
                            try:
                                instance.status = "ABORTED"
                                instance.end_time = _dt.datetime.now(
                                    _dt.timezone.utc)
                                self.storage.engine_instances.update(instance)
                            except Exception:
                                pass
                            raise
                    self.instance_id = iid
                if self.on_publish is not None:
                    tw = time.perf_counter()
                    self.on_publish(models, self._publish_info(mode))
                    t_warm = time.perf_counter() - tw
        except BaseException:
            # the retry re-runs _publish in full: un-count this attempt
            # so generations advance by exactly one per published swap
            self.generation -= 1
            raise
        self.last_publish_at = time.time()
        for fn in list(self._publish_listeners):
            try:
                fn()
            except Exception:
                log.exception("follow: publish listener failed")
        if self.on_publish is None:
            # daemon mode owns pio_model_generation; an embedded host's
            # install path sets it from the SERVER generation (which
            # also counts reloads) — two counters writing one gauge
            # would break its monotonic contract
            _M_GEN.set(self.generation)
        _M_PUBLISH_TS.set(self.last_publish_at)
        _M_FOLD_S.observe(duration_s, mode=mode)
        _M_PHASE_S.observe(t_warm, phase="warm")
        _M_PHASE_S.observe(
            max(time.perf_counter() - t_pub0 - t_warm, 0.0),
            phase="publish")
        self._persist_state(wm=wm, heads=heads, fold_events=fold_events)
        if self._pub_queue is None:
            # synchronous mode only: with the pipeline, the checkpoint
            # runs on the LOOP thread at its next quiescent point — from
            # here (the publisher thread) it would race the next _apply's
            # in-place mutations
            self._maybe_checkpoint()
        lin = _lineage.get_lineage()
        if lineage is not None and lin.enabled:
            lin.stage(lineage, "publish", start=w_pub,
                      duration_s=time.perf_counter() - t_pub0,
                      mode=mode, warm_s=round(t_warm, 6))
            lin.close(lineage, outcome="published")
        rec = _tracing.get_recorder()
        if rec.enabled:
            rec.record(trace.to_doc(rec.tag, "model_swap"))
        log.info("follow: published generation %d (%s, %d events, "
                 "%.3fs)", self.generation, mode, self.last_fold_events,
                 duration_s)

    # -- loop / lifecycle -----------------------------------------------------

    def run_forever(self) -> None:
        """Blocking daemon loop with exponential error backoff and crash
        restart from the persisted watermark.  With the pipeline enabled
        (default; PIO_FOLLOW_PIPELINE=off reverts), each folded
        generation's emit+warm+publish runs on the publisher thread so
        the loop scans and folds the next delta concurrently."""
        while not self._stop.is_set():
            try:
                if (self.mode == "fold" and self._fold is None
                        and self.generation == 0):
                    self.bootstrap()   # publishes + ticks when it lands
                    if follow_pipeline_enabled():
                        self._start_publisher()
                else:
                    if (self._pub_queue is None
                            and follow_pipeline_enabled()):
                        self._start_publisher()
                    self.tick()
                self._backoff = 0.0
            except Exception:
                log.exception("follow cycle failed; backing off")
                self._backoff = min(
                    max(self.interval, self._backoff * 2 or self.interval),
                    60.0)
            self._stop.wait(self.interval + self._backoff)

    def add_publish_listener(self, fn: Callable[[], None]) -> None:
        """Call ``fn`` (no args, exception-safe) after every successful
        publish.  The plane replicator registers its ``poke`` here so
        same-process publishes reach the wire without waiting out a
        directory-watch period."""
        self._publish_listeners.append(fn)

    def start(self) -> threading.Thread:
        """Run the loop on a daemon thread (the embedded mode)."""
        t = threading.Thread(target=self.run_forever, daemon=True,
                             name="pio-follow")
        self._thread = t
        t.start()
        return t

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        if self._pub_thread is not None:
            try:
                self._pub_queue.put_nowait(None)
            except Exception:
                pass   # full queue: the loop's 0.25 s poll sees _stop
            self._pub_thread.join(timeout=timeout)

    def status(self) -> dict:
        """The /stats.json freshness payload."""
        # snapshot once: a concurrent tick can demote (self._fold = None)
        # between a check and a dereference on the HTTP thread
        fold = self._fold
        covered = None
        if fold is not None:
            # with the pipelined publisher the resident state runs ahead
            # of serving — report what the last PUBLISHED generation
            # covers, so drains stay deterministic
            covered = (self._published_events
                       if self._pub_queue is not None
                       and self._published_events is not None
                       else len(fold.batch))
        return {
            "mode": self.mode,
            "generation": self.generation,
            "lastOutcome": self.last_outcome,
            "lastFoldEvents": self.last_fold_events,
            "stateBytes": self._state_bytes,
            "stateMode": self._state_mode,
            # total events the live (published) model covers — the
            # deterministic drain signal for scripts/benches (an
            # "idle" outcome alone can be a tick that ran BEFORE an
            # append became visible); None in retrain mode
            "coveredEvents": covered,
            "lastPublishAt": (
                _dt.datetime.fromtimestamp(
                    self.last_publish_at,
                    _dt.timezone.utc).isoformat()
                if self.last_publish_at else None),
            "engineInstanceId": self.instance_id,
            "enabled": follow_enabled(),
            "intervalSeconds": self.interval,
        }
