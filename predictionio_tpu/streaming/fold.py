"""Incremental CCO fold: delta events → updated URModel, exactly.

A full UR retrain is (a) stage/parse the whole log, (b) translate to
dense id spaces, (c) the O(U·I_p·I_t) co-occurrence count pass, (d) LLR +
per-row top-k, (e) popularity/CSR/property epilogues.  PR 3's delta
staging already made (a) incremental; this module makes (b)–(e)
incremental too, by exploiting that CCO counts are ADDITIVE:

- :class:`URFoldState` keeps, per event type, the deduped (user, item)
  pair set, the co-occurrence counts and the LLR marginals
  (distinct-user row/column counts).  Counts are **sorted-COO by
  default** (:class:`_SparseCounts`: one int64 ``(row<<32|col)`` key +
  int32 count per nonzero cell — O(nnz), so a 1M-item catalog whose
  dense matrix would be 4 TB fits in tens of MB); the legacy dense
  int32 ``[I_p, I_t]`` matrices remain behind ``PIO_FOLLOW_STATE=dense``
  as an escape hatch and as the bit-exactness oracle the property tests
  compare against.  A delta fold applies ``C_new = C + Δpᵀ·A_old +
  P_newᵀ·Δa`` as vectorized scatter-adds (dense) or one sorted merge
  (sparse) over the delta's cross-join — O(delta footprint), never
  O(U·I²).
- LLR + top-k re-runs through the SAME scoring chain training uses
  (``ops.cco._llr_mask_scores`` / ``_llr_cells`` — XLA elementwise math
  is element-value-deterministic regardless of tensor shape), so every
  recomputed cell is bit-identical to a from-scratch retrain's value —
  exactness by construction, not by tolerance.  Sparse state routes
  re-LLR through ``ops.cco._llr_topk_sparse_rows`` (the row-scoped
  variant of the training host tail — same scores, same lax.top_k tie
  order); dense state through the row-sliced ``_llr_topk_rows_jit``.
  Only *affected* rows recompute: a delta that changes no global LLR
  input (no new users, no new target-side pairs for the type) re-LLRs
  just the touched primary rows; a marginal change (new user → N, new
  target pairs → column counts) forces that type's full re-LLR, because
  Dunning G² couples every cell to N and its column marginal.
- The emitted model is a NEW ``URModel`` object per fold — PR 4/7's
  generation-keyed serving caches (rule-mask LRU, value-mask/date LRUs,
  ``host_pop_order``) invalidate by model identity, so hot-swap
  correctness needs no extra plumbing.  Where cheap and provably safe,
  derived serving state carries over instead of rebuilding: the
  ``host_inverted`` CSR is row-patched when few indicator rows changed
  (``_patch_inverted_csr`` — array-identical to a from-scratch
  inversion), and the property indexes carry when no ``$set``-family
  event arrived.

State is bounded by ``PIO_FOLLOW_STATE_BYTES`` (default 1 GiB: counts
plus the log-proportional parts — accumulated batch, pair sets, raw
popularity inputs, indicator tables); past it :class:`FoldUnsupported`
tells the follower to fall back to full (delta-staged) retrains per
tick, which stay exact — the budget gates cost, never correctness.
With sparse counts the resident total is ≈ f(events), not catalog², so
the default budget holds fold mode at million-item catalogs.

The state is also checkpointable (``checkpoint_arrays`` /
``restore_checkpoint`` + the accumulated batch via
``store.columnar.write_batch``): the follower persists it beside its
watermark so a SIGKILL restart re-folds only the unapplied suffix
instead of reparsing the covered prefix (see ``streaming.follow``).
"""

from __future__ import annotations

import dataclasses
import os
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.ops.cco import _llr_mask_scores
from predictionio_tpu.store.columnar import (
    CSRLookup,
    EventBatch,
    IdDict,
    fold_properties,
)

_LOW32 = np.int64((1 << 32) - 1)


def state_budget_bytes() -> int:
    """PIO_FOLLOW_STATE_BYTES caps the resident fold state — the count
    matrices (I_p·I_t·4 per event type) PLUS the log-proportional parts
    (accumulated columnar batch, pair sets, raw popularity inputs).
    Past it the follower retrains instead of folding (exact either way;
    the budget trades memory for fold latency)."""
    try:
        return max(int(os.environ.get("PIO_FOLLOW_STATE_BYTES",
                                      str(1 << 30))), 1)
    except ValueError:
        return 1 << 30


def fold_state_impl() -> str:
    """``PIO_FOLLOW_STATE``: 'sparse' (default) keeps sorted-COO counts —
    O(nnz) resident bytes, the representation that holds fold mode at
    million-item catalogs; 'dense' keeps the legacy [I_p, I_t] int32
    matrices (escape hatch + the oracle the sparse≡dense property tests
    compare against)."""
    conf = os.environ.get("PIO_FOLLOW_STATE", "auto").lower()
    return "dense" if conf == "dense" else "sparse"


def _dense_rellr_bytes() -> int:
    """Small-catalog fast path: a sparse-state FULL re-LLR whose dense
    [I_p, I_t] f32 matrix fits this budget (PIO_FOLLOW_DENSE_RELLR_BYTES,
    default 4 MiB) materializes it transiently and runs the jitted dense
    kernels — at tiny shapes (the sub-ms regime) the dense jit beats the
    sparse gather+lexsort ~2×, and it is the exact path the dense state
    (and PR 8) always took.  0 forces the sparse tail everywhere (the
    property tests use it so the sparse kernels stay covered at small
    shapes)."""
    try:
        return max(int(os.environ.get("PIO_FOLLOW_DENSE_RELLR_BYTES",
                                      str(4 << 20))), 0)
    except ValueError:
        return 4 << 20


class FoldUnsupported(RuntimeError):
    """The fold engine cannot (or should not) maintain incremental state
    for this engine/shape — the follower falls back to retrain mode."""


class _SparseCounts:
    """Sorted-COO co-occurrence counts: ``keys`` holds one int64
    ``(row << 32) | col`` per nonzero cell, ascending; ``counts`` the
    int32 count at that cell.  All mutations preserve the sort:

    - increments merge via searchsorted + np.insert (new cells land at
      their exact slots);
    - row/col remaps apply a STRICTLY INCREASING permutation (the
      old→new local-id map ``_extend_item_space`` computes is a
      searchsorted into the union of two sorted sets, hence monotone),
      so remapped keys stay ascending without a re-sort.
    """

    __slots__ = ("keys", "counts")

    def __init__(self, keys: np.ndarray, counts: np.ndarray):
        self.keys = np.asarray(keys, np.int64)
        self.counts = np.asarray(counts, np.int32)

    @classmethod
    def empty(cls) -> "_SparseCounts":
        return cls(np.zeros(0, np.int64), np.zeros(0, np.int32))

    @classmethod
    def from_dense(cls, C: np.ndarray) -> "_SparseCounts":
        rows, cols = np.nonzero(C)
        return cls(_pair_key(rows, cols), C[rows, cols].astype(np.int32))

    @property
    def nnz(self) -> int:
        return len(self.keys)

    @property
    def nbytes(self) -> int:
        return int(self.keys.nbytes) + int(self.counts.nbytes)

    def add_pairs(self, rows: np.ndarray, cols: np.ndarray) -> None:
        """counts[r, c] += multiplicity of (r, c) in the given pairs."""
        if len(rows) == 0:
            return
        uniq, inc = np.unique(_pair_key(rows, cols), return_counts=True)
        pos = np.searchsorted(self.keys, uniq)
        hit = np.zeros(len(uniq), bool)
        in_range = pos < len(self.keys)
        hit[in_range] = self.keys[pos[in_range]] == uniq[in_range]
        if hit.any():
            self.counts[pos[hit]] += inc[hit].astype(np.int32)
        miss = ~hit
        if miss.any():
            self.keys = np.insert(self.keys, pos[miss], uniq[miss])
            self.counts = np.insert(self.counts, pos[miss],
                                    inc[miss].astype(np.int32))

    def all_cells(self):
        """(rows, cols, counts) of every nonzero cell, (row, col)-asc."""
        return (self.keys >> np.int64(32), self.keys & _LOW32, self.counts)

    def row_cells(self, rows: np.ndarray):
        """Gather the cells of a sorted unique row subset: returns
        (local row index into ``rows``, col, count) — each row's cells
        are one contiguous key segment, bounded by two searchsorteds
        (the same repeat/arange expansion as ``_cross_scatter``)."""
        rows = np.asarray(rows, np.int64)
        starts = np.searchsorted(self.keys, rows << np.int64(32))
        ends = np.searchsorted(self.keys, (rows + 1) << np.int64(32))
        seg = ends - starts
        total = int(seg.sum())
        if total == 0:
            return (np.zeros(0, np.int64), np.zeros(0, np.int64),
                    np.zeros(0, np.int32))
        csum = np.cumsum(seg)
        within = np.arange(total, dtype=np.int64) - np.repeat(csum - seg, seg)
        idx = np.repeat(starts, seg) + within
        local = np.repeat(np.arange(len(rows), dtype=np.int64), seg)
        return local, self.keys[idx] & _LOW32, self.counts[idx]

    def remap_cols(self, perm: np.ndarray) -> None:
        """col → perm[col] (perm strictly increasing: order preserved)."""
        if self.nnz and len(perm):
            self.keys = (self.keys & ~_LOW32) \
                | np.asarray(perm, np.int64)[self.keys & _LOW32]

    def remap_rows(self, perm: np.ndarray) -> None:
        """row → perm[row] (perm strictly increasing: order preserved)."""
        if self.nnz and len(perm):
            self.keys = (np.asarray(perm, np.int64)[self.keys >> np.int64(32)]
                         << np.int64(32)) | (self.keys & _LOW32)

    def to_dense(self, n_rows: int, n_cols: int) -> np.ndarray:
        C = np.zeros((n_rows, n_cols), np.int32)
        if self.nnz:
            C[self.keys >> np.int64(32), self.keys & _LOW32] = self.counts
        return C


def _pair_key(u: np.ndarray, i: np.ndarray) -> np.ndarray:
    """(user id, type-local item id) → one sortable int64 key."""
    return (np.asarray(u, np.int64) << np.int64(32)) | np.asarray(i, np.int64)


def _key_item(key: np.ndarray) -> np.ndarray:
    return (key & _LOW32).astype(np.int64)


def _key_user(key: np.ndarray) -> np.ndarray:
    return (key >> np.int64(32)).astype(np.int64)


def _in_sorted(values: np.ndarray, sorted_arr: np.ndarray) -> np.ndarray:
    """Boolean membership of ``values`` in an ascending array."""
    if len(sorted_arr) == 0 or len(values) == 0:
        return np.zeros(len(values), bool)
    pos = np.searchsorted(sorted_arr, values)
    np.minimum(pos, len(sorted_arr) - 1, out=pos)
    return sorted_arr[pos] == values


def _cross_partners(pairs_sorted: np.ndarray, du: np.ndarray,
                    di: np.ndarray, rows_from_delta: bool):
    """Expand one side of the count update into its (row, col) increment
    pairs — shared by both count representations.

    For every delta pair (du[e], di[e]) and every partner item j in the
    OTHER side's per-user segment of ``pairs_sorted`` (deduped composite
    keys, (user, item)-ascending):

    - rows_from_delta=True:  (di[e], j)   (Δpᵀ·A — delta items are
      primary rows, partners are columns)
    - rows_from_delta=False: (j, di[e])   (Pᵀ·Δa — partners are
      primary rows, delta items are columns)

    One searchsorted pair bounds each user's partner segment; the flat
    expansion mirrors ``models.common.gather_csr_rows`` (repeat/arange,
    no per-pair Python loop).
    """
    if len(du) == 0 or len(pairs_sorted) == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    starts = np.searchsorted(pairs_sorted,
                             np.asarray(du, np.int64) << np.int64(32))
    ends = np.searchsorted(pairs_sorted,
                           (np.asarray(du, np.int64) + 1) << np.int64(32))
    seg = ends - starts                       # partners per delta pair
    total = int(seg.sum())
    if total == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    csum = np.cumsum(seg)
    within = np.arange(total, dtype=np.int64) - np.repeat(csum - seg, seg)
    partners = _key_item(pairs_sorted[np.repeat(starts, seg) + within])
    own = np.repeat(np.asarray(di, np.int64), seg)
    if rows_from_delta:
        return own, partners
    return partners, own


def _cross_scatter(counts, pairs_sorted: np.ndarray,
                   du: np.ndarray, di: np.ndarray,
                   rows_from_delta: bool) -> np.ndarray:
    """Apply one side of the count update (see ``_cross_partners``) to
    ``counts`` — a dense int32 matrix (scatter-add) or a
    :class:`_SparseCounts` (sorted merge) — and return the touched
    primary-row ids."""
    rows, cols = _cross_partners(pairs_sorted, du, di, rows_from_delta)
    if len(rows) == 0:
        return np.zeros(0, np.int64)
    if isinstance(counts, _SparseCounts):
        counts.add_pairs(rows, cols)
    else:
        np.add.at(counts, (rows, cols), 1)
    return np.unique(rows)


@partial(jax.jit, static_argnames=("top_k", "pallas"))
def _llr_topk_rows_jit(C_rows, rc_rows, cc, n_total, llr_threshold,
                       self_cols, top_k: int, pallas: str = "off"):
    """Row-sliced twin of ``ops.cco._llr_topk_dense``: the identical
    elementwise score chain (so each cell's f32 value is bit-identical —
    XLA elementwise math is element-value-deterministic regardless of
    tensor shape), the identical -inf self-pair placement (``self_cols``
    holds each row's GLOBAL primary id, -1 for non-primary types), the
    identical ``lax.top_k`` tie order."""
    scores = _llr_mask_scores(
        C_rows.astype(jnp.float32), rc_rows.astype(jnp.float32),
        cc.astype(jnp.float32), n_total, llr_threshold, pallas)
    cols = jnp.arange(scores.shape[1], dtype=jnp.int32)[None, :]
    is_self = (cols == self_cols[:, None]) & (self_cols[:, None] >= 0)
    scores = jnp.where(is_self, -jnp.inf, scores)
    s, i = jax.lax.top_k(scores, top_k)
    return s, i.astype(jnp.int32)


def _llr_topk_rows(C_rows: np.ndarray, rc_rows: np.ndarray,
                   cc: np.ndarray, n_total: float, llr_threshold: float,
                   self_rows: Optional[np.ndarray], top_k: int,
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Host wrapper: pad the row count to the next power of two so the
    jit compiles once per bucket, not per distinct slice size (padding
    rows score -inf everywhere — zero counts — and are dropped)."""
    n = C_rows.shape[0]
    pad = 1 << max((n - 1).bit_length(), 0)
    sc = np.full(pad, -1, np.int32)
    if self_rows is not None:
        sc[:n] = self_rows.astype(np.int32)
    if pad > n:
        C_rows = np.concatenate(
            [C_rows, np.zeros((pad - n, C_rows.shape[1]), C_rows.dtype)])
        rc_rows = np.concatenate(
            [rc_rows, np.zeros(pad - n, rc_rows.dtype)])
    s, i = _llr_topk_rows_jit(
        jnp.asarray(C_rows), jnp.asarray(rc_rows), jnp.asarray(cc),
        float(n_total), float(llr_threshold), jnp.asarray(sc),
        top_k=top_k)
    return np.asarray(s)[:n], np.asarray(i)[:n]


def _patch_inverted_csr(old: Tuple[np.ndarray, np.ndarray, np.ndarray],
                        changed_rows: np.ndarray,
                        new_idx: np.ndarray, new_llr: np.ndarray,
                        n_t: int, i_p: int,
                        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Row-patch a host_inverted CSR: drop every posting entry whose
    primary row changed, insert the changed rows' new entries at their
    (target, row) positions.  Output is ARRAY-IDENTICAL to rebuilding the
    inversion from the new indicator table (the rebuild's stable sort
    orders entries by (target, row); kept entries already follow that
    order and inserts go to their exact slots), so patched and rebuilt
    indexes serve byte-for-byte the same candidates."""
    indptr, rows, w = old
    tgt_of = np.repeat(np.arange(n_t, dtype=np.int64), np.diff(indptr))
    keep = ~_in_sorted(rows.astype(np.int64), changed_rows)
    k_t, k_r, k_w = tgt_of[keep], rows[keep], w[keep]
    sub = new_idx[changed_rows]
    valid = sub >= 0
    n_r = np.repeat(changed_rows.astype(np.int64),
                    sub.shape[1])[valid.ravel()]
    n_tg = sub[valid].astype(np.int64)
    n_w = new_llr[changed_rows][valid].astype(np.float32)
    order = np.lexsort((n_r, n_tg))
    n_tg, n_r, n_w = n_tg[order], n_r[order], n_w[order]
    pos = np.searchsorted(k_t * i_p + k_r.astype(np.int64),
                          n_tg * i_p + n_r)
    rows2 = np.insert(k_r, pos, n_r.astype(np.int32)).astype(np.int32)
    w2 = np.insert(k_w, pos, n_w).astype(np.float32)
    counts = (np.bincount(k_t, minlength=n_t)
              + np.bincount(n_tg, minlength=n_t))
    indptr2 = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    return indptr2, rows2, w2


@dataclasses.dataclass
class _TypeState:
    """Per-event-type incremental state.  Exactly one of ``C`` (dense
    impl) / ``sc`` (sparse impl) holds the co-occurrence counts."""

    codes: np.ndarray            # int64 sorted unique target-dict codes
    item_dict: IdDict            # strings of ``codes`` (id = position)
    local_of_target: np.ndarray  # target code → local item id (-1 unknown)
    pairs: np.ndarray            # int64 sorted deduped (u<<32 | i) keys
    col_counts: np.ndarray       # int64 [I_t] distinct users per target
    raw_items: List[np.ndarray]  # per-fold raw event items (local ids)
    raw_times: List[np.ndarray]  # per-fold raw event epoch seconds
    C: Optional[np.ndarray] = None       # int32 [I_p, I_t] counts (dense)
    sc: Optional[_SparseCounts] = None   # sorted-COO counts (sparse)
    idx: Optional[np.ndarray] = None   # int32 [I_p, K] indicator ids
    llr: Optional[np.ndarray] = None   # f32   [I_p, K] indicator scores

    @property
    def n_items(self) -> int:
        return len(self.codes)

    @property
    def counts(self):
        return self.sc if self.sc is not None else self.C


class URFoldState:
    """Resident incremental-training state for ONE Universal Recommender
    algorithm.  ``fold(delta_batch)`` folds a columnar delta (sharing
    this state's dictionaries — the scan_tail contract) and returns a
    fresh :class:`URModel` whose responses are identical to
    ``URAlgorithm.train`` over the full accumulated batch."""

    def __init__(self, algo_params, ds_params):
        from predictionio_tpu.models.universal_recommender.engine import (
            URAlgorithm,
        )

        self.params = algo_params
        self.ds_params = ds_params
        self.event_names: List[str] = list(ds_params.event_names)
        if not self.event_names:
            raise FoldUnsupported("no event_names configured")
        self.primary = self.event_names[0]
        blacklist = self.params.blacklist_events or [self.primary]
        unknown = [b for b in blacklist if b not in self.event_names]
        if unknown:
            raise ValueError(
                f"blacklist_events {unknown} not in event_names "
                f"{self.event_names}")
        bf_names = self.params.backfill_event_names or [self.primary]
        unknown_bf = [b for b in bf_names if b not in self.event_names]
        if unknown_bf:
            raise ValueError(
                f"backfill_event_names {unknown_bf} not in event_names "
                f"{self.event_names}")
        if self.params.checkpoint:
            raise FoldUnsupported(
                "checkpointed training is a batch-durability feature; "
                "the follower's unit of durability is the watermark")
        self.per_type = URAlgorithm.per_type_tuning(algo_params,
                                                    self.event_names)
        self.impl = fold_state_impl()
        self.user_dict = IdDict()
        self.user_of_code = np.full(1, -1, np.int32)
        self.row_counts = np.zeros(0, np.int64)
        self.types: Dict[str, _TypeState] = {
            name: _TypeState(
                codes=np.zeros(0, np.int64), item_dict=IdDict(),
                local_of_target=np.full(1, -1, np.int64),
                pairs=np.zeros(0, np.int64),
                C=(np.zeros((0, 0), np.int32) if self.impl == "dense"
                   else None),
                sc=(_SparseCounts.empty() if self.impl == "sparse"
                    else None),
                col_counts=np.zeros(0, np.int64),
                raw_items=[], raw_times=[])
            for name in self.event_names
        }
        self.batch: Optional[EventBatch] = None
        self._props: Dict[str, dict] = {}
        self._props_ever = False
        self._primary_perm = np.zeros(0, np.int64)
        self.generation = 0
        self.model = None
        self.last_fold_stats: Dict[str, dict] = {}

    # -- public entry ---------------------------------------------------------

    def fold(self, delta: EventBatch):
        """Fold one columnar delta (built with ``base=self.batch`` so the
        dictionaries are shared — the first call bootstraps from scratch)
        and return the new URModel."""
        if self.batch is None:
            self.batch = delta
        elif len(delta):
            self.batch = EventBatch.concat([self.batch, delta])
        self._apply(delta)
        self._check_budget()
        model = self._emit()
        self.generation += 1
        return model

    @classmethod
    def bootstrap(cls, algo_params, ds_params,
                  batch: EventBatch) -> "URFoldState":
        """Build state + first model from a full columnar batch."""
        state = cls(algo_params, ds_params)
        state.fold(batch)
        return state

    @property
    def state_mode(self) -> str:
        """'sparse' | 'dense' — the resident count representation (the
        pio_follow_state_mode gauge and /stats.json surface this)."""
        return self.impl

    def state_bytes(self) -> int:
        """Total resident bytes of the incremental state: the counts
        (sorted-COO cells — O(nnz) — or the legacy dense matrices) plus
        everything that GROWS with the log — the accumulated columnar
        batch, pair sets, raw popularity inputs and indicator tables.
        This is what ``PIO_FOLLOW_STATE_BYTES`` bounds: a long-lived
        follower at a steady event rate demotes to retrain mode when its
        resident history outgrows the budget, instead of leaking without
        limit."""
        total = 0
        for t in self.types.values():
            total += (t.sc.nbytes if t.sc is not None
                      else int(t.C.nbytes)) + int(t.pairs.nbytes)
            total += int(t.col_counts.nbytes) + int(t.local_of_target.nbytes)
            total += sum(int(a.nbytes) for a in t.raw_items)
            total += sum(int(a.nbytes) for a in t.raw_times)
            if t.idx is not None:
                total += int(t.idx.nbytes) + int(t.llr.nbytes)
        if self.batch is not None:
            b = self.batch
            for arr in (b.event_codes, b.entity_type_codes, b.entity_ids,
                        b.target_ids, b.times_us, b.ratings):
                total += int(arr.nbytes)
        return total

    # -- delta application ----------------------------------------------------

    def _check_budget(self) -> None:
        if self.state_bytes() > state_budget_bytes():
            raise FoldUnsupported(
                f"fold state {self.state_bytes()} B exceeds "
                f"PIO_FOLLOW_STATE_BYTES={state_budget_bytes()}")

    @staticmethod
    def _grow_translate(arr: np.ndarray, n: int) -> np.ndarray:
        if len(arr) >= n:
            return arr
        out = np.full(max(n, 1), -1, arr.dtype)
        out[: len(arr)] = arr
        return out

    def _apply(self, delta: EventBatch) -> None:
        """Mirror URDataSource.read_training incrementally over ``delta``
        and fold the translated pairs into the count state."""
        from predictionio_tpu.events.event import SPECIAL_EVENTS

        self.last_fold_stats = {}
        special = [delta.event_dict.id(n) for n in SPECIAL_EVENTS]
        special = np.asarray([c for c in special if c is not None], np.int32)
        props_changed = bool(len(delta)) and bool(
            np.isin(delta.event_codes, special).any())
        view = dataclasses.replace(delta, prop_columns=None)
        per_type_raw: Dict[str, tuple] = {}
        for name in self.event_names:
            sel = view.select_events([name])
            has_t = sel.target_ids >= 0
            per_type_raw[name] = (sel.entity_ids[has_t],
                                  sel.target_ids[has_t],
                                  sel.times_us[has_t].astype(np.float64) / 1e6)
        # users enroll exactly as read_training's per-type unique pass
        # does; enrollment ORDER only assigns internal user ids, and
        # responses are user-id-order independent (items carry the
        # tie-breaking ids)
        self.user_of_code = self._grow_translate(
            self.user_of_code, len(delta.entity_dict))
        n_users_before = len(self.user_dict)
        for name in self.event_names:
            e_codes = per_type_raw[name][0]
            for c in np.unique(e_codes):
                if self.user_of_code[c] < 0:
                    self.user_of_code[c] = self.user_dict.add(
                        delta.entity_dict.str(int(c)))
        new_users = len(self.user_dict) != n_users_before
        # item spaces: keep each type's sorted-unique target-code set —
        # the same set read_training's np.unique produces over the full
        # batch, so local item ids (and their tie order) match a
        # from-scratch retrain exactly even when an OLD code first
        # appears under a new type (mid-array insert + state remap)
        reshaped: Dict[str, bool] = {}
        for name in self.event_names:
            reshaped[name] = self._extend_item_space(
                name, per_type_raw[name][1], delta)
        primary_reshaped = reshaped[self.primary]
        if primary_reshaped:
            self._reshape_primary_rows()
        # translate + append raw events (popularity inputs)
        deltas: Dict[str, np.ndarray] = {}
        for name in self.event_names:
            st = self.types[name]
            e_codes, t_codes, times = per_type_raw[name]
            u = self.user_of_code[e_codes].astype(np.int64)
            i = st.local_of_target[t_codes]
            if len(i):
                st.raw_items.append(i.astype(np.int32))
                st.raw_times.append(times)
            keys = (np.unique(_pair_key(u, i)) if len(u)
                    else np.zeros(0, np.int64))
            if len(keys):
                keys = keys[~_in_sorted(keys, st.pairs)]
            deltas[name] = keys
        # counts: C_new = C + Δpᵀ·A_old + P_newᵀ·Δa per type (for the
        # primary, A ≡ P and the two terms cover (P+Δ)ᵀ(P+Δ) exactly —
        # the ΔᵀΔ diagonal term rides P_newᵀΔ).  Step A must see every
        # type's PRE-delta pair set; step C the POST-delta primary set.
        p_st = self.types[self.primary]
        dp = deltas[self.primary]
        dp_u, dp_i = _key_user(dp), _key_item(dp)
        touched: Dict[str, List[np.ndarray]] = {
            n: [] for n in self.event_names}
        for name in self.event_names:
            st = self.types[name]
            touched[name].append(_cross_scatter(
                st.counts, st.pairs, dp_u, dp_i, rows_from_delta=True))
        if len(dp):
            p_st.pairs = np.sort(np.concatenate([p_st.pairs, dp]))
            self.row_counts += np.bincount(dp_i, minlength=p_st.n_items)
        for name in self.event_names:
            st = self.types[name]
            da = deltas[name]
            if len(da) == 0:
                continue
            touched[name].append(_cross_scatter(
                st.counts, p_st.pairs, _key_user(da), _key_item(da),
                rows_from_delta=False))
            st.col_counts += np.bincount(_key_item(da),
                                         minlength=st.n_items)
            if name != self.primary:
                st.pairs = np.sort(np.concatenate([st.pairs, da]))
        # re-LLR scope per type (exact): a changed N or column marginal
        # couples every cell of that type; otherwise only rows whose C
        # cells or row marginal changed can differ
        rc_rows = np.unique(dp_i) if len(dp) else np.zeros(0, np.int64)
        for name in self.event_names:
            st = self.types[name]
            if st.n_items == 0 or p_st.n_items == 0:
                continue
            if (new_users or len(deltas[name]) or reshaped[name]
                    or primary_reshaped or st.idx is None):
                self._rellr_type(name, rows=None)
                continue
            parts = [rc_rows] + touched[name]
            rows = np.unique(np.concatenate(parts)) if parts else rc_rows
            if len(rows) == 0:
                self.last_fold_stats[name] = {"rows": 0, "mode": "skip"}
                continue
            self._rellr_type(name, rows=rows.astype(np.int64))
        if props_changed or not self._props_ever:
            # full-history recompute, not a delta merge: properties apply
            # in (eventTime, row) order, so a delta $set carrying an
            # EARLIER eventTime than an applied one must lose — an
            # append-order merge would get that wrong.  Cost is bounded
            # by PIO_FOLLOW_STATE_BYTES (breach demotes to retrain).
            self._props = {
                k: dict(v) for k, v in fold_properties(
                    self.batch, self.ds_params.item_entity_type).items()}
            self._props_ever = True
        self._last_remap = {"primary": primary_reshaped,
                            "types": dict(reshaped),
                            "props": props_changed}

    def _extend_item_space(self, name: str, t_codes: np.ndarray,
                           delta: EventBatch) -> bool:
        """Merge new target codes into the type's sorted code set;
        returns True when the type's item-id space changed shape (grew
        and/or existing ids shifted)."""
        st = self.types[name]
        st.local_of_target = self._grow_translate(
            st.local_of_target, len(delta.target_dict))
        if len(t_codes) == 0:
            return False
        uniq = np.unique(t_codes.astype(np.int64))
        new = uniq[~_in_sorted(uniq, st.codes)]
        if len(new) == 0:
            return False
        merged = np.union1d(st.codes, new)
        perm = np.searchsorted(merged, st.codes)  # old local → new local
        remapped = bool(len(st.codes)) and bool(
            (perm != np.arange(len(st.codes))).any())
        st.codes = merged
        st.item_dict = IdDict(
            [delta.target_dict.str(int(c)) for c in merged])
        lot = np.full(len(st.local_of_target), -1, np.int64)
        lot[merged] = np.arange(len(merged), dtype=np.int64)
        st.local_of_target = lot
        if remapped:
            # existing local ids shifted: remap everything keyed on them
            st.pairs = np.sort(
                (st.pairs & ~_LOW32) | perm[_key_item(st.pairs)])
            st.raw_items = [perm[a].astype(np.int32) for a in st.raw_items]
        # grow/permute the column-indexed state
        cc = np.zeros(len(merged), np.int64)
        if len(perm):
            cc[perm] = st.col_counts
        st.col_counts = cc
        if st.sc is not None:
            # absent cells stay absent; existing cells' cols follow the
            # (monotone) perm — no growth array needed, and pure growth
            # at the end (identity perm) costs nothing
            if remapped:
                st.sc.remap_cols(perm)
        else:
            C = np.zeros((st.C.shape[0], len(merged)), np.int32)
            if len(perm) and st.C.size:
                C[:, perm] = st.C
            st.C = C
        st.idx = st.llr = None   # shape changed: full re-LLR for the type
        if name == self.primary:
            self._primary_perm = perm
        return True

    def _reshape_primary_rows(self) -> None:
        """The PRIMARY item space changed shape: every type's C rows, the
        row marginals and indicator tables follow the new id order (the
        old→new row permutation _extend_item_space just computed)."""
        p_st = self.types[self.primary]
        n_p = p_st.n_items
        # primary pairs were already remapped; rebuild the row marginal
        # from them (delta pairs merge afterwards, in _apply)
        self.row_counts = (
            np.bincount(_key_item(p_st.pairs), minlength=n_p)
            .astype(np.int64) if len(p_st.pairs)
            else np.zeros(n_p, np.int64))
        perm = self._primary_perm
        for name in self.event_names:
            st = self.types[name]
            if st.sc is not None:
                st.sc.remap_rows(perm)
            else:
                C = np.zeros((n_p, st.C.shape[1]), np.int32)
                if len(perm) and st.C.size:
                    C[perm, :] = st.C
                st.C = C
            st.idx = st.llr = None

    def _rellr_type(self, name: str, rows: Optional[np.ndarray]) -> None:
        """Recompute LLR + top-k for ``rows`` of one type (None = all),
        bit-identically to what training would compute: sparse state
        routes through ``_llr_topk_sparse_rows`` (the row-scoped variant
        of the training host tail — same ``_llr_cells`` elementwise
        scores, same lax.top_k tie order), dense state through the same
        jitted dense kernels as before."""
        from predictionio_tpu.ops.cco import (
            _DenseRunner,
            _llr_topk_dense,
            _llr_topk_sparse_rows,
            topk_impl,
        )
        from predictionio_tpu.ops.pallas_kernels import pallas_mode

        st = self.types[name]
        p_st = self.types[self.primary]
        t_k, t_llr = self.per_type.get(
            name, (self.params.max_correlators_per_item,
                   self.params.min_llr))
        excl = name == self.primary
        n_t = st.n_items
        n_p = p_st.n_items
        n_total = float(len(self.user_dict))
        default_kernels = topk_impl() == "lax" and pallas_mode() == "off"
        small_dense = (default_kernels
                       and n_p * n_t * 4 <= _dense_rellr_bytes())
        if st.sc is not None and default_kernels and not small_dense:
            # the sparse tail: score only the resident nonzero cells
            # through the row-scoped variant of the training host tail
            width = min(t_k, n_t)
            if rows is None:
                crows, ccols, ccnt = st.sc.all_cells()
                rc_rows = self.row_counts
                self_cols = (np.arange(n_p, dtype=np.int64) if excl
                             else None)
                n_rows = n_p
            else:
                crows, ccols, ccnt = st.sc.row_cells(rows)
                rc_rows = self.row_counts[rows]
                self_cols = rows if excl else None
                n_rows = len(rows)
            s, i = _llr_topk_sparse_rows(
                crows, ccols, ccnt, rc_rows, st.col_counts, n_total,
                float(t_llr), top_k=width, n_rows=n_rows, n_cols=n_t,
                self_cols=self_cols)
            scores, idx = _DenseRunner.collect((s, i, n_t, t_k))
            if rows is None:
                st.idx = idx.astype(np.int32)
                st.llr = np.where(np.isfinite(scores), scores,
                                  0.0).astype(np.float32)
                self.last_fold_stats[name] = {"rows": n_p, "mode": "full"}
            else:
                st.idx[rows] = idx.astype(np.int32)
                st.llr[rows] = np.where(np.isfinite(scores), scores,
                                        0.0).astype(np.float32)
                self.last_fold_stats[name] = {"rows": int(len(rows)),
                                              "mode": "sliced"}
            return
        if st.sc is not None:
            # dense kernels over a transient materialization: the tiny-
            # catalog fast path (sub-ms regime, where the dense jit beats
            # the sparse gather+lexsort ~2× — and exactly the code path
            # the dense state and PR 8 always took), or a non-default
            # kernel selection (pallas top-k / pallas LLR) whose only
            # entry points are dense — there, unaffordable means the
            # follower must retrain
            if not small_dense and n_p * n_t * 4 > state_budget_bytes():
                raise FoldUnsupported(
                    f"non-default kernels ({topk_impl()}/{pallas_mode()}) "
                    f"need a dense [{n_p}, {n_t}] count pass that exceeds "
                    "PIO_FOLLOW_STATE_BYTES")
            C_full = st.sc.to_dense(n_p, n_t)
        else:
            C_full = st.C
        # non-default kernel selections (pallas top-k / pallas LLR) only
        # have full-matrix entry points — take the full path so the fold
        # reproduces exactly what training would have computed
        if rows is None or not default_kernels:
            s, i = _llr_topk_dense(
                jnp.asarray(C_full), jnp.asarray(self.row_counts),
                jnp.asarray(st.col_counts), n_total, float(t_llr),
                top_k=min(t_k, n_t), exclude_self=bool(excl),
                pallas=pallas_mode(), topk=topk_impl())
            scores, idx = _DenseRunner.collect((s, i, n_t, t_k))
            st.idx = idx.astype(np.int32)
            st.llr = np.where(np.isfinite(scores), scores,
                              0.0).astype(np.float32)
            self.last_fold_stats[name] = {"rows": C_full.shape[0],
                                          "mode": "full"}
            return
        scores, idx = _llr_topk_rows(
            C_full[rows], self.row_counts[rows], st.col_counts, n_total,
            float(t_llr), rows if excl else None, min(t_k, n_t))
        scores, idx = _DenseRunner.collect((scores, idx, n_t, t_k))
        st.idx[rows] = idx.astype(np.int32)
        st.llr[rows] = np.where(np.isfinite(scores), scores,
                                0.0).astype(np.float32)
        self.last_fold_stats[name] = {"rows": int(len(rows)),
                                      "mode": "sliced"}

    # -- model emission -------------------------------------------------------

    def _emit(self):
        """Build a fresh URModel from the state — the same construction
        URAlgorithm.train performs from its results dict."""
        from predictionio_tpu.models.universal_recommender.engine import (
            URModel,
        )
        from predictionio_tpu.models.universal_recommender.popmodel import (
            backfill_scores,
            parse_duration,
        )

        p_st = self.types[self.primary]
        n_items = p_st.n_items
        n_users = len(self.user_dict)
        if n_items == 0:
            raise ValueError(f"no {self.primary!r} events to train on")
        indicator_idx: Dict[str, np.ndarray] = {}
        indicator_llr: Dict[str, np.ndarray] = {}
        event_item_dicts: Dict[str, IdDict] = {}
        for name in self.event_names:
            st = self.types[name]
            if name != self.primary and st.n_items == 0:
                continue
            event_item_dicts[name] = st.item_dict
            indicator_idx[name] = st.idx.copy()
            indicator_llr[name] = st.llr.copy()
        user_seen = CSRLookup.from_pairs(
            _key_user(p_st.pairs), _key_item(p_st.pairs), n_users)
        bf_names = self.params.backfill_event_names or [self.primary]
        bf_items, bf_times = [], []
        for name in bf_names:
            st = self.types[name]
            items = (np.concatenate(st.raw_items) if st.raw_items
                     else np.zeros(0, np.int32))
            times = (np.concatenate(st.raw_times) if st.raw_times
                     else np.zeros(0, np.float64))
            if name == self.primary:
                bf_items.append(items)
                bf_times.append(times)
            else:
                translate = p_st.item_dict.lookup_many(
                    st.item_dict.strings())
                mapped = translate[items] if len(items) else items
                keep = mapped >= 0
                bf_items.append(mapped[keep])
                bf_times.append(times[keep])
        popularity = backfill_scores(
            self.params.backfill_type,
            np.concatenate(bf_items) if bf_items else np.zeros(0, np.int32),
            np.concatenate(bf_times) if bf_times else np.zeros(0, np.float64),
            n_items,
            parse_duration(self.params.backfill_duration),
        )
        blacklist_events = self.params.blacklist_events or [self.primary]
        user_seen_by_event: Dict[str, CSRLookup] = {}
        for name in blacklist_events:
            if name == self.primary or name not in event_item_dicts:
                continue
            st = self.types[name]
            translate = p_st.item_dict.lookup_many(st.item_dict.strings())
            u, i = _key_user(st.pairs), _key_item(st.pairs)
            mapped = translate[i] if len(i) else i
            keep = mapped >= 0
            user_seen_by_event[name] = CSRLookup.from_pairs(
                u[keep], mapped[keep], n_users)
        prev = self.model
        model = URModel(
            primary_event=self.primary,
            item_dict=p_st.item_dict,
            user_dict=IdDict(self.user_dict.strings()),
            indicator_idx=indicator_idx,
            indicator_llr=indicator_llr,
            event_item_dicts=event_item_dicts,
            popularity=popularity,
            item_properties=self._props,
            user_seen=user_seen,
            user_seen_by_event=user_seen_by_event,
        )
        self._carry_serving_state(model, prev)
        self.model = model
        return model

    def _carry_serving_state(self, model, prev) -> None:
        """Incremental serving-state handoff to the new generation, only
        where provably identical to a from-scratch rebuild; everything
        else stays generation-keyed (a fresh ``__dict__`` IS the
        invalidation)."""
        if prev is None:
            return
        remap = getattr(self, "_last_remap",
                        {"primary": True, "types": {}, "props": True})
        same_catalog = (not remap["primary"]
                        and len(model.item_dict) == len(prev.item_dict))
        if same_catalog and not remap["props"] \
                and model.item_properties is prev.item_properties:
            for attr in ("_prop_value_index", "_prop_date_array",
                         "_known_prop_names", "_date_off"):
                v = prev.__dict__.get(attr)
                if v is not None:
                    model.__dict__[attr] = v
        if not same_catalog:
            return
        inv_prev = prev.__dict__.get("_host_inv") or {}
        for name, old in inv_prev.items():
            if name not in model.indicator_idx or remap["types"].get(name):
                continue
            new_idx = model.indicator_idx[name]
            old_idx = prev.indicator_idx.get(name)
            if old_idx is None or old_idx.shape != new_idx.shape:
                continue
            new_llr = model.indicator_llr[name]
            diff = ((new_idx != old_idx)
                    | (new_llr != prev.indicator_llr[name])).any(axis=1)
            changed = np.flatnonzero(diff).astype(np.int64)
            i_p = new_idx.shape[0]
            n_t = max(len(model.event_item_dicts[name]), 1)
            if len(changed) == 0:
                patched = old
            elif len(changed) * 4 <= i_p:
                patched = _patch_inverted_csr(old, changed, new_idx,
                                              new_llr, n_t, i_p)
            else:
                continue   # too many rows moved: lazy rebuild is cheaper
            model.__dict__.setdefault("_host_inv", {})[name] = patched

    # -- checkpointing --------------------------------------------------------
    #
    # The numeric state serializes to one flat array dict (npz-able, no
    # pickle) + a small JSON meta; the accumulated EventBatch persists
    # separately through store.columnar.write_batch (which carries the
    # dictionaries and property columns).  Strings are NOT duplicated:
    # the user/item dictionaries reconstruct from the batch's dicts plus
    # the stored code maps.  ``state_fingerprint`` (crc32 over pairs +
    # marginals + code sets) makes bit-rot detectable: restore verifies
    # it and the caller restages on mismatch.

    def state_fingerprint(self) -> int:
        import zlib

        h = zlib.crc32(self.row_counts.tobytes())
        for name in self.event_names:
            st = self.types[name]
            h = zlib.crc32(np.ascontiguousarray(st.pairs).tobytes(), h)
            h = zlib.crc32(np.ascontiguousarray(st.col_counts).tobytes(), h)
            h = zlib.crc32(np.ascontiguousarray(st.codes).tobytes(), h)
        return int(h)

    def checkpoint_arrays(self) -> Tuple[Dict[str, np.ndarray], dict]:
        """(arrays, meta) capturing everything but the batch."""
        arrays: Dict[str, np.ndarray] = {
            "user_of_code": self.user_of_code,
            "row_counts": self.row_counts,
        }
        meta = {
            "version": 1,
            "impl": self.impl,
            "event_names": list(self.event_names),
            "n_users": len(self.user_dict),
            "props_ever": bool(self._props_ever),
            "generation": int(self.generation),
            "fingerprint": self.state_fingerprint(),
        }
        for k, name in enumerate(self.event_names):
            st = self.types[name]
            p = f"t{k}_"
            arrays[p + "codes"] = st.codes
            arrays[p + "local_of_target"] = st.local_of_target
            arrays[p + "pairs"] = st.pairs
            arrays[p + "col_counts"] = st.col_counts
            arrays[p + "raw_items"] = (
                np.concatenate(st.raw_items) if st.raw_items
                else np.zeros(0, np.int32))
            arrays[p + "raw_times"] = (
                np.concatenate(st.raw_times) if st.raw_times
                else np.zeros(0, np.float64))
            if st.idx is not None:
                arrays[p + "idx"] = st.idx
                arrays[p + "llr"] = st.llr
            if st.sc is not None:
                arrays[p + "cell_keys"] = st.sc.keys
                arrays[p + "cell_counts"] = st.sc.counts
            else:
                arrays[p + "dense_C"] = st.C
        return arrays, meta

    @classmethod
    def restore_checkpoint(cls, algo_params, ds_params, batch,
                           arrays, meta) -> "URFoldState":
        """Rebuild a fold state from ``checkpoint_arrays`` output + the
        persisted accumulated batch, verify the integrity fingerprint,
        and emit the model it describes.  Raises ValueError on ANY
        mismatch (version, config drift, corrupt arrays) — callers
        restage from the log."""
        if meta.get("version") != 1:
            raise ValueError(f"unknown checkpoint version {meta.get('version')}")
        state = cls(algo_params, ds_params)
        if list(meta.get("event_names") or []) != state.event_names:
            raise ValueError("checkpoint event_names do not match the "
                             "current engine params")
        state.batch = batch
        state.user_of_code = np.array(arrays["user_of_code"], np.int32)
        state.row_counts = np.array(arrays["row_counts"], np.int64)
        # the user dictionary reconstructs by inverting user_of_code
        # over the batch's entity dictionary (enrollment order is the
        # value order of the map)
        n_users = int(meta["n_users"])
        order = np.full(n_users, -1, np.int64)
        valid = np.flatnonzero(state.user_of_code >= 0)
        order[state.user_of_code[valid]] = valid
        if n_users and (order < 0).any():
            raise ValueError("checkpoint user map is not a bijection")
        state.user_dict = IdDict(
            [batch.entity_dict.str(int(c)) for c in order])
        state.impl = str(meta.get("impl") or "sparse")
        for k, name in enumerate(state.event_names):
            st = state.types[name]
            p = f"t{k}_"
            st.codes = np.array(arrays[p + "codes"], np.int64)
            st.item_dict = IdDict(
                [batch.target_dict.str(int(c)) for c in st.codes])
            st.local_of_target = np.array(arrays[p + "local_of_target"],
                                          np.int64)
            st.pairs = np.array(arrays[p + "pairs"], np.int64)
            st.col_counts = np.array(arrays[p + "col_counts"], np.int64)
            ri = np.array(arrays[p + "raw_items"], np.int32)
            rt = np.array(arrays[p + "raw_times"], np.float64)
            if len(ri) != len(rt):
                raise ValueError("checkpoint raw popularity arrays torn")
            st.raw_items = [ri] if len(ri) else []
            st.raw_times = [rt] if len(rt) else []
            if p + "idx" in arrays:
                st.idx = np.array(arrays[p + "idx"], np.int32)
                st.llr = np.array(arrays[p + "llr"], np.float32)
            if p + "cell_keys" in arrays:
                st.sc = _SparseCounts(np.array(arrays[p + "cell_keys"]),
                                      np.array(arrays[p + "cell_counts"]))
                st.C = None
            elif p + "dense_C" in arrays:
                st.C = np.array(arrays[p + "dense_C"], np.int32)
                st.sc = None
            else:
                raise ValueError(f"checkpoint carries no counts for {name}")
        if state.state_fingerprint() != int(meta["fingerprint"]):
            raise ValueError("checkpoint integrity fingerprint mismatch")
        if meta.get("props_ever"):
            state._props = {
                k2: dict(v) for k2, v in fold_properties(
                    batch, ds_params.item_entity_type).items()}
            state._props_ever = True
        state.generation = int(meta.get("generation", 0))
        state.model = None
        state.model = state._emit()
        return state
