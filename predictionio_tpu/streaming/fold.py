"""Incremental CCO fold: delta events → updated URModel, exactly.

A full UR retrain is (a) stage/parse the whole log, (b) translate to
dense id spaces, (c) the O(U·I_p·I_t) co-occurrence count pass, (d) LLR +
per-row top-k, (e) popularity/CSR/property epilogues.  PR 3's delta
staging already made (a) incremental; this module makes (b)–(e)
incremental too, by exploiting that CCO counts are ADDITIVE:

- :class:`URFoldState` keeps, per event type, the deduped (user, item)
  pair set, the co-occurrence counts and the LLR marginals
  (distinct-user row/column counts).  Counts are **sorted-COO by
  default** (:class:`_SparseCounts`: one int64 ``(row<<32|col)`` key +
  int32 count per nonzero cell — O(nnz), so a 1M-item catalog whose
  dense matrix would be 4 TB fits in tens of MB); the legacy dense
  int32 ``[I_p, I_t]`` matrices remain behind ``PIO_FOLLOW_STATE=dense``
  as an escape hatch and as the bit-exactness oracle the property tests
  compare against.  A delta fold applies ``C_new = C + Δpᵀ·A_old +
  P_newᵀ·Δa`` as vectorized scatter-adds (dense) or one sorted merge
  (sparse) over the delta's cross-join — O(delta footprint), never
  O(U·I²).
- LLR + top-k re-runs through the SAME scoring chain training uses
  (``ops.cco._llr_mask_scores`` / ``_llr_cells`` — XLA elementwise math
  is element-value-deterministic regardless of tensor shape), so every
  recomputed cell is bit-identical to a from-scratch retrain's value —
  exactness by construction, not by tolerance.  Sparse state routes
  re-LLR through ``ops.cco._llr_topk_sparse_rows`` (the row-scoped
  variant of the training host tail — same scores, same lax.top_k tie
  order); dense state through the row-sliced ``_llr_topk_rows_jit``.
  Only *affected* rows recompute: a delta that changes no global LLR
  input (no new users, no new target-side pairs for the type) re-LLRs
  just the touched primary rows; a marginal change (new user → N, new
  target pairs → column counts) forces that type's full re-LLR, because
  Dunning G² couples every cell to N and its column marginal.
- The emitted model is a NEW ``URModel`` object per fold — PR 4/7's
  generation-keyed serving caches (rule-mask LRU, value-mask/date LRUs,
  ``host_pop_order``) invalidate by model identity, so hot-swap
  correctness needs no extra plumbing.  Where cheap and provably safe,
  derived serving state carries over instead of rebuilding: the
  ``host_inverted`` CSR is row-patched when few indicator rows changed
  (``_patch_inverted_csr`` — array-identical to a from-scratch
  inversion), and the property indexes carry when no ``$set``-family
  event arrived.

State is bounded by ``PIO_FOLLOW_STATE_BYTES`` (default 1 GiB: counts
plus the log-proportional parts — accumulated batch, pair sets, raw
popularity inputs, indicator tables); past it :class:`FoldUnsupported`
tells the follower to fall back to full (delta-staged) retrains per
tick, which stay exact — the budget gates cost, never correctness.
With sparse counts the resident total is ≈ f(events), not catalog², so
the default budget holds fold mode at million-item catalogs.

The state is also checkpointable (``checkpoint_arrays`` /
``restore_checkpoint`` + the accumulated batch via
``store.columnar.write_batch``): the follower persists it beside its
watermark so a SIGKILL restart re-folds only the unapplied suffix
instead of reparsing the covered prefix (see ``streaming.follow``).
"""

from __future__ import annotations

import dataclasses
import os
import time
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.obs import metrics as _obs_metrics
from predictionio_tpu.ops.cco import _llr_mask_scores
from predictionio_tpu.store.columnar import (
    CSRLookup,
    EventBatch,
    IdDict,
    fold_properties,
)

_LOW32 = np.int64((1 << 32) - 1)

_REG = _obs_metrics.get_registry()
_M_RELLR_ROWS = _REG.counter(
    "pio_follow_rellr_rows_total",
    "Primary rows handled by a full (marginal-coupled) re-LLR pass, by "
    "outcome: certified (the selection-stability certificate proved the "
    "row's stored top-k keeps membership AND order under the new scores "
    "— its k stored scores refresh in O(k), no per-row sort) vs "
    "selected (routed through the per-row top-k re-selection)")
_M_EMIT = _REG.counter(
    "pio_follow_emit_total",
    "Derived-serving-state emissions by component (inverted | pop_order "
    "| popularity | user_seen | seen_by_event | props) and path: "
    "carried (previous "
    "generation's object reused, provably identical), patched "
    "(incremental splice/merge/weight-regather), rebuilt (from scratch)")


def rellr_prune_enabled() -> bool:
    """``PIO_FOLLOW_RELLR_PRUNE=off`` disables the selection-stability
    certificate — every full re-LLR re-selects every row (the PR-8/11
    behavior, kept as the exactness oracle the pruning property tests
    compare against)."""
    return os.environ.get("PIO_FOLLOW_RELLR_PRUNE", "").lower() not in (
        "off", "0", "false")


def rellr_workers() -> int:
    """``PIO_FOLLOW_RELLR_WORKERS``: worker threads for the chunked
    per-row top-k re-selection (the lexsort is the dominant full-re-LLR
    term and is embarrassingly row-parallel — numpy's sorts release the
    GIL on large arrays).  Default min(4, cores); 1 = inline."""
    try:
        w = int(os.environ.get("PIO_FOLLOW_RELLR_WORKERS", "0"))
    except ValueError:
        w = 0
    if w <= 0:
        w = min(4, os.cpu_count() or 1)
    return max(w, 1)


# below this many cells the pool's handoff overhead exceeds the sort
_RELLR_CHUNK_MIN_CELLS = 262_144


def _select_topk_chunked(rows: np.ndarray, cols: np.ndarray,
                         scores: np.ndarray, n_rows: int, width: int):
    """``ops.cco._select_topk_cells`` partitioned at row boundaries
    across a small thread pool (``PIO_FOLLOW_RELLR_WORKERS``).  Selection
    is independent per row, so the chunked outputs are identical to one
    global pass; ``rows`` must be sorted ascending (cell order)."""
    from predictionio_tpu.ops.cco import _select_topk_cells

    workers = rellr_workers()
    if workers <= 1 or len(rows) < _RELLR_CHUNK_MIN_CELLS or n_rows < 2:
        return _select_topk_cells(rows, cols, scores, n_rows, width)
    import concurrent.futures as _cf

    out_s = np.full((n_rows, width), -np.inf, np.float32)
    out_i = np.full((n_rows, width), -1, np.int32)
    n_chunks = min(workers * 2, n_rows)
    # split at row boundaries near equal CELL counts (not equal row
    # counts — cell skew is what unbalances the sorts)
    marks = (np.arange(1, n_chunks) * (len(rows) / n_chunks)).astype(np.int64)
    edges, prev = [0], 0
    for m in marks:
        r = int(rows[min(int(m), len(rows) - 1)])
        if r > prev:
            edges.append(r)
            prev = r
    edges.append(n_rows)

    def work(r0: int, r1: int) -> None:
        lo = np.searchsorted(rows, r0, side="left")
        hi = np.searchsorted(rows, r1, side="left")
        s, i = _select_topk_cells(rows[lo:hi] - r0, cols[lo:hi],
                                  scores[lo:hi], r1 - r0, width)
        out_s[r0:r1] = s
        out_i[r0:r1] = i

    with _cf.ThreadPoolExecutor(max_workers=workers) as ex:
        list(ex.map(lambda b: work(*b), zip(edges[:-1], edges[1:])))
    return out_s, out_i


def _merge_pop_order(old_order: np.ndarray, new_pop: np.ndarray,
                     changed_ids: np.ndarray) -> np.ndarray:
    """Incrementally maintain ``URModel.host_pop_order``: remove the
    changed ids from the previous generation's order (unchanged members
    keep their relative order — their keys didn't move), rank the
    changed ids by the SAME composite key ``host_topk_desc`` sorts by,
    and splice them in.  Array-identical to
    ``host_topk_desc(new_pop, n)[1]`` whenever ``changed_ids`` contains
    every id whose popularity differs from the old generation's plus
    every NEW id (supersets are fine — an unchanged member re-inserts at
    exactly its old slot, keys being distinct per id)."""
    from predictionio_tpu.models.common import topk_order_keys

    changed = np.asarray(changed_ids, np.int64)
    if len(changed) == 0:
        return old_order
    keys = topk_order_keys(np.asarray(new_pop, np.float32))
    keep = ~_in_sorted(old_order.astype(np.int64), changed)
    base = old_order[keep].astype(np.int32, copy=False)
    corder = changed[np.argsort(-keys[changed])].astype(np.int32)
    pos = np.searchsorted(-keys[base.astype(np.int64)],
                          -keys[corder.astype(np.int64)])
    return np.insert(base, pos, corder)


def _inverted_perm(idx: np.ndarray) -> np.ndarray:
    """The row-major flat positions of ``idx``'s valid cells in
    host_inverted CSR order (stable sort by target): the rebuild's
    weight array is exactly ``llr.ravel()[perm]``, so a generation whose
    CSR STRUCTURE is unchanged (same idx) refreshes its weights with one
    gather instead of re-inverting."""
    valid = idx >= 0
    flat = np.flatnonzero(valid.ravel())
    return flat[np.argsort(idx.ravel()[flat], kind="stable")]


def state_budget_bytes() -> int:
    """PIO_FOLLOW_STATE_BYTES caps the resident fold state — the count
    matrices (I_p·I_t·4 per event type) PLUS the log-proportional parts
    (accumulated columnar batch, pair sets, raw popularity inputs).
    Past it the follower retrains instead of folding (exact either way;
    the budget trades memory for fold latency)."""
    try:
        return max(int(os.environ.get("PIO_FOLLOW_STATE_BYTES",
                                      str(1 << 30))), 1)
    except ValueError:
        return 1 << 30


def fold_state_impl() -> str:
    """``PIO_FOLLOW_STATE``: 'sparse' (default) keeps sorted-COO counts —
    O(nnz) resident bytes, the representation that holds fold mode at
    million-item catalogs; 'dense' keeps the legacy [I_p, I_t] int32
    matrices (escape hatch + the oracle the sparse≡dense property tests
    compare against)."""
    conf = os.environ.get("PIO_FOLLOW_STATE", "auto").lower()
    return "dense" if conf == "dense" else "sparse"


def _dense_rellr_bytes() -> int:
    """Small-catalog fast path: a sparse-state FULL re-LLR whose dense
    [I_p, I_t] f32 matrix fits this budget (PIO_FOLLOW_DENSE_RELLR_BYTES,
    default 4 MiB) materializes it transiently and runs the jitted dense
    kernels — at tiny shapes (the sub-ms regime) the dense jit beats the
    sparse gather+lexsort ~2×, and it is the exact path the dense state
    (and PR 8) always took.  0 forces the sparse tail everywhere (the
    property tests use it so the sparse kernels stay covered at small
    shapes)."""
    try:
        return max(int(os.environ.get("PIO_FOLLOW_DENSE_RELLR_BYTES",
                                      str(4 << 20))), 0)
    except ValueError:
        return 4 << 20


class FoldUnsupported(RuntimeError):
    """The fold engine cannot (or should not) maintain incremental state
    for this engine/shape — the follower falls back to retrain mode."""


class _SparseCounts:
    """Sorted-COO co-occurrence counts: ``keys`` holds one int64
    ``(row << 32) | col`` per nonzero cell, ascending; ``counts`` the
    int32 count at that cell.  All mutations preserve the sort:

    - increments merge via searchsorted + np.insert (new cells land at
      their exact slots);
    - row/col remaps apply a STRICTLY INCREASING permutation (the
      old→new local-id map ``_extend_item_space`` computes is a
      searchsorted into the union of two sorted sets, hence monotone),
      so remapped keys stay ascending without a re-sort.
    """

    __slots__ = ("keys", "counts")

    def __init__(self, keys: np.ndarray, counts: np.ndarray):
        self.keys = np.asarray(keys, np.int64)
        self.counts = np.asarray(counts, np.int32)

    @classmethod
    def empty(cls) -> "_SparseCounts":
        return cls(np.zeros(0, np.int64), np.zeros(0, np.int32))

    @classmethod
    def from_dense(cls, C: np.ndarray) -> "_SparseCounts":
        rows, cols = np.nonzero(C)
        return cls(_pair_key(rows, cols), C[rows, cols].astype(np.int32))

    @property
    def nnz(self) -> int:
        return len(self.keys)

    @property
    def nbytes(self) -> int:
        return int(self.keys.nbytes) + int(self.counts.nbytes)

    def add_pairs(self, rows: np.ndarray, cols: np.ndarray) -> None:
        """counts[r, c] += multiplicity of (r, c) in the given pairs."""
        if len(rows) == 0:
            return
        uniq, inc = np.unique(_pair_key(rows, cols), return_counts=True)
        pos = np.searchsorted(self.keys, uniq)
        hit = np.zeros(len(uniq), bool)
        in_range = pos < len(self.keys)
        hit[in_range] = self.keys[pos[in_range]] == uniq[in_range]
        if hit.any():
            self.counts[pos[hit]] += inc[hit].astype(np.int32)
        miss = ~hit
        if miss.any():
            self.keys = np.insert(self.keys, pos[miss], uniq[miss])
            self.counts = np.insert(self.counts, pos[miss],
                                    inc[miss].astype(np.int32))

    def all_cells(self):
        """(rows, cols, counts) of every nonzero cell, (row, col)-asc."""
        return (self.keys >> np.int64(32), self.keys & _LOW32, self.counts)

    def row_cells(self, rows: np.ndarray):
        """Gather the cells of a sorted unique row subset: returns
        (local row index into ``rows``, col, count) — each row's cells
        are one contiguous key segment, bounded by two searchsorteds
        (the same repeat/arange expansion as ``_cross_scatter``)."""
        rows = np.asarray(rows, np.int64)
        starts = np.searchsorted(self.keys, rows << np.int64(32))
        ends = np.searchsorted(self.keys, (rows + 1) << np.int64(32))
        seg = ends - starts
        total = int(seg.sum())
        if total == 0:
            return (np.zeros(0, np.int64), np.zeros(0, np.int64),
                    np.zeros(0, np.int32))
        csum = np.cumsum(seg)
        within = np.arange(total, dtype=np.int64) - np.repeat(csum - seg, seg)
        idx = np.repeat(starts, seg) + within
        local = np.repeat(np.arange(len(rows), dtype=np.int64), seg)
        return local, self.keys[idx] & _LOW32, self.counts[idx]

    def remap_cols(self, perm: np.ndarray) -> None:
        """col → perm[col] (perm strictly increasing: order preserved)."""
        if self.nnz and len(perm):
            self.keys = (self.keys & ~_LOW32) \
                | np.asarray(perm, np.int64)[self.keys & _LOW32]

    def remap_rows(self, perm: np.ndarray) -> None:
        """row → perm[row] (perm strictly increasing: order preserved)."""
        if self.nnz and len(perm):
            self.keys = (np.asarray(perm, np.int64)[self.keys >> np.int64(32)]
                         << np.int64(32)) | (self.keys & _LOW32)

    def to_dense(self, n_rows: int, n_cols: int) -> np.ndarray:
        C = np.zeros((n_rows, n_cols), np.int32)
        if self.nnz:
            C[self.keys >> np.int64(32), self.keys & _LOW32] = self.counts
        return C


def _pair_key(u: np.ndarray, i: np.ndarray) -> np.ndarray:
    """(user id, type-local item id) → one sortable int64 key."""
    return (np.asarray(u, np.int64) << np.int64(32)) | np.asarray(i, np.int64)


def _key_item(key: np.ndarray) -> np.ndarray:
    return (key & _LOW32).astype(np.int64)


def _key_user(key: np.ndarray) -> np.ndarray:
    return (key >> np.int64(32)).astype(np.int64)


def _in_sorted(values: np.ndarray, sorted_arr: np.ndarray) -> np.ndarray:
    """Boolean membership of ``values`` in an ascending array."""
    if len(sorted_arr) == 0 or len(values) == 0:
        return np.zeros(len(values), bool)
    pos = np.searchsorted(sorted_arr, values)
    np.minimum(pos, len(sorted_arr) - 1, out=pos)
    return sorted_arr[pos] == values


def _cross_partners(pairs_sorted: np.ndarray, du: np.ndarray,
                    di: np.ndarray, rows_from_delta: bool):
    """Expand one side of the count update into its (row, col) increment
    pairs — shared by both count representations.

    For every delta pair (du[e], di[e]) and every partner item j in the
    OTHER side's per-user segment of ``pairs_sorted`` (deduped composite
    keys, (user, item)-ascending):

    - rows_from_delta=True:  (di[e], j)   (Δpᵀ·A — delta items are
      primary rows, partners are columns)
    - rows_from_delta=False: (j, di[e])   (Pᵀ·Δa — partners are
      primary rows, delta items are columns)

    One searchsorted pair bounds each user's partner segment; the flat
    expansion mirrors ``models.common.gather_csr_rows`` (repeat/arange,
    no per-pair Python loop).
    """
    if len(du) == 0 or len(pairs_sorted) == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    starts = np.searchsorted(pairs_sorted,
                             np.asarray(du, np.int64) << np.int64(32))
    ends = np.searchsorted(pairs_sorted,
                           (np.asarray(du, np.int64) + 1) << np.int64(32))
    seg = ends - starts                       # partners per delta pair
    total = int(seg.sum())
    if total == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    csum = np.cumsum(seg)
    within = np.arange(total, dtype=np.int64) - np.repeat(csum - seg, seg)
    partners = _key_item(pairs_sorted[np.repeat(starts, seg) + within])
    own = np.repeat(np.asarray(di, np.int64), seg)
    if rows_from_delta:
        return own, partners
    return partners, own


def _cross_scatter(counts, pairs_sorted: np.ndarray,
                   du: np.ndarray, di: np.ndarray,
                   rows_from_delta: bool) -> np.ndarray:
    """Apply one side of the count update (see ``_cross_partners``) to
    ``counts`` — a dense int32 matrix (scatter-add) or a
    :class:`_SparseCounts` (sorted merge) — and return the touched
    primary-row ids."""
    rows, cols = _cross_partners(pairs_sorted, du, di, rows_from_delta)
    if len(rows) == 0:
        return np.zeros(0, np.int64)
    if isinstance(counts, _SparseCounts):
        counts.add_pairs(rows, cols)
    else:
        np.add.at(counts, (rows, cols), 1)
    return np.unique(rows)


@partial(jax.jit, static_argnames=("top_k", "pallas"))
def _llr_topk_rows_jit(C_rows, rc_rows, cc, n_total, llr_threshold,
                       self_cols, top_k: int, pallas: str = "off"):
    """Row-sliced twin of ``ops.cco._llr_topk_dense``: the identical
    elementwise score chain (so each cell's f32 value is bit-identical —
    XLA elementwise math is element-value-deterministic regardless of
    tensor shape), the identical -inf self-pair placement (``self_cols``
    holds each row's GLOBAL primary id, -1 for non-primary types), the
    identical ``lax.top_k`` tie order."""
    scores = _llr_mask_scores(
        C_rows.astype(jnp.float32), rc_rows.astype(jnp.float32),
        cc.astype(jnp.float32), n_total, llr_threshold, pallas)
    cols = jnp.arange(scores.shape[1], dtype=jnp.int32)[None, :]
    is_self = (cols == self_cols[:, None]) & (self_cols[:, None] >= 0)
    scores = jnp.where(is_self, -jnp.inf, scores)
    s, i = jax.lax.top_k(scores, top_k)
    return s, i.astype(jnp.int32)


def _llr_topk_rows(C_rows: np.ndarray, rc_rows: np.ndarray,
                   cc: np.ndarray, n_total: float, llr_threshold: float,
                   self_rows: Optional[np.ndarray], top_k: int,
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Host wrapper: pad the row count to the next power of two so the
    jit compiles once per bucket, not per distinct slice size (padding
    rows score -inf everywhere — zero counts — and are dropped)."""
    n = C_rows.shape[0]
    pad = 1 << max((n - 1).bit_length(), 0)
    sc = np.full(pad, -1, np.int32)
    if self_rows is not None:
        sc[:n] = self_rows.astype(np.int32)
    if pad > n:
        C_rows = np.concatenate(
            [C_rows, np.zeros((pad - n, C_rows.shape[1]), C_rows.dtype)])
        rc_rows = np.concatenate(
            [rc_rows, np.zeros(pad - n, rc_rows.dtype)])
    s, i = _llr_topk_rows_jit(
        jnp.asarray(C_rows), jnp.asarray(rc_rows), jnp.asarray(cc),
        float(n_total), float(llr_threshold), jnp.asarray(sc),
        top_k=top_k)
    return np.asarray(s)[:n], np.asarray(i)[:n]


def _patch_inverted_csr(old_indptr: np.ndarray, old_rows: np.ndarray,
                        old_perm: np.ndarray, changed_rows: np.ndarray,
                        old_idx: np.ndarray, new_idx: np.ndarray,
                        n_t: int, i_p: int,
                        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Row-patch a host_inverted CSR's STRUCTURE: drop every posting
    entry whose primary row changed, insert the changed rows' new
    entries at their (target, row) positions, and splice the weight
    permutation (``_inverted_perm``) the same way — the caller gathers
    weights as ``new_llr.ravel()[perm]``, so the weights of UNCHANGED
    rows still refresh (an N bump moves every LLR value without moving
    any structure).  ``indptr`` updates as an indptr-delta splice: old
    prefix sums plus the prefix sums of (inserted − removed) per target
    — O(n_t + changed·K), never a full posting recount — and extends
    for target-space growth (new targets at the end) and primary-row
    growth (``changed_rows`` may exceed ``old_idx``'s rows), so pure
    catalog growth patches instead of rebuilding.  Output is
    ARRAY-IDENTICAL to rebuilding the inversion from the new indicator
    table (the rebuild's stable sort orders entries by (target, row);
    kept entries already follow that order and inserts go to their
    exact slots)."""
    k = new_idx.shape[1]
    changed_rows = np.asarray(changed_rows, np.int64)
    if len(old_indptr) < n_t + 1:
        old_indptr = np.concatenate([
            old_indptr,
            np.full(n_t + 1 - len(old_indptr), old_indptr[-1], np.int64)])
    tgt_of = np.repeat(np.arange(n_t, dtype=np.int64), np.diff(old_indptr))
    keep = ~_in_sorted(old_rows.astype(np.int64), changed_rows)
    k_t, k_r, k_p = tgt_of[keep], old_rows[keep], old_perm[keep]
    changed_old = changed_rows[changed_rows < old_idx.shape[0]]
    rem = old_idx[changed_old]
    rem_t = rem[rem >= 0].astype(np.int64)
    sub = new_idx[changed_rows]
    valid = sub >= 0
    n_r = np.repeat(changed_rows, k)[valid.ravel()]
    n_tg = sub[valid].astype(np.int64)
    n_flat = (changed_rows[:, None] * k
              + np.arange(k, dtype=np.int64)).ravel()[valid.ravel()]
    order = np.lexsort((n_r, n_tg))
    n_tg, n_r, n_flat = n_tg[order], n_r[order], n_flat[order]
    pos = np.searchsorted(k_t * i_p + k_r.astype(np.int64),
                          n_tg * i_p + n_r)
    rows2 = np.insert(k_r, pos, n_r.astype(np.int32)).astype(np.int32)
    perm2 = np.insert(k_p, pos, n_flat)
    delta = (np.bincount(n_tg, minlength=n_t)
             - np.bincount(rem_t, minlength=n_t))
    indptr2 = (old_indptr
               + np.concatenate([[0], np.cumsum(delta)])).astype(np.int64)
    return indptr2, rows2, perm2


@dataclasses.dataclass
class _TypeState:
    """Per-event-type incremental state.  Exactly one of ``C`` (dense
    impl) / ``sc`` (sparse impl) holds the co-occurrence counts."""

    codes: np.ndarray            # int64 sorted unique target-dict codes
    item_dict: IdDict            # strings of ``codes`` (id = position)
    local_of_target: np.ndarray  # target code → local item id (-1 unknown)
    pairs: np.ndarray            # int64 sorted deduped (u<<32 | i) keys
    col_counts: np.ndarray       # int64 [I_t] distinct users per target
    raw_items: List[np.ndarray]  # per-fold raw event items (local ids)
    raw_times: List[np.ndarray]  # per-fold raw event epoch seconds
    C: Optional[np.ndarray] = None       # int32 [I_p, I_t] counts (dense)
    sc: Optional[_SparseCounts] = None   # sorted-COO counts (sparse)
    idx: Optional[np.ndarray] = None   # int32 [I_p, K] indicator ids
    llr: Optional[np.ndarray] = None   # f32   [I_p, K] indicator scores
    # copy-on-write marks: an emitted model shares idx/llr and item_dict
    # by reference (the emit may run on the publisher thread); any
    # in-place mutation must clone first
    shared_tables: bool = False
    shared_dict: bool = False

    def mutable_tables(self) -> None:
        """COW guard before an IN-PLACE idx/llr write (sliced re-LLR,
        certified-score refresh): the emitted model may share these
        arrays."""
        if self.shared_tables:
            if self.idx is not None:
                self.idx = self.idx.copy()
                self.llr = self.llr.copy()
            self.shared_tables = False

    @property
    def n_items(self) -> int:
        return len(self.codes)

    @property
    def counts(self):
        return self.sc if self.sc is not None else self.C


@dataclasses.dataclass
class _EmitSnapshot:
    """Consistent emission view captured by ``URFoldState.fold_apply``:
    structure references (replaced-on-change) plus COW-marked shared
    arrays/dictionaries, so ``emit_snapshot`` — and the serving-bundle
    warm behind it — can run on the follower's publisher thread while
    the next delta applies on the fold loop."""

    generation: int
    n_users: int
    user_dict: IdDict
    types: Dict[str, dict]
    props: Dict[str, dict]
    pop_f32: Optional[np.ndarray]
    pop_changed: Optional[np.ndarray]
    remap: dict
    hints: Dict[str, dict]


class URFoldState:
    """Resident incremental-training state for ONE Universal Recommender
    algorithm.  ``fold(delta_batch)`` folds a columnar delta (sharing
    this state's dictionaries — the scan_tail contract) and returns a
    fresh :class:`URModel` whose responses are identical to
    ``URAlgorithm.train`` over the full accumulated batch."""

    def __init__(self, algo_params, ds_params):
        from predictionio_tpu.models.universal_recommender.engine import (
            URAlgorithm,
        )

        self.params = algo_params
        self.ds_params = ds_params
        self.event_names: List[str] = list(ds_params.event_names)
        if not self.event_names:
            raise FoldUnsupported("no event_names configured")
        self.primary = self.event_names[0]
        blacklist = self.params.blacklist_events or [self.primary]
        unknown = [b for b in blacklist if b not in self.event_names]
        if unknown:
            raise ValueError(
                f"blacklist_events {unknown} not in event_names "
                f"{self.event_names}")
        bf_names = self.params.backfill_event_names or [self.primary]
        unknown_bf = [b for b in bf_names if b not in self.event_names]
        if unknown_bf:
            raise ValueError(
                f"backfill_event_names {unknown_bf} not in event_names "
                f"{self.event_names}")
        if self.params.checkpoint:
            raise FoldUnsupported(
                "checkpointed training is a batch-durability feature; "
                "the follower's unit of durability is the watermark")
        self.per_type = URAlgorithm.per_type_tuning(algo_params,
                                                    self.event_names)
        self.impl = fold_state_impl()
        self.user_dict = IdDict()
        self.user_of_code = np.full(1, -1, np.int32)
        self.row_counts = np.zeros(0, np.int64)
        self.types: Dict[str, _TypeState] = {
            name: _TypeState(
                codes=np.zeros(0, np.int64), item_dict=IdDict(),
                local_of_target=np.full(1, -1, np.int64),
                pairs=np.zeros(0, np.int64),
                C=(np.zeros((0, 0), np.int32) if self.impl == "dense"
                   else None),
                sc=(_SparseCounts.empty() if self.impl == "sparse"
                    else None),
                col_counts=np.zeros(0, np.int64),
                raw_items=[], raw_times=[])
            for name in self.event_names
        }
        self.batch: Optional[EventBatch] = None
        self._props: Dict[str, dict] = {}
        self._props_ever = False
        self._primary_perm = np.zeros(0, np.int64)
        self.generation = 0
        self.model = None
        self.last_fold_stats: Dict[str, dict] = {}
        self.last_rellr_stats: Dict[str, dict] = {}
        self.last_phase_s: Dict[str, float] = {}
        self._rellr_s = 0.0
        self._user_dict_shared = False
        self._emit_hints: Dict[str, dict] = {}
        self._reshape_identity: Dict[str, bool] = {}
        # incremental popularity: running int64 per-item event counts +
        # observed time range, valid while the backfill window covers
        # every event (the default 3650-day window practically always
        # does); outside the supported config the emit recomputes from
        # the raw lists exactly as before
        bf_names = list(self.params.backfill_event_names or [self.primary])
        self._pop_incremental = (self.params.backfill_type == "popular"
                                 and bf_names == [self.primary])
        self._pop_duration = 0.0
        if self._pop_incremental:
            from predictionio_tpu.models.universal_recommender.popmodel \
                import parse_duration
            try:
                self._pop_duration = parse_duration(
                    self.params.backfill_duration)
            except (ValueError, TypeError):
                self._pop_incremental = False
        self._pop: Optional[list] = None     # [counts, t_min, t_max]
        self._pop_changed_now: Optional[np.ndarray] = None
        # emit-side caches (touched only by emit_snapshot, which runs
        # serialized — at most one emit at a time, in snapshot order)
        self._user_seen_cache: Optional[tuple] = None
        self._seen_by_ev_cache: Dict[str, tuple] = {}
        self._inv_cache: Dict[str, dict] = {}

    # -- public entry ---------------------------------------------------------

    def fold(self, delta: EventBatch):
        """Fold one columnar delta (built with ``base=self.batch`` so the
        dictionaries are shared — the first call bootstraps from scratch)
        and return the new URModel."""
        return self.emit_snapshot(self.fold_apply(delta))

    def fold_apply(self, delta: EventBatch) -> "_EmitSnapshot":
        """Apply one columnar delta to the resident state and return an
        emission snapshot — everything :meth:`emit_snapshot` needs,
        captured by reference for replace-on-change structures and
        marked copy-on-write for the in-place-mutated ones.  The split
        lets the follower run the emit (and the serving-bundle warm
        behind it) on its publisher thread while the NEXT delta applies
        on the fold loop — ticks pipeline instead of serializing
        fold+emit+warm."""
        t0 = time.perf_counter()
        self._rellr_s = 0.0
        if self.batch is None:
            self.batch = delta
        elif len(delta):
            self.batch = EventBatch.concat([self.batch, delta])
        self._apply(delta)
        self._check_budget()
        self.last_phase_s = {
            "apply": max(time.perf_counter() - t0 - self._rellr_s, 0.0),
            "rellr": self._rellr_s,
        }
        snap = self._snapshot()
        self.generation += 1
        return snap

    @classmethod
    def bootstrap(cls, algo_params, ds_params,
                  batch: EventBatch) -> "URFoldState":
        """Build state + first model from a full columnar batch."""
        state = cls(algo_params, ds_params)
        state.fold(batch)
        return state

    @property
    def state_mode(self) -> str:
        """'sparse' | 'dense' — the resident count representation (the
        pio_follow_state_mode gauge and /stats.json surface this)."""
        return self.impl

    def state_bytes(self) -> int:
        """Total resident bytes of the incremental state: the counts
        (sorted-COO cells — O(nnz) — or the legacy dense matrices) plus
        everything that GROWS with the log — the accumulated columnar
        batch, pair sets, raw popularity inputs and indicator tables.
        This is what ``PIO_FOLLOW_STATE_BYTES`` bounds: a long-lived
        follower at a steady event rate demotes to retrain mode when its
        resident history outgrows the budget, instead of leaking without
        limit."""
        total = 0
        for t in self.types.values():
            total += (t.sc.nbytes if t.sc is not None
                      else int(t.C.nbytes)) + int(t.pairs.nbytes)
            total += int(t.col_counts.nbytes) + int(t.local_of_target.nbytes)
            total += sum(int(a.nbytes) for a in t.raw_items)
            total += sum(int(a.nbytes) for a in t.raw_times)
            if t.idx is not None:
                total += int(t.idx.nbytes) + int(t.llr.nbytes)
        if self._pop is not None:
            total += int(self._pop[0].nbytes)
        # list(): the publisher thread's emit may be (re)installing cache
        # entries concurrently with this read-only walk
        for inv in list(self._inv_cache.values()):
            total += int(inv["perm"].nbytes)
        if self.batch is not None:
            b = self.batch
            for arr in (b.event_codes, b.entity_type_codes, b.entity_ids,
                        b.target_ids, b.times_us, b.ratings):
                total += int(arr.nbytes)
        return total

    # -- delta application ----------------------------------------------------

    def _check_budget(self) -> None:
        if self.state_bytes() > state_budget_bytes():
            raise FoldUnsupported(
                f"fold state {self.state_bytes()} B exceeds "
                f"PIO_FOLLOW_STATE_BYTES={state_budget_bytes()}")

    @staticmethod
    def _grow_translate(arr: np.ndarray, n: int) -> np.ndarray:
        if len(arr) >= n:
            return arr
        out = np.full(max(n, 1), -1, arr.dtype)
        out[: len(arr)] = arr
        return out

    def _apply(self, delta: EventBatch) -> None:
        """Mirror URDataSource.read_training incrementally over ``delta``
        and fold the translated pairs into the count state."""
        from predictionio_tpu.events.event import SPECIAL_EVENTS

        self.last_fold_stats = {}
        self.last_rellr_stats = {}
        self._emit_hints = {}
        self._reshape_identity = {}
        self._pop_changed_now = None
        special = [delta.event_dict.id(n) for n in SPECIAL_EVENTS]
        special = np.asarray([c for c in special if c is not None], np.int32)
        props_changed = bool(len(delta)) and bool(
            np.isin(delta.event_codes, special).any())
        view = dataclasses.replace(delta, prop_columns=None)
        per_type_raw: Dict[str, tuple] = {}
        for name in self.event_names:
            sel = view.select_events([name])
            has_t = sel.target_ids >= 0
            per_type_raw[name] = (sel.entity_ids[has_t],
                                  sel.target_ids[has_t],
                                  sel.times_us[has_t].astype(np.float64) / 1e6)
        # users enroll exactly as read_training's per-type unique pass
        # does; enrollment ORDER only assigns internal user ids, and
        # responses are user-id-order independent (items carry the
        # tie-breaking ids)
        self.user_of_code = self._grow_translate(
            self.user_of_code, len(delta.entity_dict))
        n_users_before = len(self.user_dict)
        for name in self.event_names:
            e_codes = per_type_raw[name][0]
            for c in np.unique(e_codes):
                if self.user_of_code[c] < 0:
                    if self._user_dict_shared:
                        # COW: the emitted model shares this dictionary
                        self.user_dict = self.user_dict.clone()
                        self._user_dict_shared = False
                    self.user_of_code[c] = self.user_dict.add(
                        delta.entity_dict.str(int(c)))
        new_users = len(self.user_dict) != n_users_before
        # item spaces: keep each type's sorted-unique target-code set —
        # the same set read_training's np.unique produces over the full
        # batch, so local item ids (and their tie order) match a
        # from-scratch retrain exactly even when an OLD code first
        # appears under a new type (mid-array insert + state remap)
        reshaped: Dict[str, bool] = {}
        for name in self.event_names:
            reshaped[name] = self._extend_item_space(
                name, per_type_raw[name][1], delta)
        primary_reshaped = reshaped[self.primary]
        if primary_reshaped:
            self._reshape_primary_rows()
        # translate + append raw events (popularity inputs)
        deltas: Dict[str, np.ndarray] = {}
        for name in self.event_names:
            st = self.types[name]
            e_codes, t_codes, times = per_type_raw[name]
            u = self.user_of_code[e_codes].astype(np.int64)
            i = st.local_of_target[t_codes]
            if len(i):
                st.raw_items.append(i.astype(np.int32))
                st.raw_times.append(times)
            if name == self.primary and self._pop_incremental:
                n_p_now = st.n_items
                if self._pop is None:
                    self._pop = [np.zeros(max(n_p_now, 1), np.int64),
                                 np.inf, -np.inf]
                cnts = self._pop[0]
                if len(cnts) < n_p_now:   # growth the reshape didn't see
                    grown = np.zeros(n_p_now, np.int64)
                    grown[:len(cnts)] = cnts
                    self._pop[0] = cnts = grown
                if len(i):
                    cnts += np.bincount(i, minlength=len(cnts))
                    self._pop[1] = min(self._pop[1], float(times.min()))
                    self._pop[2] = max(self._pop[2], float(times.max()))
                    self._pop_changed_now = np.unique(i).astype(np.int64)
                else:
                    self._pop_changed_now = np.zeros(0, np.int64)
            keys = (np.unique(_pair_key(u, i)) if len(u)
                    else np.zeros(0, np.int64))
            if len(keys):
                keys = keys[~_in_sorted(keys, st.pairs)]
            deltas[name] = keys
        # counts: C_new = C + Δpᵀ·A_old + P_newᵀ·Δa per type (for the
        # primary, A ≡ P and the two terms cover (P+Δ)ᵀ(P+Δ) exactly —
        # the ΔᵀΔ diagonal term rides P_newᵀΔ).  Step A must see every
        # type's PRE-delta pair set; step C the POST-delta primary set.
        p_st = self.types[self.primary]
        dp = deltas[self.primary]
        dp_u, dp_i = _key_user(dp), _key_item(dp)
        touched: Dict[str, List[np.ndarray]] = {
            n: [] for n in self.event_names}
        for name in self.event_names:
            st = self.types[name]
            touched[name].append(_cross_scatter(
                st.counts, st.pairs, dp_u, dp_i, rows_from_delta=True))
        if len(dp):
            p_st.pairs = np.sort(np.concatenate([p_st.pairs, dp]))
            self.row_counts += np.bincount(dp_i, minlength=p_st.n_items)
        for name in self.event_names:
            st = self.types[name]
            da = deltas[name]
            if len(da) == 0:
                continue
            touched[name].append(_cross_scatter(
                st.counts, p_st.pairs, _key_user(da), _key_item(da),
                rows_from_delta=False))
            st.col_counts += np.bincount(_key_item(da),
                                         minlength=st.n_items)
            if name != self.primary:
                st.pairs = np.sort(np.concatenate([st.pairs, da]))
        # re-LLR scope per type (exact): a changed N or column marginal
        # couples every cell of that type; otherwise only rows whose C
        # cells or row marginal changed can differ
        rc_rows = np.unique(dp_i) if len(dp) else np.zeros(0, np.int64)
        for name in self.event_names:
            st = self.types[name]
            if st.n_items == 0 or p_st.n_items == 0:
                continue
            if (new_users or len(deltas[name]) or reshaped[name]
                    or primary_reshaped or st.idx is None):
                self._rellr_type(name, rows=None)
                continue
            parts = [rc_rows] + touched[name]
            rows = np.unique(np.concatenate(parts)) if parts else rc_rows
            if len(rows) == 0:
                self.last_fold_stats[name] = {"rows": 0, "mode": "skip"}
                self._emit_hints[name] = {
                    "idx_rows": np.zeros(0, np.int64), "llr_changed": False}
                continue
            self._rellr_type(name, rows=rows.astype(np.int64))
        if props_changed or not self._props_ever:
            # full-history recompute, not a delta merge: properties apply
            # in (eventTime, row) order, so a delta $set carrying an
            # EARLIER eventTime than an applied one must lose — an
            # append-order merge would get that wrong.  Cost is bounded
            # by PIO_FOLLOW_STATE_BYTES (breach demotes to retrain).
            self._props = {
                k: dict(v) for k, v in fold_properties(
                    self.batch, self.ds_params.item_entity_type).items()}
            self._props_ever = True
        self._last_remap = {
            "primary": primary_reshaped,
            "primary_identity": self._reshape_identity.get(
                self.primary, True),
            "types": dict(reshaped),
            "type_identity": dict(self._reshape_identity),
            "props": props_changed,
        }

    def _extend_item_space(self, name: str, t_codes: np.ndarray,
                           delta: EventBatch) -> bool:
        """Merge new target codes into the type's sorted code set;
        returns True when the type's item-id space changed shape (grew
        and/or existing ids shifted)."""
        st = self.types[name]
        st.local_of_target = self._grow_translate(
            st.local_of_target, len(delta.target_dict))
        if len(t_codes) == 0:
            return False
        uniq = np.unique(t_codes.astype(np.int64))
        new = uniq[~_in_sorted(uniq, st.codes)]
        if len(new) == 0:
            return False
        merged = np.union1d(st.codes, new)
        perm = np.searchsorted(merged, st.codes)  # old local → new local
        remapped = bool(len(st.codes)) and bool(
            (perm != np.arange(len(st.codes))).any())
        n_old = len(st.codes)
        st.codes = merged
        self._reshape_identity[name] = not remapped
        if remapped or n_old == 0:
            st.item_dict = IdDict(
                [delta.target_dict.str(int(c)) for c in merged])
            st.shared_dict = False
        else:
            # pure end growth (every new code sorts after every old one):
            # existing local ids are stable, so the dictionary APPENDS
            # instead of rebuilding — O(new items), not O(catalog) —
            # with a COW clone when an emitted model shares it
            if st.shared_dict:
                st.item_dict = st.item_dict.clone()
                st.shared_dict = False
            for c in merged[n_old:]:
                st.item_dict.add(delta.target_dict.str(int(c)))
        lot = np.full(len(st.local_of_target), -1, np.int64)
        lot[merged] = np.arange(len(merged), dtype=np.int64)
        st.local_of_target = lot
        if remapped:
            # existing local ids shifted: remap everything keyed on them
            st.pairs = np.sort(
                (st.pairs & ~_LOW32) | perm[_key_item(st.pairs)])
            st.raw_items = [perm[a].astype(np.int32) for a in st.raw_items]
        # grow/permute the column-indexed state
        cc = np.zeros(len(merged), np.int64)
        if len(perm):
            cc[perm] = st.col_counts
        st.col_counts = cc
        if st.sc is not None:
            # absent cells stay absent; existing cells' cols follow the
            # (monotone) perm — no growth array needed, and pure growth
            # at the end (identity perm) costs nothing
            if remapped:
                st.sc.remap_cols(perm)
        else:
            C = np.zeros((st.C.shape[0], len(merged)), np.int32)
            if len(perm) and st.C.size:
                C[:, perm] = st.C
            st.C = C
        if remapped:
            # mid-array insert: stored indicator COLUMN ids shifted —
            # the full re-LLR rebuilds the tables from scratch
            st.idx = st.llr = None
        # else: pure end growth keeps every stored column id valid; the
        # marginal-triggered full re-LLR re-certifies each row against
        # the new columns (a new column can only ENTER a row's top-k
        # through the certificate's re-selection route)
        if name == self.primary:
            self._primary_perm = perm
        return True

    def _reshape_primary_rows(self) -> None:
        """The PRIMARY item space changed shape: every type's C rows, the
        row marginals and indicator tables follow the new id order (the
        old→new row permutation _extend_item_space just computed)."""
        p_st = self.types[self.primary]
        n_p = p_st.n_items
        # primary pairs were already remapped; rebuild the row marginal
        # from them (delta pairs merge afterwards, in _apply)
        self.row_counts = (
            np.bincount(_key_item(p_st.pairs), minlength=n_p)
            .astype(np.int64) if len(p_st.pairs)
            else np.zeros(n_p, np.int64))
        perm = self._primary_perm
        identity = self._reshape_identity.get(self.primary, True)
        if self._pop is not None:
            cnts = np.zeros(n_p, np.int64)
            if len(perm):
                cnts[perm] = self._pop[0][:len(perm)]
            self._pop[0] = cnts
        for name in self.event_names:
            st = self.types[name]
            if st.sc is not None:
                st.sc.remap_rows(perm)
            else:
                C = np.zeros((n_p, st.C.shape[1]), np.int32)
                if len(perm) and st.C.size:
                    C[perm, :] = st.C
                st.C = C
            if identity and st.idx is not None and st.idx.shape[0] <= n_p:
                # pure end growth of the primary space: existing rows
                # keep their ids — extend the indicator tables with
                # empty rows (the new rows re-select through their own
                # delta pairs) instead of discarding every stored
                # selection
                pad = n_p - st.idx.shape[0]
                if pad:
                    st.idx = np.concatenate([st.idx, np.full(
                        (pad, st.idx.shape[1]), -1, np.int32)])
                    st.llr = np.concatenate([st.llr, np.zeros(
                        (pad, st.llr.shape[1]), np.float32)])
                    st.shared_tables = False
            else:
                st.idx = st.llr = None

    def _rellr_type(self, name: str, rows: Optional[np.ndarray]) -> None:
        """Recompute LLR + top-k for ``rows`` of one type (None = all),
        bit-identically to what training would compute: sparse state
        routes through the cell-scoring + selection tail shared with the
        training host path (same ``_llr_cells`` elementwise scores, same
        lax.top_k tie order) — full passes PRUNED by the selection-
        stability certificate (:meth:`_rellr_full_sparse`) — dense state
        through the same jitted dense kernels as before."""
        t0 = time.perf_counter()
        try:
            self._rellr_type_inner(name, rows)
        finally:
            self._rellr_s += time.perf_counter() - t0

    def _rellr_type_inner(self, name: str,
                          rows: Optional[np.ndarray]) -> None:
        from predictionio_tpu.ops.cco import (
            _DenseRunner,
            _llr_topk_dense,
            _llr_topk_sparse_rows,
            topk_impl,
        )
        from predictionio_tpu.ops.pallas_kernels import pallas_mode

        st = self.types[name]
        p_st = self.types[self.primary]
        t_k, t_llr = self.per_type.get(
            name, (self.params.max_correlators_per_item,
                   self.params.min_llr))
        excl = name == self.primary
        n_t = st.n_items
        n_p = p_st.n_items
        n_total = float(len(self.user_dict))
        default_kernels = topk_impl() == "lax" and pallas_mode() == "off"
        small_dense = (default_kernels
                       and n_p * n_t * 4 <= _dense_rellr_bytes())
        if st.sc is not None and default_kernels and not small_dense:
            # the sparse tail: score only the resident nonzero cells
            # through the row-scoped variant of the training host tail
            width = min(t_k, n_t)
            if rows is None:
                self._rellr_full_sparse(name, st, width, t_k,
                                        float(t_llr), excl, n_p, n_t,
                                        n_total)
                return
            crows, ccols, ccnt = st.sc.row_cells(rows)
            rc_rows = self.row_counts[rows]
            self_cols = rows if excl else None
            s, i = _llr_topk_sparse_rows(
                crows, ccols, ccnt, rc_rows, st.col_counts, n_total,
                float(t_llr), top_k=width, n_rows=len(rows), n_cols=n_t,
                self_cols=self_cols)
            scores, idx = _DenseRunner.collect((s, i, n_t, t_k))
            st.mutable_tables()
            st.idx[rows] = idx.astype(np.int32)
            st.llr[rows] = np.where(np.isfinite(scores), scores,
                                    0.0).astype(np.float32)
            self.last_fold_stats[name] = {"rows": int(len(rows)),
                                          "mode": "sliced"}
            self._emit_hints[name] = {"idx_rows": rows,
                                      "llr_changed": True}
            return
        if st.sc is not None:
            # dense kernels over a transient materialization: the tiny-
            # catalog fast path (sub-ms regime, where the dense jit beats
            # the sparse gather+lexsort ~2× — and exactly the code path
            # the dense state and PR 8 always took), or a non-default
            # kernel selection (pallas top-k / pallas LLR) whose only
            # entry points are dense — there, unaffordable means the
            # follower must retrain
            if not small_dense and n_p * n_t * 4 > state_budget_bytes():
                raise FoldUnsupported(
                    f"non-default kernels ({topk_impl()}/{pallas_mode()}) "
                    f"need a dense [{n_p}, {n_t}] count pass that exceeds "
                    "PIO_FOLLOW_STATE_BYTES")
            C_full = st.sc.to_dense(n_p, n_t)
        else:
            C_full = st.C
        # non-default kernel selections (pallas top-k / pallas LLR) only
        # have full-matrix entry points — take the full path so the fold
        # reproduces exactly what training would have computed
        if rows is None or not default_kernels:
            s, i = _llr_topk_dense(
                jnp.asarray(C_full), jnp.asarray(self.row_counts),
                jnp.asarray(st.col_counts), n_total, float(t_llr),
                top_k=min(t_k, n_t), exclude_self=bool(excl),
                pallas=pallas_mode(), topk=topk_impl())
            scores, idx = _DenseRunner.collect((s, i, n_t, t_k))
            st.idx = idx.astype(np.int32)
            st.llr = np.where(np.isfinite(scores), scores,
                              0.0).astype(np.float32)
            st.shared_tables = False
            self.last_fold_stats[name] = {"rows": C_full.shape[0],
                                          "mode": "full"}
            self._emit_hints[name] = {"idx_rows": None,
                                      "llr_changed": True}
            return
        scores, idx = _llr_topk_rows(
            C_full[rows], self.row_counts[rows], st.col_counts, n_total,
            float(t_llr), rows if excl else None, min(t_k, n_t))
        scores, idx = _DenseRunner.collect((scores, idx, n_t, t_k))
        st.mutable_tables()
        st.idx[rows] = idx.astype(np.int32)
        st.llr[rows] = np.where(np.isfinite(scores), scores,
                                0.0).astype(np.float32)
        self.last_fold_stats[name] = {"rows": int(len(rows)),
                                      "mode": "sliced"}
        self._emit_hints[name] = {"idx_rows": rows, "llr_changed": True}

    def _rellr_full_sparse(self, name: str, st: _TypeState, width: int,
                           t_k: int, t_llr: float, excl: bool,
                           n_p: int, n_t: int, n_total: float) -> None:
        """Full (marginal-coupled) re-LLR of one type over the sparse
        state, PRUNED: ONE vectorized G² score pass over every resident
        nonzero cell — the same power-of-two-padded ``_llr_cells``
        program the unpruned tail runs, so every emitted score is
        bit-exact — followed by per-row top-k re-selection only where
        the selection could have moved.

        The per-row certificate is exact, not a bound, because it
        compares the NEW scores directly: a row keeps its stored
        selection iff (a) membership holds — with a full selection its
        weakest selected cell strictly beats its best non-selected cell
        (score TIES route to re-selection: the column tie-break could
        flip membership); with a deficit selection (< ``width`` stored)
        no non-selected cell scores finite and no selected cell fell to
        -inf — and (b) the stored order is still (score desc, col asc)-
        sorted under the new scores.  Certified rows provably keep
        membership AND order, so they refresh their k stored scores by
        one gather (O(k)) and skip the lexsort entirely; the rest
        re-select through ``_select_topk_cells``, chunked across
        ``PIO_FOLLOW_RELLR_WORKERS``.  ``PIO_FOLLOW_RELLR_PRUNE=off``
        forces every row down the re-selection route (the exactness
        oracle)."""
        from predictionio_tpu.ops.cco import _DenseRunner, _score_llr_cells

        crows, ccols, ccnt = st.sc.all_cells()
        if excl and len(crows):
            off = ccols != crows
            crows, ccols, ccnt = crows[off], ccols[off], ccnt[off]
        scores = _score_llr_cells(
            ccnt.astype(np.float32),
            self.row_counts[crows].astype(np.float32),
            st.col_counts[ccols].astype(np.float32), n_total, t_llr)
        old_idx = st.idx if (rellr_prune_enabled() and st.idx is not None
                             and st.llr is not None
                             and st.idx.shape == (n_p, t_k)) else None
        self.last_fold_stats[name] = {"rows": n_p, "mode": "full"}
        if old_idx is None:
            keep = scores > -np.inf
            s, i = _select_topk_chunked(
                crows[keep], ccols[keep], scores[keep], n_p, width)
            sc2, idx2 = _DenseRunner.collect((s, i, n_t, t_k))
            st.idx = idx2.astype(np.int32)
            st.llr = np.where(np.isfinite(sc2), sc2,
                              0.0).astype(np.float32)
            st.shared_tables = False
            if n_p:
                _M_RELLR_ROWS.inc(n_p, outcome="selected")
            self.last_rellr_stats[name] = {"certified": 0,
                                           "selected": int(n_p)}
            self._emit_hints[name] = {"idx_rows": None,
                                      "llr_changed": True}
            return
        # -- certification ------------------------------------------------
        valid = old_idx >= 0
        sel_count = valid.sum(axis=1)
        span = np.int64(n_t + 1)
        cell_flat = crows * span + ccols
        # ONE searchsorted: locate every stored cell among the COO cells
        # (they must exist — counts never decrease; a miss = corrupt
        # state degrades to -inf, which fails certification and
        # re-selects the row from the actual cells).  The located
        # positions both refresh the stored scores AND mark the cells as
        # selected — no second membership pass.
        vr, vj = np.nonzero(valid)
        vc = old_idx[vr, vj].astype(np.int64)
        new_sel = np.full((n_p, t_k), -np.inf, np.float32)
        is_sel = np.zeros(len(cell_flat), bool)
        if len(vr) and len(cell_flat):
            key = vr.astype(np.int64) * span + vc
            pos = np.searchsorted(cell_flat, key)
            np.minimum(pos, len(cell_flat) - 1, out=pos)
            hit = cell_flat[pos] == key
            is_sel[pos[hit]] = True
            new_sel[vr[hit], vj[hit]] = scores[pos[hit]]
        # per-row best non-selected contender (segment max; cells are
        # (row, col)-sorted so each row is one contiguous run)
        max_nonsel = np.full(n_p, -np.inf, np.float32)
        starts = np.zeros(0, np.int64)
        if len(crows):
            non_scores = np.where(is_sel, np.float32(-np.inf), scores)
            starts = np.concatenate(
                [[0], np.flatnonzero(np.diff(crows)) + 1])
            max_nonsel[crows[starts]] = np.maximum.reduceat(
                non_scores, starts)
        min_sel = np.where(valid, new_sel, np.inf).min(axis=1)
        # a SCORE tie at the membership boundary is still exactly
        # decidable: under (score desc, col asc) the tied selected cells
        # win iff their largest column is below the tied contenders'
        # smallest column (common in uniform-count catalogs, where whole
        # rows share one score — without this, every such row would
        # re-sort on every N bump)
        nonsel_tie_min = np.full(n_p, int(span), np.int64)
        if len(crows):
            tie_cols = np.where(~is_sel & (scores == max_nonsel[crows]),
                                ccols, span)
            nonsel_tie_min[crows[starts]] = np.minimum.reduceat(
                tie_cols, starts)
        sel_tie_max = np.where(
            valid & (new_sel == min_sel[:, None]), old_idx,
            -1).max(axis=1).astype(np.int64) if t_k else \
            np.full(n_p, -1, np.int64)
        tie_ok = (min_sel > -np.inf) & (sel_tie_max < nonsel_tie_min)
        member_ok = np.where(
            sel_count == width,
            (min_sel > max_nonsel)
            | ((min_sel == max_nonsel) & tie_ok),
            (max_nonsel == -np.inf) & (min_sel > -np.inf))
        if t_k > 1:
            s0, s1 = new_sel[:, :-1], new_sel[:, 1:]
            i0 = old_idx[:, :-1].astype(np.int64)
            i1 = old_idx[:, 1:].astype(np.int64)
            # padding forms a suffix, so valid[:, 1:] marks exactly the
            # adjacent pairs that are BOTH valid
            pair_ok = ((s0 > s1) | ((s0 == s1) & (i0 < i1))
                       | ~valid[:, 1:])
            certified = member_ok & pair_ok.all(axis=1)
        else:
            certified = member_ok
        uncert = np.flatnonzero(~certified).astype(np.int64)
        idx_new = old_idx.copy()
        llr_new = np.zeros((n_p, t_k), np.float32)
        cert2d = certified[:, None] & valid
        llr_new[cert2d] = new_sel[cert2d]
        if len(uncert):
            keep = scores > -np.inf
            kr, kc, ks = crows[keep], ccols[keep], scores[keep]
            lo = np.searchsorted(kr, uncert, side="left")
            hi = np.searchsorted(kr, uncert, side="right")
            seg = hi - lo
            total = int(seg.sum())
            if total:
                csum = np.cumsum(seg)
                within = np.arange(total, dtype=np.int64) \
                    - np.repeat(csum - seg, seg)
                gidx = np.repeat(lo, seg) + within
                local = np.repeat(
                    np.arange(len(uncert), dtype=np.int64), seg)
                s_u, i_u = _select_topk_chunked(
                    local, kc[gidx], ks[gidx], len(uncert), width)
            else:
                s_u = np.full((len(uncert), width), -np.inf, np.float32)
                i_u = np.full((len(uncert), width), -1, np.int32)
            sc2, idx2 = _DenseRunner.collect((s_u, i_u, n_t, t_k))
            idx_new[uncert] = idx2.astype(np.int32)
            llr_new[uncert] = np.where(np.isfinite(sc2), sc2,
                                       0.0).astype(np.float32)
        st.idx, st.llr = idx_new, llr_new
        st.shared_tables = False
        n_cert = int(n_p - len(uncert))
        if n_cert:
            _M_RELLR_ROWS.inc(n_cert, outcome="certified")
        if len(uncert):
            _M_RELLR_ROWS.inc(int(len(uncert)), outcome="selected")
        self.last_rellr_stats[name] = {"certified": n_cert,
                                       "selected": int(len(uncert))}
        self._emit_hints[name] = {"idx_rows": uncert, "llr_changed": True}

    # -- model emission -------------------------------------------------------

    def _snapshot(self) -> "_EmitSnapshot":
        """Capture a consistent emission view of the state: references
        for structures that are REPLACED on change (pairs, dicts, props,
        per-fold raw arrays), copies for the in-place-mutated popularity
        counts, and copy-on-write marks on the indicator tables and
        dictionaries the emitted model will share.  After this call the
        fold loop may apply the next delta while the emit runs."""
        pop_f32, pop_changed = self._pop_view()
        types: Dict[str, dict] = {}
        for name in self.event_names:
            st = self.types[name]
            types[name] = {
                "idx": st.idx, "llr": st.llr, "pairs": st.pairs,
                "item_dict": st.item_dict, "n_items": st.n_items,
                "raw_items": list(st.raw_items),
                "raw_times": list(st.raw_times),
            }
            st.shared_tables = True
            st.shared_dict = True
        self._user_dict_shared = True
        return _EmitSnapshot(
            generation=self.generation + 1,
            n_users=len(self.user_dict),
            user_dict=self.user_dict,
            types=types,
            props=self._props,
            pop_f32=pop_f32,
            pop_changed=pop_changed,
            remap=dict(getattr(self, "_last_remap", None)
                       or {"primary": True, "primary_identity": False,
                           "types": {}, "type_identity": {},
                           "props": True}),
            hints=dict(self._emit_hints),
        )

    def _pop_view(self):
        """(popularity f32, changed ids) when the incremental counts
        are valid — the counts convert to EXACTLY what backfill_scores
        computes, provided no event has fallen out of the (end-anchored)
        window: end = max_t + 1e-6 shifts with every append, so validity
        is min_t >= end - duration, the same float64 arithmetic the full
        recompute applies.  (None, None) otherwise → full recompute."""
        if not self._pop_incremental or self._pop is None:
            return None, None
        cnts, t_min, t_max = self._pop
        if np.isfinite(t_max) \
                and t_min < (float(t_max) + 1e-6) - float(self._pop_duration):
            return None, None
        return cnts.astype(np.float32), \
            (self._pop_changed_now if self._pop_changed_now is not None
             else None)

    def _emit(self):
        """Build a fresh URModel from the current state (snapshot taken
        inline) — the restore/bootstrap entry; the follower's pipelined
        path uses fold_apply + emit_snapshot instead."""
        return self.emit_snapshot(self._snapshot())

    def emit_snapshot(self, snap: "_EmitSnapshot"):
        """Build the URModel one snapshot describes — array-identical to
        the construction ``URAlgorithm.train`` performs — reusing
        derived serving state across generations wherever provably
        identical.  Runs off the fold loop when the follower pipelines
        (streaming.follow's publisher thread); emits are serialized and
        in snapshot order, so the prev-generation chain (``self.model``)
        stays consistent."""
        from predictionio_tpu.models.universal_recommender.engine import (
            URModel,
        )
        from predictionio_tpu.models.universal_recommender.popmodel import (
            backfill_scores,
            parse_duration,
        )

        t0 = time.perf_counter()
        p = snap.types[self.primary]
        n_items = p["n_items"]
        n_users = snap.n_users
        if n_items == 0:
            raise ValueError(f"no {self.primary!r} events to train on")
        indicator_idx: Dict[str, np.ndarray] = {}
        indicator_llr: Dict[str, np.ndarray] = {}
        event_item_dicts: Dict[str, IdDict] = {}
        for name in self.event_names:
            t = snap.types[name]
            if name != self.primary and t["n_items"] == 0:
                continue
            event_item_dicts[name] = t["item_dict"]
            indicator_idx[name] = t["idx"]
            indicator_llr[name] = t["llr"]
        # user → seen primary items: the resident pair set is already
        # (user, item)-sorted and deduped, so a changed generation
        # rebuilds in O(pairs) with NO sort; an untouched one carries
        # the previous CSR object outright
        pairs = p["pairs"]
        us_cache = self._user_seen_cache
        if us_cache is not None and us_cache[0] is pairs \
                and us_cache[1] == n_users:
            user_seen = us_cache[2]
            _M_EMIT.inc(1, component="user_seen", path="carried")
        else:
            user_seen = CSRLookup.from_sorted_pairs(
                _key_user(pairs), _key_item(pairs), n_users)
            self._user_seen_cache = (pairs, n_users, user_seen)
            _M_EMIT.inc(1, component="user_seen", path="rebuilt")
        bf_names = self.params.backfill_event_names or [self.primary]
        if snap.pop_f32 is not None:
            popularity = snap.pop_f32
            _M_EMIT.inc(1, component="popularity", path="patched")
        else:
            _M_EMIT.inc(1, component="popularity", path="rebuilt")
            bf_items, bf_times = [], []
            for name in bf_names:
                t = snap.types[name]
                items = (np.concatenate(t["raw_items"]) if t["raw_items"]
                         else np.zeros(0, np.int32))
                times = (np.concatenate(t["raw_times"]) if t["raw_times"]
                         else np.zeros(0, np.float64))
                if name == self.primary:
                    bf_items.append(items)
                    bf_times.append(times)
                else:
                    translate = p["item_dict"].lookup_many(
                        t["item_dict"].strings())
                    mapped = translate[items] if len(items) else items
                    keep = mapped >= 0
                    bf_items.append(mapped[keep])
                    bf_times.append(times[keep])
            popularity = backfill_scores(
                self.params.backfill_type,
                np.concatenate(bf_items) if bf_items
                else np.zeros(0, np.int32),
                np.concatenate(bf_times) if bf_times
                else np.zeros(0, np.float64),
                n_items,
                parse_duration(self.params.backfill_duration),
            )
        blacklist_events = self.params.blacklist_events or [self.primary]
        user_seen_by_event: Dict[str, CSRLookup] = {}
        for name in blacklist_events:
            if name == self.primary or name not in event_item_dicts:
                continue
            t = snap.types[name]
            cache = self._seen_by_ev_cache.get(name)
            if cache is not None and cache[0] is t["pairs"] \
                    and cache[1] is p["item_dict"] \
                    and cache[2] is t["item_dict"] and cache[3] == n_users:
                user_seen_by_event[name] = cache[4]
                _M_EMIT.inc(1, component="seen_by_event", path="carried")
                continue
            translate = p["item_dict"].lookup_many(
                t["item_dict"].strings())
            u, i = _key_user(t["pairs"]), _key_item(t["pairs"])
            mapped = translate[i] if len(i) else i
            keep = mapped >= 0
            csr = CSRLookup.from_pairs(u[keep], mapped[keep], n_users)
            user_seen_by_event[name] = csr
            self._seen_by_ev_cache[name] = (
                t["pairs"], p["item_dict"], t["item_dict"], n_users, csr)
            _M_EMIT.inc(1, component="seen_by_event", path="rebuilt")
        prev = self.model
        model = URModel(
            primary_event=self.primary,
            item_dict=p["item_dict"],
            user_dict=snap.user_dict,
            indicator_idx=indicator_idx,
            indicator_llr=indicator_llr,
            event_item_dicts=event_item_dicts,
            popularity=popularity,
            item_properties=snap.props,
            user_seen=user_seen,
            user_seen_by_event=user_seen_by_event,
        )
        self._carry_serving_state(model, prev, snap)
        self.model = model
        self.last_emit_s = time.perf_counter() - t0
        return model

    def _carry_serving_state(self, model, prev,
                             snap: "_EmitSnapshot") -> None:
        """Incremental serving-state handoff to the new generation, only
        where provably identical to a from-scratch rebuild; everything
        else stays generation-keyed (a fresh ``__dict__`` IS the
        invalidation).  Pure end growth of the catalog (identity perms)
        patches rather than invalidates: the host_inverted CSR splices
        the changed rows (and regathers ALL weights through the cached
        inversion permutation — an N bump moves every LLR value without
        moving structure), and host_pop_order merges (changed ∪ new)
        ids into the previous order by the exact host_topk_desc key."""
        if prev is None:
            return
        # provenance for the model plane's delta publisher: which ids
        # moved in pop_order and which indicator rows changed per type —
        # the EXACT arguments of the patch/merge replays below, so the
        # publisher can ship instructions instead of rewritten arrays
        # and plane workers replay the same functions bit-exactly
        # (streaming.plane).  Keyed to ``prev`` by weakref: the stash is
        # only valid relative to the generation it patched from.
        import weakref

        prov: Dict[str, object] = {"prev": weakref.ref(prev), "inv": {}}
        model.__dict__["_plane_prov"] = prov
        remap = snap.remap
        same_catalog = (not remap["primary"]
                        and len(model.item_dict) == len(prev.item_dict))
        grown_ok = same_catalog or (remap["primary"]
                                    and remap.get("primary_identity"))
        props_carried = (same_catalog and not remap["props"]
                         and model.item_properties is prev.item_properties)
        if props_carried:
            carried = False
            for attr in ("_prop_value_index", "_prop_date_array",
                         "_known_prop_names", "_date_off"):
                v = prev.__dict__.get(attr)
                if v is not None:
                    model.__dict__[attr] = v
                    carried = True
            if carried:
                _M_EMIT.inc(1, component="props", path="carried")
        # rule-mask / value-mask / date caches: pure functions of
        # (item_dict, item_properties) — exactly what props_carried
        # proves unchanged, so the LRU objects survive the swap (and a
        # props change records the drop instead of flushing silently)
        model.adopt_rule_caches(prev, carry=props_carried)
        if not grown_ok:
            return
        # -- serve-level provenance (serve.response_cache) ---------------
        # The response cache needs per-type changed primary rows and
        # changed popularity ids INDEPENDENT of whether this process ever
        # built the host inverted index or pop order, so they come
        # straight from the emit hints (the same rows the CSR patch
        # trusts for bit-exactness) + COW object identity for untouched
        # types.  Any unknowable piece (full re-select, column remap,
        # non-incremental popularity) withholds the stash entirely — the
        # cache then full-flushes, never serves stale.
        n_new, n_old = len(model.item_dict), len(prev.item_dict)
        grow = (np.arange(n_old, n_new, dtype=np.int64) if n_new > n_old
                else None)
        sinv: Dict[str, np.ndarray] = {}
        serve_ok = set(model.indicator_idx) == set(prev.indicator_idx)
        for name in (model.indicator_idx if serve_ok else ()):
            if remap["types"].get(name) \
                    and not remap["type_identity"].get(name):
                serve_ok = False   # target-column ids shifted
                break
            new_idx = model.indicator_idx[name]
            old_idx = prev.indicator_idx.get(name)
            if new_idx is old_idx:
                changed = np.zeros(0, np.int64)   # COW: provably untouched
            elif new_idx is None or old_idx is None:
                serve_ok = False
                break
            else:
                hint = snap.hints.get(name)
                if hint is None or hint.get("idx_rows") is None:
                    serve_ok = False   # full re-select: any row may move
                    break
                changed = np.asarray(hint["idx_rows"], np.int64)
                if new_idx.shape[0] > old_idx.shape[0]:
                    changed = np.union1d(changed, np.arange(
                        old_idx.shape[0], new_idx.shape[0],
                        dtype=np.int64))
            sinv[name] = changed
        if serve_ok and snap.pop_changed is not None:
            pchg = np.asarray(snap.pop_changed, np.int64)
            if grow is not None:
                pchg = np.union1d(pchg, grow)
            prov["serve"] = {"inv": sinv, "pop": pchg}
        # -- host_pop_order: incremental merge of (changed ∪ new) ids ----
        old_order = prev.__dict__.get("_host_pop_order")
        if old_order is not None and snap.pop_changed is not None:
            n_new, n_old = len(model.item_dict), len(prev.item_dict)
            changed = snap.pop_changed
            if n_new > n_old:
                changed = np.union1d(
                    changed, np.arange(n_old, n_new, dtype=np.int64))
            model.__dict__["_host_pop_order"] = _merge_pop_order(
                old_order, np.asarray(model.popularity, np.float32),
                changed)
            prov["pop_order"] = np.asarray(changed, np.int64)
            _M_EMIT.inc(1, component="pop_order",
                        path="patched" if len(changed) else "carried")
        # -- host_inverted CSR: carry / weight-regather / row-patch ------
        inv_prev = prev.__dict__.get("_host_inv") or {}
        for name, old in inv_prev.items():
            if name not in model.indicator_idx:
                continue
            if remap["types"].get(name) \
                    and not remap["type_identity"].get(name):
                self._inv_cache.pop(name, None)
                continue   # column ids shifted: rebuild from scratch
            new_idx = model.indicator_idx[name]
            old_idx = prev.indicator_idx.get(name)
            if old_idx is None or old_idx.ndim != 2 or new_idx.ndim != 2 \
                    or old_idx.shape[1] != new_idx.shape[1] \
                    or old_idx.shape[0] > new_idx.shape[0]:
                self._inv_cache.pop(name, None)
                continue
            new_llr = model.indicator_llr[name]
            i_p = new_idx.shape[0]
            n_t = max(len(model.event_item_dicts[name]), 1)
            hint = snap.hints.get(name)
            if hint is not None and hint["idx_rows"] is not None:
                changed = np.asarray(hint["idx_rows"], np.int64)
                llr_changed = bool(hint["llr_changed"])
            else:
                # no hint (restored state / non-default kernels): full
                # structural diff, row-extended for catalog growth
                rows_eq = min(old_idx.shape[0], i_p)
                diff = (new_idx[:rows_eq] != old_idx[:rows_eq]).any(axis=1)
                changed = np.flatnonzero(diff).astype(np.int64)
                llr_changed = True
            if old_idx.shape[0] < i_p:
                changed = np.union1d(
                    changed,
                    np.arange(old_idx.shape[0], i_p, dtype=np.int64))
            if len(changed) * 2 > i_p:
                self._inv_cache.pop(name, None)
                continue   # most rows moved: a from-scratch inversion
                # (in the warm, off the fold loop) is the better deal
            cache = self._inv_cache.get(name)
            if cache is not None and cache["for_idx"] is old_idx:
                perm = cache["perm"]
            else:
                perm = _inverted_perm(old_idx)
            if len(changed) == 0:
                if not llr_changed:
                    model.__dict__.setdefault("_host_inv", {})[name] = old
                    self._inv_cache[name] = {"for_idx": new_idx,
                                             "perm": perm}
                    _M_EMIT.inc(1, component="inverted", path="carried")
                    continue
                indptr, rows = old[0], old[1]
                if len(indptr) < n_t + 1:
                    indptr = np.concatenate([indptr, np.full(
                        n_t + 1 - len(indptr), indptr[-1], np.int64)])
            else:
                indptr, rows, perm = _patch_inverted_csr(
                    old[0], old[1], perm, changed, old_idx, new_idx,
                    n_t, i_p)
            w = new_llr.ravel()[perm].astype(np.float32, copy=False)
            model.__dict__.setdefault("_host_inv", {})[name] = \
                (indptr, rows, w)
            self._inv_cache[name] = {"for_idx": new_idx, "perm": perm}
            prov["inv"][name] = np.asarray(changed, np.int64)
            _M_EMIT.inc(1, component="inverted", path="patched")

    # -- checkpointing --------------------------------------------------------
    #
    # The numeric state serializes to one flat array dict (npz-able, no
    # pickle) + a small JSON meta; the accumulated EventBatch persists
    # separately through store.columnar.write_batch (which carries the
    # dictionaries and property columns).  Strings are NOT duplicated:
    # the user/item dictionaries reconstruct from the batch's dicts plus
    # the stored code maps.  ``state_fingerprint`` (crc32 over pairs +
    # marginals + code sets) makes bit-rot detectable: restore verifies
    # it and the caller restages on mismatch.

    def state_fingerprint(self) -> int:
        import zlib

        h = zlib.crc32(self.row_counts.tobytes())
        for name in self.event_names:
            st = self.types[name]
            h = zlib.crc32(np.ascontiguousarray(st.pairs).tobytes(), h)
            h = zlib.crc32(np.ascontiguousarray(st.col_counts).tobytes(), h)
            h = zlib.crc32(np.ascontiguousarray(st.codes).tobytes(), h)
        return int(h)

    def checkpoint_arrays(self) -> Tuple[Dict[str, np.ndarray], dict]:
        """(arrays, meta) capturing everything but the batch."""
        arrays: Dict[str, np.ndarray] = {
            "user_of_code": self.user_of_code,
            "row_counts": self.row_counts,
        }
        meta = {
            "version": 1,
            "impl": self.impl,
            "event_names": list(self.event_names),
            "n_users": len(self.user_dict),
            "props_ever": bool(self._props_ever),
            "generation": int(self.generation),
            "fingerprint": self.state_fingerprint(),
        }
        for k, name in enumerate(self.event_names):
            st = self.types[name]
            p = f"t{k}_"
            arrays[p + "codes"] = st.codes
            arrays[p + "local_of_target"] = st.local_of_target
            arrays[p + "pairs"] = st.pairs
            arrays[p + "col_counts"] = st.col_counts
            arrays[p + "raw_items"] = (
                np.concatenate(st.raw_items) if st.raw_items
                else np.zeros(0, np.int32))
            arrays[p + "raw_times"] = (
                np.concatenate(st.raw_times) if st.raw_times
                else np.zeros(0, np.float64))
            if st.idx is not None:
                arrays[p + "idx"] = st.idx
                arrays[p + "llr"] = st.llr
            if st.sc is not None:
                arrays[p + "cell_keys"] = st.sc.keys
                arrays[p + "cell_counts"] = st.sc.counts
            else:
                arrays[p + "dense_C"] = st.C
        return arrays, meta

    @classmethod
    def restore_checkpoint(cls, algo_params, ds_params, batch,
                           arrays, meta) -> "URFoldState":
        """Rebuild a fold state from ``checkpoint_arrays`` output + the
        persisted accumulated batch, verify the integrity fingerprint,
        and emit the model it describes.  Raises ValueError on ANY
        mismatch (version, config drift, corrupt arrays) — callers
        restage from the log."""
        if meta.get("version") != 1:
            raise ValueError(f"unknown checkpoint version {meta.get('version')}")
        state = cls(algo_params, ds_params)
        if list(meta.get("event_names") or []) != state.event_names:
            raise ValueError("checkpoint event_names do not match the "
                             "current engine params")
        state.batch = batch
        state.user_of_code = np.array(arrays["user_of_code"], np.int32)
        state.row_counts = np.array(arrays["row_counts"], np.int64)
        # the user dictionary reconstructs by inverting user_of_code
        # over the batch's entity dictionary (enrollment order is the
        # value order of the map)
        n_users = int(meta["n_users"])
        order = np.full(n_users, -1, np.int64)
        valid = np.flatnonzero(state.user_of_code >= 0)
        order[state.user_of_code[valid]] = valid
        if n_users and (order < 0).any():
            raise ValueError("checkpoint user map is not a bijection")
        state.user_dict = IdDict(
            [batch.entity_dict.str(int(c)) for c in order])
        state.impl = str(meta.get("impl") or "sparse")
        for k, name in enumerate(state.event_names):
            st = state.types[name]
            p = f"t{k}_"
            st.codes = np.array(arrays[p + "codes"], np.int64)
            st.item_dict = IdDict(
                [batch.target_dict.str(int(c)) for c in st.codes])
            st.local_of_target = np.array(arrays[p + "local_of_target"],
                                          np.int64)
            st.pairs = np.array(arrays[p + "pairs"], np.int64)
            st.col_counts = np.array(arrays[p + "col_counts"], np.int64)
            ri = np.array(arrays[p + "raw_items"], np.int32)
            rt = np.array(arrays[p + "raw_times"], np.float64)
            if len(ri) != len(rt):
                raise ValueError("checkpoint raw popularity arrays torn")
            st.raw_items = [ri] if len(ri) else []
            st.raw_times = [rt] if len(rt) else []
            if p + "idx" in arrays:
                st.idx = np.array(arrays[p + "idx"], np.int32)
                st.llr = np.array(arrays[p + "llr"], np.float32)
            if p + "cell_keys" in arrays:
                st.sc = _SparseCounts(np.array(arrays[p + "cell_keys"]),
                                      np.array(arrays[p + "cell_counts"]))
                st.C = None
            elif p + "dense_C" in arrays:
                st.C = np.array(arrays[p + "dense_C"], np.int32)
                st.sc = None
            else:
                raise ValueError(f"checkpoint carries no counts for {name}")
        if state.state_fingerprint() != int(meta["fingerprint"]):
            raise ValueError("checkpoint integrity fingerprint mismatch")
        if meta.get("props_ever"):
            state._props = {
                k2: dict(v) for k2, v in fold_properties(
                    batch, ds_params.item_entity_type).items()}
            state._props_ever = True
        state.generation = int(meta.get("generation", 0))
        if state._pop_incremental:
            # the running popularity counts are derived state — rebuild
            # from the restored raw lists so post-restore folds keep the
            # incremental path (counts-then-astype equals the full
            # recompute exactly)
            p_st2 = state.types[state.primary]
            items = (np.concatenate(p_st2.raw_items) if p_st2.raw_items
                     else np.zeros(0, np.int32))
            times = (np.concatenate(p_st2.raw_times) if p_st2.raw_times
                     else np.zeros(0, np.float64))
            state._pop = [
                np.bincount(items, minlength=max(p_st2.n_items, 1))
                .astype(np.int64),
                float(times.min()) if len(times) else np.inf,
                float(times.max()) if len(times) else -np.inf,
            ]
        state.model = None
        state.model = state._emit()
        return state
