"""Multi-node model-plane replication: stream delta arenas over TCP.

The plane's `gen-N.{arena,delta}` containers are already a
self-describing wire format — magic + JSON header + 64-aligned blobs —
and the keyframe chain is snapshot-plus-log replication by construction.
This module adds the missing network leg:

- :class:`PlaneReplicator` (publisher side) watches the local plane dir
  (same inotify/stat-poll machinery as :class:`~.plane.PlaneWatcher`)
  and streams every new generation file to K connected subscribers over
  a length-prefixed channel, then a ``flip`` frame carrying the
  manifest.
- :class:`PlaneSubscriber` (subscriber side) lands each container
  two-phase (tmp + hash-verify + fsync + rename) into its own
  node-LOCAL plane dir and flips ``CURRENT.json`` under the plane's
  flock'd publish lock — from there the existing
  ``PlaneWatcher``/compose/install path takes over unchanged, so the
  serving hot path never learns replication exists.

Failure modes reuse what the plane already proves locally:

- a cold or lagging subscriber asks for generation ``have``; when the
  publisher's GC has moved past it, the publisher re-plans from the
  nearest keyframe and replays the ``prevFile`` chain forward;
- a torn transfer (hash mismatch) is quarantined on the subscriber
  (``<file>.quarantine``, never flipped, never served) and the batch is
  re-requested;
- a SIGKILLed subscriber resumes from its last flipped manifest — the
  ``have`` in its first sync frame IS the last-acked generation;
- a dead/stuck subscriber costs the publisher one blocked ``send`` (the
  per-subscriber queue is the socket buffer plus one chunk — bounded
  memory by construction); the send timeout drops the session and the
  lag gauge's series with it.

Wire protocol (version ``PRP1``): every frame is
``b"PRP1" + u32 header_len + u64 payload_len + header_json + payload``.
Frame types: ``sync`` (subscriber → publisher: ``have`` generation +
``reason``; doubles as the per-flip ack), ``file`` (one container +
sha256), ``flip`` (the manifest), ``ping`` (keepalive carrying the
publisher's current generation, so an idle subscriber still reports
lag).

Split-brain guards: every manifest a subscriber lands carries
``replicatedFrom`` (:data:`~.plane.REPLICA_KEY`); the subscriber
refuses a plane dir whose manifest lacks it (a LOCAL publisher owns
that dir), and a local publisher that finds it degrades to
keyframe-only publishes (see ``ModelPlane.publish``).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from predictionio_tpu.obs import lineage as _obs_lineage
from predictionio_tpu.obs import metrics as _obs_metrics
from predictionio_tpu.streaming.plane import (
    REPLICA_KEY,
    ModelPlane,
    _DirNotify,
    _gen_of,
    _PlaneCorrupt,
    plane_notify_enabled,
    plane_poll_s,
)

log = logging.getLogger("pio.planerepl")

_REG = _obs_metrics.get_registry()
_M_RBYTES = _REG.counter(
    "pio_plane_repl_bytes_total",
    "Replicated plane bytes by direction (out=published to subscribers, "
    "in=landed from a publisher) and container kind (full|delta) — the "
    "per-hop sizing signal: steady state should be delta-dominated")
_M_RLAG = _REG.gauge(
    "pio_plane_repl_lag_generations",
    "Generations the named peer is behind the publisher's current one "
    "(publisher: one series per subscriber node = the slowest-subscriber "
    "view; subscriber: its own lag vs the source). Series are removed "
    "when the peer disconnects")
_M_RSUBS = _REG.gauge(
    "pio_plane_repl_subscribers",
    "Connected replication subscriber sessions on this publisher")
_M_RESYNC = _REG.counter(
    "pio_plane_repl_resyncs_total",
    "Keyframe-chain re-syncs by reason: cold (fresh subscriber), lag "
    "(subscriber fell behind the publisher's GC window), torn (hash "
    "mismatch on a transferred container)")

_MAGIC = b"PRP1"
_HDR = struct.Struct("<4sIQ")      # magic, header_len, payload_len
_MAX_HEADER = 16 << 20


def repl_ping_s() -> float:
    """PIO_PLANE_REPL_PING_S: publisher keepalive period while idle
    (default 5 s).  Also how often an idle subscriber's lag view
    refreshes."""
    try:
        return max(float(os.environ.get("PIO_PLANE_REPL_PING_S", "5")), 0.2)
    except ValueError:
        return 5.0


def repl_timeout_s() -> float:
    """PIO_PLANE_REPL_TIMEOUT_S: socket send/ack timeout (default 30 s).
    A subscriber that stops reading for this long is dropped — this is
    the publisher's memory bound: one in-flight chunk per subscriber,
    never an unbounded queue."""
    try:
        return max(float(os.environ.get("PIO_PLANE_REPL_TIMEOUT_S", "30")),
                   1.0)
    except ValueError:
        return 30.0


def repl_backoff_s() -> float:
    """PIO_PLANE_REPL_BACKOFF_S: subscriber's initial reconnect backoff
    (default 1 s, doubling to 30 s)."""
    try:
        return max(float(os.environ.get("PIO_PLANE_REPL_BACKOFF_S", "1")),
                   0.05)
    except ValueError:
        return 1.0


def repl_chunk_bytes() -> int:
    """PIO_PLANE_REPL_CHUNK: transfer chunk size (default 1 MiB) — also
    the per-subscriber publisher-side memory high-water mark."""
    try:
        return max(int(os.environ.get("PIO_PLANE_REPL_CHUNK",
                                      str(1 << 20))), 4096)
    except ValueError:
        return 1 << 20


def parse_endpoint(spec: str, default_host: str = "0.0.0.0",
                   ) -> Tuple[str, int]:
    """``HOST:PORT`` | ``:PORT`` | ``PORT`` → (host, port)."""
    s = str(spec).strip()
    if ":" in s:
        host, _, port = s.rpartition(":")
        host = host or default_host
    else:
        host, port = default_host, s
    try:
        return host, int(port)
    except ValueError:
        raise ValueError(f"bad replication endpoint {spec!r} "
                         "(want HOST:PORT or PORT)")


def _send_frame(sock: socket.socket, header: Dict[str, Any],
                payload_len: int = 0) -> None:
    hj = json.dumps(header, separators=(",", ":")).encode()
    sock.sendall(_HDR.pack(_MAGIC, len(hj), payload_len) + hj)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    parts = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise ConnectionError("peer closed mid-frame")
        parts.append(b)
        n -= len(b)
    return b"".join(parts)


def _recv_frame(sock: socket.socket) -> Tuple[Dict[str, Any], int]:
    """(header, payload_len); the caller drains the payload itself (a
    file body streams straight to disk, never through one big bytes)."""
    magic, hlen, plen = _HDR.unpack(_recv_exact(sock, _HDR.size))
    if magic != _MAGIC:
        raise ConnectionError(f"bad frame magic {magic!r}")
    if hlen > _MAX_HEADER:
        raise ConnectionError(f"oversized frame header ({hlen} bytes)")
    header = json.loads(_recv_exact(sock, hlen))
    if not isinstance(header, dict) or "type" not in header:
        raise ConnectionError("malformed frame header")
    return header, plen


def _safe_plane_name(name: str) -> str:
    """A generation file name as received from the wire, validated — the
    subscriber only ever writes ``gen-N.arena|.delta`` basenames inside
    its own plane dir."""
    base = os.path.basename(str(name))
    if base != name or _gen_of(base) is None \
            or not (base.endswith(".arena") or base.endswith(".delta")):
        raise ConnectionError(f"refusing wire file name {name!r}")
    return base


def _manifest_lid(manifest: Optional[Dict[str, Any]]) -> Optional[str]:
    """The publisher's lineage id riding the manifest; None on
    pre-lineage manifests (stitching simply stays off for them)."""
    lid = (manifest or {}).get("lineageId")
    return str(lid) if lid else None


class _Session:
    """One publisher→subscriber connection, owned by its thread."""

    def __init__(self, sock: socket.socket, addr, node: str, have: int):
        self.sock = sock
        self.addr = addr
        self.node = node
        self.have = int(have)
        self.http_port = 0           # subscriber's /metrics endpoint
        self.sent_bytes = 0
        self.resyncs = 0
        self.connected_at = time.time()


class PlaneReplicator:
    """Publisher side: serve the local plane dir to K subscribers.

    Runs three kinds of daemon threads: an acceptor on ``bind``, a
    plane-dir watcher (inotify fast path, stat-poll fallback) that
    re-reads the manifest and wakes every session, and one session
    thread per connected subscriber.  Sessions are pull-paced: after
    each ``flip`` the publisher waits for the subscriber's next ``sync``
    (the ack) before streaming more — so a slow subscriber throttles
    only its own connection and costs one chunk of memory."""

    def __init__(self, plane: ModelPlane, bind: str = "0.0.0.0:0"):
        self.plane = plane
        self.host, self.port = parse_endpoint(bind)
        self._sessions: Dict[int, _Session] = {}
        # every subscriber node EVER seen this process lifetime — the
        # cluster's "expected" set for lineage stitching and the
        # federation scrape list; disconnect marks, never removes
        self._peers: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._cur_gen = 0
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._notify: Optional[_DirNotify] = None
        self._listener: Optional[socket.socket] = None
        self._session_seq = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._listener is not None:
            return
        os.makedirs(self.plane.dir, exist_ok=True)
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self.host, self.port))
        srv.listen(64)
        self.port = srv.getsockname()[1]
        self._listener = srv
        cur = self.plane.current()
        self._cur_gen = int(cur["generation"]) if cur else 0
        for target, name in ((self._accept_loop, "pio-plane-repl-accept"),
                             (self._watch_loop, "pio-plane-repl-watch")):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        log.info("plane replication: publishing %s on %s:%d",
                 self.plane.dir, self.host, self.port)

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._notify is not None:
            self._notify.poke()
        with self._cond:
            self._cond.notify_all()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._lock:
            sessions = list(self._sessions.values())
        for s in sessions:
            try:
                s.sock.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=timeout)
        self._threads = []
        if self._notify is not None:
            self._notify.close()
            self._notify = None

    def poke(self) -> None:
        """Manifest may have flipped (the in-process follower's publish
        listener calls this — sub-poll-latency propagation even where
        inotify is unavailable)."""
        self._refresh_gen()

    def status(self) -> Dict[str, Any]:
        with self._lock:
            subs = [{
                "node": s.node, "ackedGeneration": s.have,
                "lagGenerations": max(self._cur_gen - s.have, 0),
                "sentBytes": s.sent_bytes, "resyncs": s.resyncs,
            } for s in self._sessions.values()]
        return {"role": "publisher",
                "bind": f"{self.host}:{self.port}",
                "generation": self._cur_gen,
                "subscribers": sorted(subs, key=lambda d: d["node"])}

    # -- cluster membership ----------------------------------------------------

    def peers(self) -> Dict[str, Dict[str, Any]]:
        """Every subscriber node this publisher has ever seen: node →
        {addr, httpPort, connected, lastSeen}.  The federation layer
        scrapes this list; lineage stitching uses it as the expected
        set."""
        with self._lock:
            return {n: dict(p) for n, p in self._peers.items()}

    def cluster_view(self) -> Dict[str, Any]:
        """{"expected", "live"} node-name sets for
        :func:`~predictionio_tpu.obs.lineage.set_cluster_provider`."""
        with self._lock:
            return {"expected": sorted(self._peers),
                    "live": sorted(n for n, p in self._peers.items()
                                   if p.get("connected"))}

    def _note_peer(self, sess: _Session, connected: bool = True) -> None:
        with self._lock:
            p = self._peers.setdefault(sess.node, {"httpPort": 0})
            p["addr"] = sess.addr[0]
            p["lastSeen"] = time.time()
            p["connected"] = connected
            if sess.http_port:
                p["httpPort"] = sess.http_port

    def _ingest_sync(self, sess: _Session, raw: bytes) -> None:
        """The push half of lineage stitching: a subscriber's sync
        frames (initial and per-flip ack) carry its recent lineage
        fragments + HTTP endpoint as the payload.  Old subscribers send
        an empty payload; a malformed one never kills the session."""
        if not raw:
            return
        try:
            doc = json.loads(raw)
            if not isinstance(doc, dict):
                return
            port = int(doc.get("httpPort") or 0)
            if port:
                sess.http_port = port
            recs = doc.get("records")
            if recs:
                rec = _obs_lineage.get_lineage()
                if rec.enabled:
                    rec.ingest(recs, node=sess.node)
        except Exception:
            log.debug("plane replication: bad sync payload from %s",
                      sess.node, exc_info=True)

    # -- watch ---------------------------------------------------------------

    def _refresh_gen(self) -> None:
        cur = self.plane.current()
        gen = int(cur["generation"]) if cur else 0
        with self._cond:
            if gen != self._cur_gen:
                self._cur_gen = gen
                self._cond.notify_all()

    def _watch_loop(self) -> None:
        if plane_notify_enabled():
            try:
                self._notify = _DirNotify(self.plane.dir)
            except OSError:
                self._notify = None
        poll = plane_poll_s()
        while not self._stop.is_set():
            if self._notify is not None:
                self._notify.wait(poll)
            else:
                self._stop.wait(poll)
            if self._stop.is_set():
                return
            try:
                self._refresh_gen()
            except Exception:
                log.exception("plane replication: watch failed")

    # -- sessions ------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return          # stop() closed the listener
            t = threading.Thread(target=self._serve, args=(sock, addr),
                                 daemon=True, name="pio-plane-repl-session")
            t.start()

    def _serve(self, sock: socket.socket, addr) -> None:
        sid = None
        node = f"{addr[0]}:{addr[1]}"
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(repl_timeout_s())
            header, plen = _recv_frame(sock)
            if header.get("type") != "sync":
                raise ConnectionError(
                    f"expected sync, got {header.get('type')!r}")
            raw = _recv_exact(sock, plen) if plen else b""
            node = str(header.get("node") or node)
            sess = _Session(sock, addr, node, int(header.get("have") or 0))
            self._ingest_sync(sess, raw)
            self._note_peer(sess)
            with self._lock:
                self._session_seq += 1
                sid = self._session_seq
                self._sessions[sid] = sess
                _M_RSUBS.set(len(self._sessions))
            log.info("plane replication: subscriber %s connected "
                     "(have=%d, reason=%s)", node, sess.have,
                     header.get("reason"))
            self._session_loop(sess, str(header.get("reason") or "cold"))
        except (ConnectionError, socket.timeout, OSError) as e:
            if not self._stop.is_set():
                log.info("plane replication: subscriber %s dropped (%s)",
                         node, e)
        except Exception:
            log.exception("plane replication: session %s failed", node)
        finally:
            try:
                sock.close()
            except OSError:
                pass
            if sid is not None:
                with self._lock:
                    self._sessions.pop(sid, None)
                    _M_RSUBS.set(len(self._sessions))
                    if node in self._peers:
                        self._peers[node]["connected"] = False
                        self._peers[node]["lastSeen"] = time.time()
                # a dead subscriber's lag series must not linger at its
                # last value and page someone forever
                _M_RLAG.remove(node=node)

    def _session_loop(self, sess: _Session, reason: str) -> None:
        ping_s = repl_ping_s()
        while not self._stop.is_set():
            with self._cond:
                deadline = time.time() + ping_s
                while (self._cur_gen <= sess.have
                       and not self._stop.is_set()):
                    left = deadline - time.time()
                    if left <= 0:
                        break
                    self._cond.wait(left)
                gen = self._cur_gen
            if self._stop.is_set():
                return
            _M_RLAG.set(max(gen - sess.have, 0), node=sess.node)
            if gen <= sess.have:
                _send_frame(sess.sock, {"type": "ping", "gen": gen})
                continue
            cur = self.plane.current()
            if cur is None or int(cur["generation"]) <= sess.have:
                continue
            reason = self._ship(sess, cur, reason)

    def _plan(self, have: int, cur: Dict[str, Any],
              reason: str) -> Tuple[List[str], Optional[str]]:
        """(ordered files to ship, resync reason or None for an
        incremental catch-up)."""
        gen = int(cur["generation"])
        resync = None
        if reason == "torn":
            resync = "torn"
        elif have <= 0:
            resync = "cold"
        files: List[str] = []
        if resync is None:
            for g in range(have + 1, gen + 1):
                for nm in (f"gen-{g:010d}.delta", f"gen-{g:010d}.arena"):
                    if os.path.exists(os.path.join(self.plane.dir, nm)):
                        files.append(nm)
                        break
                else:
                    resync = "lag"   # GC moved past the subscriber
                    break
        if resync is not None:
            files = self.plane.chain_files(str(cur["file"]))
        return files, resync

    def _ship(self, sess: _Session, cur: Dict[str, Any],
              reason: str) -> str:
        """Stream one catch-up batch (files + flip), then block on the
        subscriber's ack-sync.  Returns the next batch's request reason
        (from that sync)."""
        gen = int(cur["generation"])
        lid = _manifest_lid(cur)
        t_plan = time.time()
        p0 = time.perf_counter()
        try:
            files, resync = self._plan(sess.have, cur, reason)
        except _PlaneCorrupt as e:
            # the local chain itself is broken (quarantined file): the
            # next keyframe publish heals it; keep the session alive
            log.warning("plane replication: cannot plan catch-up for %s "
                        "(%s) — waiting for a healing keyframe",
                        sess.node, e)
            _send_frame(sess.sock, {"type": "ping", "gen": gen})
            time.sleep(min(repl_ping_s(), 1.0))
            return "lag"
        if resync is not None:
            sess.resyncs += 1
            _M_RESYNC.inc(reason=resync)
            log.info("plane replication: re-syncing %s from keyframe "
                     "(%s, %d files)", sess.node, resync, len(files))
        if lid:
            lin = _obs_lineage.get_lineage()
            if lin.enabled:
                lin.stage(lid, "repl.plan", start=t_plan,
                          duration_s=time.perf_counter() - p0,
                          generation=gen, peer=sess.node,
                          files=len(files),
                          resync=resync or "incremental")
        for nm in files:
            if not self._send_file(sess, nm, lid):
                # vanished mid-plan (GC race): re-plan from the live
                # manifest on the next loop turn
                return "lag"
        _send_frame(sess.sock, {"type": "flip", "manifest": cur,
                                "resync": resync})
        header, plen = _recv_frame(sess.sock)   # the ack
        if header.get("type") != "sync":
            raise ConnectionError(
                f"expected ack sync, got {header.get('type')!r}")
        raw = _recv_exact(sess.sock, plen) if plen else b""
        self._ingest_sync(sess, raw)
        self._note_peer(sess)
        sess.have = int(header.get("have") or 0)
        _M_RLAG.set(max(self._cur_gen - sess.have, 0), node=sess.node)
        return str(header.get("reason") or "ack")

    def _send_file(self, sess: _Session, name: str,
                   lid: Optional[str] = None) -> bool:
        """Hash-then-stream one container from a single open fd (GC may
        unlink the path mid-send; the fd keeps the bytes).  False when
        the file is already gone.  ``lid`` rides the frame header so the
        subscriber can open its ``repl.recv`` stage under the
        publisher's lineage id before the flip arrives."""
        chunk = repl_chunk_bytes()
        try:
            f = open(os.path.join(self.plane.dir, name), "rb")
        except FileNotFoundError:
            return False
        with f:
            h = hashlib.sha256()
            size = 0
            while True:
                b = f.read(chunk)
                if not b:
                    break
                h.update(b)
                size += len(b)
            kind = "delta" if name.endswith(".delta") else "full"
            hdr = {
                "type": "file", "name": name, "gen": _gen_of(name),
                "bytes": size, "sha256": h.hexdigest(), "kind": kind,
            }
            if lid:
                hdr["lid"] = lid
            _send_frame(sess.sock, hdr, payload_len=size)
            f.seek(0)
            left = size
            while left:
                b = f.read(min(chunk, left))
                if not b:
                    raise ConnectionError(
                        f"{name}: shrank mid-send ({left} bytes short)")
                sess.sock.sendall(b)
                left -= len(b)
        sess.sent_bytes += size
        _M_RBYTES.inc(size, dir="out", kind=kind)
        return True


class PlaneSubscriber:
    """Subscriber side: mirror a remote publisher's plane into a local
    plane dir.  Connects with exponential backoff, announces its last
    flipped generation (crash-resumable: that state IS the local
    manifest), lands containers two-phase and flips the manifest under
    the plane's flock'd publish lock with :data:`REPLICA_KEY` stamped —
    the local ``PlaneWatcher``/compose/install path (and GC) then work
    unchanged."""

    def __init__(self, plane_dir: str, source: str,
                 node: Optional[str] = None):
        self.plane = ModelPlane(plane_dir)
        self.source = source
        self.host, self.port = parse_endpoint(source,
                                              default_host="127.0.0.1")
        self.node = (node or _obs_lineage.cluster_node()
                     or f"{socket.gethostname()}-{os.getpid()}")
        # this node's serving HTTP port, announced in every sync frame
        # so the publisher's federation layer can scrape /metrics and
        # /lineage here; 0 = not serving (bare subscriber in tests)
        self.http_port = 0
        self.generation = 0          # last flipped locally
        self.source_generation = 0   # publisher's, from pings/flips
        self.resyncs = 0
        self.connected = False
        self.last_flip_at = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._sock: Optional[socket.socket] = None
        self._flip_cond = threading.Condition()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self.generation = self._initial_have()   # raises on foreign dir
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="pio-plane-subscribe")
        self._thread.start()

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        sock = self._sock
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def status(self) -> Dict[str, Any]:
        src_gen = max(self.source_generation, self.generation)
        return {"role": "subscriber", "source": self.source,
                "node": self.node, "connected": self.connected,
                "generation": self.generation,
                "sourceGeneration": src_gen,
                "lagGenerations": max(src_gen - self.generation, 0),
                "resyncs": self.resyncs, "lastFlipAt": self.last_flip_at}

    def wait_generation(self, gen: int, timeout: float) -> bool:
        """Block until generation ``gen`` has flipped locally (tests and
        the check scripts use this)."""
        deadline = time.time() + timeout
        with self._flip_cond:
            while self.generation < gen:
                left = deadline - time.time()
                if left <= 0:
                    return False
                self._flip_cond.wait(left)
        return True

    # -- resume / split-brain ------------------------------------------------

    def _initial_have(self) -> int:
        """Resume point: the local manifest's generation when it was
        landed by replication AND its chain files survive; 0 (full
        re-sync) otherwise.  A manifest WITHOUT the replication marker
        means a local publisher owns this dir — refuse loudly rather
        than fight it for the flock."""
        cur = self.plane.current()
        if cur is None:
            return 0
        if REPLICA_KEY not in cur:
            raise RuntimeError(
                f"plane dir {self.plane.dir} has a locally-published "
                "manifest (no replication marker) — subscribing to it "
                "would split-brain with the local publisher. Point "
                "--plane-dir/PIO_MODEL_PLANE_DIR at a directory this "
                "subscriber owns.")
        try:
            self.plane.chain_files(str(cur["file"]))
        except _PlaneCorrupt:
            return 0
        return int(cur["generation"])

    # -- receive loop --------------------------------------------------------

    def _loop(self) -> None:
        backoff = repl_backoff_s()
        reason = "cold" if self.generation == 0 else "resume"
        while not self._stop.is_set():
            try:
                reason = self._run_once(reason)
                backoff = repl_backoff_s()   # a clean pass resets it
            except (ConnectionError, socket.timeout, OSError) as e:
                if self._stop.is_set():
                    return
                log.warning("plane replication: subscriber link to %s "
                            "lost (%s) — reconnecting in %.1fs",
                            self.source, e, backoff)
                self._stop.wait(backoff)
                backoff = min(backoff * 2, 30.0)
            except Exception:
                if self._stop.is_set():
                    return
                log.exception("plane replication: subscriber failed — "
                              "reconnecting in %.1fs", backoff)
                self._stop.wait(backoff)
                backoff = min(backoff * 2, 30.0)
            finally:
                self.connected = False
                _M_RLAG.remove(node=self.node)

    def _run_once(self, reason: str) -> str:
        ping_s = repl_ping_s()
        sock = socket.create_connection((self.host, self.port),
                                        timeout=repl_timeout_s())
        self._sock = sock
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # recv must outlive the publisher's ping cadence comfortably
            sock.settimeout(max(repl_timeout_s(), ping_s * 3))
            self._send_sync(sock, reason)
            self.connected = True
            log.info("plane replication: subscribed to %s (have=%d, %s)",
                     self.source, self.generation, reason)
            torn: Optional[str] = None
            while not self._stop.is_set():
                header, plen = _recv_frame(sock)
                typ = header.get("type")
                if typ == "ping":
                    self.source_generation = int(header.get("gen") or 0)
                    self._note_lag()
                elif typ == "file":
                    name, ok = self._land_file(sock, header, plen)
                    if not ok and torn is None:
                        torn = name
                elif typ == "flip":
                    manifest = header.get("manifest") or {}
                    self.source_generation = int(
                        manifest.get("generation") or 0)
                    if torn is None and self._flip(manifest):
                        reason = "ack"
                    else:
                        # quarantined (or chain-incomplete) batch: never
                        # flip over it — re-request the whole chain
                        self.resyncs += 1
                        _M_RESYNC.inc(reason="torn")
                        reason = "torn"
                    torn = None
                    self._note_lag()
                    self._send_sync(sock, reason)
                elif typ == "error":
                    raise ConnectionError(
                        f"publisher error: {header.get('msg')}")
                else:
                    raise ConnectionError(f"unexpected frame {typ!r}")
            return reason
        finally:
            self._sock = None
            try:
                sock.close()
            except OSError:
                pass

    def _note_lag(self) -> None:
        _M_RLAG.set(max(self.source_generation - self.generation, 0),
                    node=self.node)

    def _send_sync(self, sock: socket.socket, reason: str) -> None:
        """Sync frame (initial and per-flip ack) with the stitching
        push-payload: this node's recent lineage fragments + HTTP
        endpoint.  Publishers predating stitching drain and discard the
        payload — the wire format always carried a payload length — so
        this is backward compatible in both directions."""
        payload = b""
        try:
            doc: Dict[str, Any] = {"node": self.node,
                                   "httpPort": int(self.http_port)}
            rec = _obs_lineage.get_lineage()
            if rec.enabled:
                doc["records"] = rec.export()
            payload = json.dumps(doc, separators=(",", ":")).encode()
        except Exception:
            payload = b""
        _send_frame(sock, {"type": "sync", "have": self.generation,
                           "node": self.node, "reason": reason},
                    payload_len=len(payload))
        if payload:
            sock.sendall(payload)

    def _repl_stage(self, lid: Any, name: str, **kw: Any) -> None:
        """One replication stage under the publisher's lineage id.
        ``node=`` is passed explicitly (not left to env stamping):
        in-process tests run several subscribers in one process."""
        if not lid:
            return
        try:
            rec = _obs_lineage.get_lineage()
            if rec.enabled:
                rec.stage(str(lid), name, node=self.node, **kw)
        except Exception:
            log.debug("plane replication: lineage stage %s failed",
                      name, exc_info=True)

    def _land_file(self, sock: socket.socket, header: Dict[str, Any],
                   plen: int) -> Tuple[str, bool]:
        """Stream one container to ``.<name>.tmp-<pid>`` while hashing;
        rename into place only when the hash matches, else quarantine
        the evidence and report the tear.  (name, landed_ok)."""
        name = _safe_plane_name(header.get("name"))
        want_sha = str(header.get("sha256") or "")
        kind = "delta" if name.endswith(".delta") else "full"
        os.makedirs(self.plane.dir, exist_ok=True)
        tmp = os.path.join(self.plane.dir, f".{name}.tmp-{os.getpid()}")
        t_recv = time.time()
        r0 = time.perf_counter()
        h = hashlib.sha256()
        left = plen
        chunk = repl_chunk_bytes()
        with open(tmp, "wb") as f:
            while left:
                b = sock.recv(min(left, chunk))
                if not b:
                    raise ConnectionError(f"{name}: peer closed mid-blob")
                h.update(b)
                f.write(b)
                left -= len(b)
            f.flush()
            os.fsync(f.fileno())
        _M_RBYTES.inc(plen, dir="in", kind=kind)
        torn_flag = 0 if h.hexdigest() == want_sha else 1
        self._repl_stage(header.get("lid"), "repl.recv", start=t_recv,
                         duration_s=time.perf_counter() - r0,
                         generation=_gen_of(name), kind=kind,
                         bytes=plen, torn=torn_flag)
        if h.hexdigest() != want_sha:
            # torn transfer: keep the evidence out-of-band, never flip it
            qpath = os.path.join(self.plane.dir, name + ".quarantine")
            try:
                os.replace(tmp, qpath)
            except OSError:
                pass
            log.warning("plane replication: %s torn in transit "
                        "(sha256 %s != %s) — quarantined, will "
                        "re-request", name, h.hexdigest()[:12],
                        want_sha[:12])
            return name, False
        os.replace(tmp, os.path.join(self.plane.dir, name))
        return name, True

    def _flip(self, manifest: Dict[str, Any]) -> bool:
        """Flip the local manifest to the replicated generation under
        the plane's publish lock (the marker keeps local publishers and
        other subscribers honest), then GC exactly like a publisher.
        False when the chain is incomplete locally (caller re-syncs)."""
        if not isinstance(manifest, dict) or "generation" not in manifest \
                or "file" not in manifest:
            raise ConnectionError("flip without a usable manifest")
        gen = int(manifest["generation"])
        lid = _manifest_lid(manifest)
        t_ver = time.time()
        v0 = time.perf_counter()
        try:
            self.plane.chain_files(str(manifest["file"]))
        except _PlaneCorrupt as e:
            log.warning("plane replication: not flipping to generation "
                        "%d — chain incomplete locally (%s)", gen, e)
            return False
        self._repl_stage(lid, "repl.verify", start=t_ver,
                         duration_s=time.perf_counter() - v0,
                         generation=gen)
        doc = dict(manifest)
        doc[REPLICA_KEY] = self.source
        doc["publisherPid"] = os.getpid()
        doc["replicatedAt"] = time.time()
        t_land = time.time()
        l0 = time.perf_counter()
        with self.plane._publish_lock():
            local = self.plane.current()
            if local is not None and REPLICA_KEY not in local \
                    and int(local.get("generation") or 0) >= gen:
                raise RuntimeError(
                    f"plane dir {self.plane.dir} was taken over by a "
                    "local publisher mid-stream — refusing to fight it")
            self.plane._write_manifest(doc)
            kf = doc.get("keyframeGeneration")
            self.plane._gc_keyframes[gen] = int(kf) if kf else gen
            self.plane._gc(gen)
        self.generation = gen
        self.last_flip_at = time.time()
        # repl.land is the publish-equivalent marker on a subscriber:
        # lineage supersession closes pre-resync records against it
        self._repl_stage(lid, "repl.land", start=t_land,
                         duration_s=time.perf_counter() - l0,
                         generation=gen, flush=True)
        if lid:
            try:
                rec = _obs_lineage.get_lineage()
                if rec.enabled:
                    rec.note_generation(str(lid), gen)
            except Exception:
                pass
        with self._flip_cond:
            self._flip_cond.notify_all()
        log.info("plane replication: generation %d live locally (%s)",
                 gen, manifest.get("file"))
        return True
